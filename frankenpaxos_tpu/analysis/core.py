"""paxlint core: project model, findings, pragmas, and the rule driver.

A *rule* is a function ``rule(project) -> Iterable[Finding]`` registered
with :func:`register_rule`. The driver parses every file once into a
:class:`Project`, runs each rule family, then filters findings through
per-line / per-scope ``# paxlint: disable=<rule>`` pragmas. Baseline
handling (grandfathered findings) lives in ``baseline.py``.

Findings carry a *stable key* -- (rule, file, scope qualname, detail) --
rather than a line number, so a baseline survives unrelated edits to the
same file.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Iterable, Iterator


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str          # e.g. "TPU201"
    file: str          # repo-relative posix path
    line: int          # 1-based, for display only
    scope: str         # enclosing qualname ("Class.method" / "<module>")
    detail: str        # stable short detail (call name, class name, ...)
    message: str       # human explanation

    @property
    def key(self) -> tuple:
        """Line-independent identity used by pragmas and the baseline."""
        return (self.rule, self.file, self.scope, self.detail)

    def render(self) -> str:
        return (f"{self.file}:{self.line}: {self.rule} [{self.scope}] "
                f"{self.message}")


@dataclasses.dataclass
class Module:
    """One parsed source file."""

    path: str                  # repo-relative posix path
    tree: ast.Module
    lines: list                # source lines, 0-indexed
    # module dotted name, e.g. "frankenpaxos_tpu.ops.quorum"
    name: str

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


class Project:
    """All parsed modules under a root directory (one package)."""

    def __init__(self, root: str, package: str = "frankenpaxos_tpu",
                 exclude: tuple = ("analysis",)):
        self.root = os.path.abspath(root)
        self.package = package
        self.modules: dict[str, Module] = {}  # path -> Module
        self.by_name: dict[str, Module] = {}  # dotted name -> Module
        #: Diff-aware mode (``--changed-since``): when not None, only
        #: findings in these repo-relative paths are reported, and rule
        #: families may skip per-module work outside the set (the
        #: project itself still parses EVERY module, so cross-module
        #: caches -- callgraph, class index -- stay warm and correct).
        self.focus: set | None = None
        pkg_dir = os.path.join(self.root, package)
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = sorted(
                d for d in dirnames
                if d != "__pycache__"
                and os.path.relpath(os.path.join(dirpath, d), pkg_dir)
                .replace(os.sep, "/") not in exclude)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    self._load(os.path.join(dirpath, fn))

    def _load(self, abspath: str) -> None:
        rel = os.path.relpath(abspath, self.root).replace(os.sep, "/")
        with open(abspath, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError as e:
            raise SystemExit(f"paxlint: cannot parse {rel}: {e}")
        name = rel[:-len(".py")].replace("/", ".")
        if name.endswith(".__init__"):
            name = name[:-len(".__init__")]
        mod = Module(path=rel, tree=tree, lines=source.splitlines(),
                     name=name)
        self.modules[rel] = mod
        self.by_name[name] = mod

    def __iter__(self) -> Iterator[Module]:
        return iter(self.modules.values())


# --- rule registry ----------------------------------------------------------

RULES: dict[str, str] = {}  # rule id -> one-line description
_RULE_FUNCS: list = []


def register_rules(ids: dict, func: Callable[[Project], Iterable[Finding]],
                   ) -> None:
    """Register a rule family: a checker function plus the IDs it can
    emit (IDs feed ``--list-rules`` and pragma validation)."""
    RULES.update(ids)
    _RULE_FUNCS.append(func)


def run_rules(project: Project) -> list:
    """All findings from all registered rule families, pragma-filtered,
    sorted by (file, line)."""
    _ensure_loaded()
    findings: list = []
    seen: set = set()
    for func in _RULE_FUNCS:
        for f in func(project):
            # One finding per stable key: a nested AST walk (or two
            # rule paths) may flag the same construct twice.
            if f.key not in seen:
                seen.add(f.key)
                findings.append(f)
    findings = [f for f in findings if not _suppressed(project, f)]
    if project.focus is not None:
        # Diff-aware mode: the per-family focus skips are a speedup;
        # THIS filter is the semantics (cheap project-global families
        # run in full and are trimmed here, so a focused run equals
        # the full run restricted to the focus set).
        findings = [f for f in findings if f.file in project.focus]
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


def focused(project: Project, path: str) -> bool:
    """Should a rule family spend per-module work on ``path``? True
    always in a full run; in ``--changed-since`` mode only for files
    in the transitively-affected closure."""
    return project.focus is None or path in project.focus


def focus_touches(project: Project, surface) -> bool:
    """May any focused module ANCHOR one of this family's findings?
    ``surface`` is the family's declared finding surface: path
    substrings (directories, specific files) its findings' ``file``
    fields always fall under. Cross-module families (paxflow, codec
    exhaustiveness) pay expensive project-wide passes even in
    diff-aware mode -- but when the focus closure cannot hold any of
    their findings, the whole family is droppable: a send or handler
    change in an out-of-surface module only affects findings anchored
    ELSEWHERE, which run_rules' focus filter discards anyway."""
    if project.focus is None:
        return True
    return any(any(seg in path for seg in surface)
               for path in project.focus)


def _ensure_loaded() -> None:
    """Import the rule-family modules (each registers itself)."""
    from frankenpaxos_tpu.analysis import (  # noqa: F401
        actor_rules,
        alias_rules,
        codec_rules,
        device_rules,
        durability_rules,
        epoch_rules,
        flow_rules,
        geo_rules,
        hotpath_rules,
        net_rules,
        obs_rules,
        overload_rules,
        ownership_rules,
        safety_rules,
        shape_rules,
    )


# --- pragmas ----------------------------------------------------------------

_PRAGMA = re.compile(r"#\s*paxlint:\s*disable=([A-Za-z0-9_,\s]+)")


def pragma_rules(line: str) -> set:
    """Rule IDs disabled by a ``# paxlint: disable=A,B`` comment on
    ``line`` (empty set if none)."""
    m = _PRAGMA.search(line)
    if not m:
        return set()
    return {r.strip() for r in m.group(1).split(",") if r.strip()}


def _suppressed(project: Project, finding: Finding) -> bool:
    """A finding is suppressed by a pragma on its own line, on the
    immediately preceding (comment) line, or on the ``def``/``class``
    line of any enclosing scope."""
    mod = project.modules.get(finding.file)
    if mod is None:
        return False
    if finding.rule in pragma_rules(mod.line(finding.line)):
        return True
    line = finding.line - 1
    while line >= 1:
        prev = mod.line(line).strip()
        if not prev.startswith("#"):
            break
        if finding.rule in pragma_rules(prev):
            return True
        line -= 1
    for node in _enclosing_defs(mod.tree, finding.line):
        if finding.rule in pragma_rules(mod.line(node.lineno)):
            return True
    return False


def _enclosing_defs(tree: ast.Module, lineno: int) -> list:
    """Every def/class whose span contains ``lineno``."""
    out = []
    for node in cached_walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= lineno <= end:
                out.append(node)
    return out


# --- shared AST helpers (used by every rule family) -------------------------


#: Memo for :func:`qualname_index`, keyed by tree identity (the same
#: pinning contract and size bound as ``_ALIAS_CACHE``): several
#: families index the same module trees and the visit must not repeat.
_QUALNAME_CACHE: dict = {}


def qualname_index(tree: ast.Module) -> dict:
    """id(def-node) -> dotted qualname ("Class.method", "func.inner")."""
    hit = _QUALNAME_CACHE.get(id(tree))
    if hit is not None and hit[0] is tree:
        return hit[1]
    if len(_QUALNAME_CACHE) > 4096:
        _QUALNAME_CACHE.clear()
    out: dict = {}

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                out[id(child)] = q
                visit(child, q)
            else:
                visit(child, prefix)

    visit(tree, "")
    _QUALNAME_CACHE[id(tree)] = (tree, out)
    return out


#: Memo for :func:`cached_walk` (same identity check and size bound as
#: ``_ALIAS_CACHE``). Every rule family traverses the same module trees
#: and function bodies, several of them more than once per run; the
#: materialized walk order turns those repeat traversals into list
#: iteration, which is where most of the diff-aware <10s budget comes
#: from (docs/ANALYSIS.md).
_WALK_CACHE: dict = {}


def cached_walk(node: ast.AST) -> list:
    """``list(ast.walk(node))``, memoized on node identity."""
    hit = _WALK_CACHE.get(id(node))
    if hit is not None and hit[0] is node:
        return hit[1]
    if len(_WALK_CACHE) > 16384:
        # Bound the pinned-node set (throwaway Projects in long test
        # runs), same rationale as _ALIAS_CACHE.
        _WALK_CACHE.clear()
    nodes = list(ast.walk(node))
    _WALK_CACHE[id(node)] = (node, nodes)
    return nodes


def dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression ("jax.device_get",
    "self.tracker.drain", "np.asarray"); "" when unnameable."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Call):
        return dotted(node.func)
    return ""


def call_name(node: ast.Call) -> str:
    return dotted(node.func)


# --- buffer provenance (paxown: shared by ownership/device rules) -----------

#: Calls whose result is a VIEW over (or an index table into) a
#: caller-supplied buffer: mutating or compacting the backing buffer
#: invalidates the result. The paxown rules (OWN11xx) track locals
#: bound to these through aliases, helper params, and container
#: stores. Matched on the LAST dotted component, so both
#: ``native.scan_frames`` and a bare ``scan_frames`` import hit.
BUFFER_VIEW_CALLS = frozenset({
    "memoryview",
    "scan_frames", "fpx_scan_frames",
    "scan_batch", "fpx_scan_batch",
    "ingest_scan", "fpx_ingest_scan",
    "value_columns", "fpx_value_columns",
    "parse_client_batch", "parse_client_array", "parse_ack_batch",
    "value_view", "lazy_values", "frombuffer",
})

#: ctypes raw-pointer exports: a live export pins a bytearray against
#: resize (BufferError) and dangles if the buffer is reallocated.
#: ``from_buffer_copy`` is deliberately NOT here -- it is the
#: sanitizer.
BUFFER_EXPORT_CALLS = frozenset({"from_buffer", "cast"})

#: Calls that take ownership: the result is an independent copy, so
#: provenance (and every OWN11xx obligation) ends here.
BUFFER_SANITIZERS = frozenset({
    "bytes", "bytearray", "tobytes", "to_owned", "copy", "deepcopy",
    "tolist", "list", "tuple", "value_bytes", "from_buffer_copy",
})


def is_sanitizer_call(node: ast.AST) -> bool:
    """Is ``node`` a call that copies its buffer argument out
    (``bytes(x)``, ``x.tobytes()``, ``x.to_owned()``, ...)?"""
    return (isinstance(node, ast.Call)
            and call_name(node).split(".")[-1] in BUFFER_SANITIZERS)


def own_scope_walk(func: ast.AST) -> Iterator[ast.AST]:
    """Nodes of ``func`` excluding nested function/class bodies (each
    nested def is analyzed as its own scope)."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def buffer_locals(func: ast.AST, sources: frozenset = BUFFER_VIEW_CALLS,
                  ) -> dict:
    """Locals of ``func``'s own scope bound to a buffer-view source,
    directly or through plain-name aliases and tuple unpacking: name
    -> (source call name, line of the binding). A rebinding through a
    sanitizer (``x = bytes(x)``) removes the name again."""
    out: dict = {}
    for node in own_scope_walk(func):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target, value = node.targets[0], node.value
        names = []
        if isinstance(target, ast.Name):
            names = [target.id]
        elif isinstance(target, ast.Tuple) and all(
                isinstance(e, ast.Name) for e in target.elts):
            names = [e.id for e in target.elts]
        if not names:
            continue
        src = None
        if isinstance(value, ast.Call):
            last = call_name(value).split(".")[-1]
            if last in sources:
                # A call can be BOTH a sanitizer and a requested source
                # (``bytearray(x)`` copies x out, but IS the mutable
                # segment the OWN1102/OWN1103 source sets ask about):
                # the caller's source set wins over the sanitizer pop.
                src = last
            elif is_sanitizer_call(value):
                for n in names:
                    out.pop(n, None)
                continue
        elif isinstance(value, ast.Name) and value.id in out:
            src = out[value.id][0]
        elif isinstance(value, ast.Subscript) and \
                isinstance(value.value, ast.Name) and \
                value.value.id in out:
            # An element of a view table (a scan's offset tuple, a
            # parsed column) keeps the backing buffer's provenance.
            src = out[value.value.id][0]
        if src is not None:
            for n in names:
                out[n] = (src, node.lineno)
        else:
            for n in names:
                out.pop(n, None)  # rebound to something unrelated
    return out


#: Memo for :func:`import_aliases`, keyed by tree identity (trees are
#: held alive by their Project for the process lifetime; the cache
#: pins them, which is what makes id() a safe key). Rule families call
#: this per (module, class, function) -- the walk must not repeat.
_ALIAS_CACHE: dict = {}


def import_aliases(tree: ast.Module, package: str) -> dict:
    """local alias -> fully qualified module or symbol name, for both
    ``import x.y as z`` and ``from x import y [as z]``."""
    hit = _ALIAS_CACHE.get(id(tree))
    if hit is not None and hit[0] is tree:
        return hit[1]
    if len(_ALIAS_CACHE) > 4096:
        # Bound the pinned-tree set: long test runs construct many
        # throwaway Projects, and the id()-keyed entries would
        # otherwise hold every one of their ASTs forever.
        _ALIAS_CACHE.clear()
    out: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    _ALIAS_CACHE[id(tree)] = (tree, out)
    return out
