"""DUR5xx: durability dataflow -- the group-commit contract as a rule.

paxlog's safety argument (wal/role.py) is one ordering: records staged
during a drain are fsynced ONCE, and only then do the acks that depend
on them leave the actor. The WAL-wired roles uphold it by routing
every state-acknowledging reply through ``_wal_send`` (held in
``_wal_sends`` until ``_wal_drain``'s sync). These rules make the
ordering machine-checked for EVERY WAL-wired role, present and future:

  * DUR501 -- a handler (or drain) method that appends a WAL record
    AND releases a non-Nack reply via direct ``send``/``broadcast``:
    the ack can reach the wire before the fsync, so a crash loses
    acked state. (Nacks are exempt: a rejection acknowledges nothing.)
  * DUR502 -- a class that touches the WAL surface (``wal.append`` /
    ``_wal_send`` / ``_wal_drain``) without mixing in DurableRole: the
    group-commit machinery isn't wired, so deferred sends either crash
    or silently bypass the fsync.
  * DUR503 -- a DurableRole subclass whose ``on_drain`` never reaches
    ``_wal_drain``: staged records are never synced and held acks
    never released (the role deadlocks its own clients).

The rules are name-based like the rest of paxlint: DurableRole
membership walks the base-name chain project-wide, and the handler
closure reuses the flow graph's receive-flow scan.
"""

from __future__ import annotations

import ast

from frankenpaxos_tpu.analysis import flowgraph
from frankenpaxos_tpu.analysis.core import (
    cached_walk,
    dotted,
    Finding,
    focused,
    Project,
    qualname_index,
    register_rules,
)

RULES = {
    "DUR501": "direct send of a reply in a WAL-appending handler "
              "(ack may precede the group commit)",
    "DUR502": "WAL surface used without the DurableRole mixin",
    "DUR503": "DurableRole on_drain never reaches _wal_drain",
}

#: Direct-send entry points (NOT ``_wal_send`` -- that is the held,
#: group-committed path the rule steers toward).
_DIRECT_SENDS = frozenset({"send", "send_no_flush", "broadcast"})

#: The WAL touchpoints whose presence marks a class as WAL-wired.
_WAL_SURFACE = frozenset({"_wal_send", "_wal_drain", "_wal_init"})


def _is_durable(name: str, classes: dict, seen: set | None = None) -> bool:
    if name == "DurableRole":
        return True
    seen = seen or set()
    if name in seen or name not in classes:
        return False
    seen.add(name)
    for _, node in classes[name]:
        for base in node.bases:
            if _is_durable(dotted(base).split(".")[-1], classes, seen):
                return True
    return False


def _wal_appends(fn) -> list:
    """``self.wal.append(...)`` call nodes inside ``fn``."""
    return [node for node in cached_walk(fn)
            if isinstance(node, ast.Call)
            and dotted(node.func).endswith("wal.append")]


def check(project: Project):
    findings: list = []
    classes = flowgraph._class_index(project)

    for mod in project:
        if not focused(project, mod.path):
            continue
        quals = qualname_index(mod.tree)
        ns = flowgraph._module_namespace(project, mod)
        for cls in mod.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            durable = any(
                _is_durable(dotted(b).split(".")[-1], classes)
                for b in cls.bases)
            methods = {n.name: n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            uses_wal = any(
                (isinstance(node, ast.Call)
                 and (dotted(node.func).endswith("wal.append")
                      or dotted(node.func).split(".")[-1]
                      in _WAL_SURFACE))
                for fn in methods.values() for node in cached_walk(fn))

            if uses_wal and not durable and cls.name != "DurableRole":
                findings.append(Finding(
                    rule="DUR502", file=mod.path, line=cls.lineno,
                    scope=cls.name, detail=cls.name,
                    message=f"{cls.name} uses the WAL surface "
                            f"(wal.append/_wal_send) but does not mix "
                            f"in DurableRole: deferred sends bypass "
                            f"the group commit"))

            if not durable:
                continue

            # DUR503: an on_drain override must reach _wal_drain
            # (directly or through its self-call closure).
            if "on_drain" in methods:
                scan = flowgraph._RoleScan(ns, mod, cls, quals)
                closure = scan._closure(["on_drain"])
                reaches = any(
                    isinstance(node, ast.Call)
                    and dotted(node.func).split(".")[-1] == "_wal_drain"
                    for m in closure
                    for node in cached_walk(methods[m]))
                if not reaches:
                    findings.append(Finding(
                        rule="DUR503", file=mod.path,
                        line=methods["on_drain"].lineno,
                        scope=f"{cls.name}.on_drain",
                        detail=f"{cls.name}.on_drain",
                        message=f"{cls.name}.on_drain never calls "
                                f"_wal_drain: staged WAL records are "
                                f"never fsynced and held acks never "
                                f"released"))

            # DUR501: append + direct non-Nack send in one method.
            for name, fn in methods.items():
                appends = _wal_appends(fn)
                if not appends:
                    continue
                for node in cached_walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    leaf = dotted(node.func).split(".")[-1]
                    if leaf not in _DIRECT_SENDS:
                        continue
                    for arg in node.args:
                        top = flowgraph._unwrap_replace(arg)
                        if not isinstance(top, ast.Call):
                            continue
                        found = ns.resolve(mod, dotted(top.func))
                        if found is None:
                            continue
                        msg = found[1].name
                        if "Nack" in msg:
                            continue
                        findings.append(Finding(
                            rule="DUR501", file=mod.path,
                            line=node.lineno,
                            scope=f"{cls.name}.{name}",
                            detail=f"{leaf}:{msg}",
                            message=f"{cls.name}.{name} appends a WAL "
                                    f"record but releases {msg} via "
                                    f"direct {leaf}(): the ack can "
                                    f"precede the drain's fsync -- "
                                    f"route it through _wal_send"))
    return findings


register_rules(RULES, check)
