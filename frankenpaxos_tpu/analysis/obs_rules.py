"""OBS13xx: metric-name drift between exporters and dashboards.

The observability plane has two halves that only meet at runtime: the
``fpx_*`` series registered on a collector registry (obs/trace.py's
RuntimeMetrics and friends -- ``collectors.counter/gauge/histogram/
summary("fpx_...")``), and the PromQL expressions the Grafana
generator (``grafana/generate_dashboards.py``) and the committed
dashboards chart. Nothing ties them together: rename a metric on one
side and the dashboard goes silently blank -- the worst observability
failure mode, because every panel still renders.

Two directions, one rule family:

  * **OBS1301 -- charted but never exported.** An ``fpx_*`` series
    referenced anywhere under ``grafana/`` that no registered metric
    can produce. Histogram registrations export ``_bucket``/``_sum``/
    ``_count`` children and summaries ``_sum``/``_count``, so those
    suffixed forms resolve to their base registration; every other
    name must match a registration exactly.
  * **OBS1302 -- exported but never charted.** A registered ``fpx_*``
    metric that no dashboard or generator expression references (via
    any of its exported series forms) and that is not explicitly
    exempted. Anchored on the registration call so a justified
    ``# paxlint: disable=OBS1302`` pragma (or an ``_UNCHARTED_OK``
    entry here, for families) can clear it.

OBS1301 findings anchor in ``grafana/`` files, which are outside the
package: they surface in full runs (the CI gate) but not in
``--changed-since`` focus runs, like every out-of-focus finding.
"""

from __future__ import annotations

import ast
import os
import re

from frankenpaxos_tpu.analysis.core import (
    cached_walk,
    Finding,
    Project,
    register_rules,
)

RULES = {
    "OBS1301": "dashboard charts an fpx_* series no registered metric "
               "exports (renamed or deleted exporter)",
    "OBS1302": "registered fpx_* metric is charted nowhere and not "
               "exempted (dead series or missing panel)",
}

#: Registered metrics that are deliberately NOT charted. Each entry
#: needs a trailing comment saying why (scrape-only debugging series,
#: metrics consumed by alerts rather than panels, ...). Keep this
#: empty-by-default: the honest fix is usually a panel.
_UNCHARTED_OK: frozenset = frozenset()

#: Exported-series suffixes per registration kind. Counters/gauges
#: export exactly their registered name (this repo registers counters
#: WITH the ``_total`` suffix).
_CHILD_SUFFIXES = {
    "histogram": ("_bucket", "_sum", "_count"),
    "summary": ("_sum", "_count"),
}

_COLLECTOR_METHODS = ("counter", "gauge", "histogram", "summary")

#: A series token: fpx_ followed by snake_case, not ending in ``_``
#: (so a bare ``fpx_runtime_`` prefix in prose never matches).
_SERIES_RE = re.compile(r"\bfpx_[a-z0-9_]*[a-z0-9]\b")

_GRAFANA_DIR = "grafana"


def _registrations(project: Project) -> dict:
    """{metric name: (module path, lineno, kind)} for every
    ``<obj>.counter/gauge/histogram/summary("fpx_...", ...)`` call in
    the package."""
    out: dict = {}
    for mod in project:
        for node in cached_walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _COLLECTOR_METHODS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith("fpx_")):
                continue
            name = node.args[0].value
            out.setdefault(name, (mod.path, node.lineno, node.func.attr))
    return out


def _grafana_files(project: Project) -> list:
    """Repo-relative paths of the generator + committed dashboards."""
    root = os.path.join(project.root, _GRAFANA_DIR)
    files = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for fn in sorted(filenames):
            if fn.endswith((".py", ".json")):
                abspath = os.path.join(dirpath, fn)
                files.append(os.path.relpath(abspath, project.root)
                             .replace(os.sep, "/"))
    return files


def _charted_series(project: Project) -> dict:
    """{series name: (grafana file, first lineno)}."""
    out: dict = {}
    for rel in _grafana_files(project):
        abspath = os.path.join(project.root, rel)
        with open(abspath, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                for m in _SERIES_RE.finditer(line):
                    out.setdefault(m.group(0), (rel, lineno))
    return out


def _exported_forms(name: str, kind: str) -> tuple:
    """Every series name a registration can emit."""
    return (name,) + tuple(
        name + sfx for sfx in _CHILD_SUFFIXES.get(kind, ()))


def check(project: Project):
    registered = _registrations(project)
    charted = _charted_series(project)

    exported: set = set()
    for name, (_, _, kind) in registered.items():
        exported.update(_exported_forms(name, kind))

    findings = []
    for series, (rel, lineno) in sorted(charted.items()):
        if series in exported:
            continue
        findings.append(Finding(
            rule="OBS1301", file=rel, line=lineno,
            scope="<grafana>", detail=series,
            message=f"charts series {series} that no registered metric "
                    f"exports -- the panel renders blank; rename the "
                    f"expression or (re)register the metric"))

    for name, (path, lineno, kind) in sorted(registered.items()):
        if name in _UNCHARTED_OK:
            continue
        if any(form in charted for form in _exported_forms(name, kind)):
            continue
        findings.append(Finding(
            rule="OBS1302", file=path, line=lineno,
            scope="<registry>", detail=name,
            message=f"{kind} {name} is exported but charted nowhere -- "
                    f"add a panel (grafana/generate_dashboards.py), "
                    f"exempt it in analysis/obs_rules.py, or drop the "
                    f"registration"))
    return findings


register_rules(RULES, check)
