"""PAX111: unbounded inbound buffers and sleep-based retry loops.

The overload postmortem shape paxload (serve/, docs/SERVING.md)
exists to prevent: a role buffers inbound work in a bare ``list`` /
``deque`` with no capacity, so offered load past capacity turns into
memory growth and timeout storms instead of explicit shedding; or a
retry "discipline" blocks an event loop in ``time.sleep`` instead of
using transport timers with jittered backoff (serve/backoff.py).

Two patterns, both scoped to role/transport code:

  * **Unbounded inbound buffer** -- an Actor whose ``__init__``
    creates ``self.<X> = []``/``list()``/``deque()`` (no ``maxlen``)
    where ``<X>`` is named like an inbound queue (inbox/inbound/
    pending/queue/buffer/backlog) and a handler-closure method
    appends/extends it. Bounding it (a ``deque(maxlen=...)``, any
    ``len(self.<X>)`` guard in the class, or an
    ``AdmissionController.inbox_full`` check) clears the finding.
  * **Sleep-based retry loop** -- a ``time.sleep`` (or bare
    ``sleep``) call lexically inside a loop anywhere under
    ``runtime/`` or ``protocols/``. Retry pacing belongs on transport
    timers with ``serve.Backoff``; a sleeping loop wedges the event
    loop exactly when the cluster is congested.

Justified exceptions carry ``# paxlint: disable=PAX111``.
"""

from __future__ import annotations

import ast

from frankenpaxos_tpu.analysis.actor_rules import (
    _actor_classes,
    _handler_closure,
)
from frankenpaxos_tpu.analysis.core import (
    cached_walk,
    dotted,
    Finding,
    focused,
    Project,
    register_rules,
)

RULES = {
    "PAX111": "unbounded inbound list/deque buffer or sleep-based "
              "retry loop in role/transport code",
}

#: Attribute-name fragments that mark a buffer as INBOUND work (the
#: shape overload grows without bound). Purpose-named state like
#: ``_staged_writes`` or ``_wal_sends`` is drain-cleared by contract
#: and stays out of scope.
_BUFFER_WORDS = ("inbox", "inbound", "pending", "queue", "buffer",
                 "backlog")

_APPENDS = ("append", "appendleft", "extend", "extendleft")

#: Path segments that mark role/transport code for the sleep-loop
#: pattern (Actor classes are covered wherever they live). Matched
#: package-relative so fixture projects scope the same way.
_SLEEP_SCOPES = ("/runtime/", "/protocols/")


def _unbounded_buffer_attrs(cls: ast.ClassDef) -> dict:
    """{attr name: assign line} for __init__-created list/deque
    buffers with an inbound-ish name and no maxlen."""
    out: dict = {}
    for node in cls.body:
        if not (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == "__init__"):
            continue
        for sub in cached_walk(node):
            if not isinstance(sub, ast.Assign):
                continue
            for target in sub.targets:
                if not (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    continue
                name = target.attr.lower()
                if not any(w in name for w in _BUFFER_WORDS):
                    continue
                value = sub.value
                if isinstance(value, ast.List) and not value.elts:
                    out[target.attr] = sub.lineno
                elif isinstance(value, ast.Call):
                    callee = dotted(value.func).split(".")[-1]
                    if callee in ("list", "deque") and not any(
                            kw.arg == "maxlen" for kw in value.keywords):
                        out[target.attr] = sub.lineno
    return out


def _class_has_bound_guard(cls: ast.ClassDef, attr: str) -> bool:
    """Any ``len(self.<attr>)`` read or ``inbox_full`` call in the
    class counts as a capacity guard."""
    for node in cached_walk(cls):
        if isinstance(node, ast.Call):
            callee = dotted(node.func)
            if callee.split(".")[-1] == "inbox_full":
                return True
            if callee == "len" and node.args \
                    and dotted(node.args[0]) == f"self.{attr}":
                return True
    return False


def _walk_same_scope(root: ast.AST):
    """``ast.walk`` that does not descend into nested function/class
    definitions: their bodies run in another scope that may never
    execute inside the enclosing loop."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)


def check(project: Project):
    findings: list = []
    for mod, cls in _actor_classes(project):
        if not focused(project, mod.path):
            continue
        buffers = _unbounded_buffer_attrs(cls)
        if not buffers:
            continue
        flagged: set = set()
        for name, func in _handler_closure(cls).items():
            for node in cached_walk(func):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _APPENDS):
                    continue
                owner = dotted(node.func.value)
                if not owner.startswith("self."):
                    continue
                attr = owner.split(".", 1)[1]
                if attr not in buffers or attr in flagged:
                    continue
                if _class_has_bound_guard(cls, attr):
                    continue
                flagged.add(attr)
                findings.append(Finding(
                    rule="PAX111", file=mod.path, line=node.lineno,
                    scope=f"{cls.name}.{name}",
                    detail=f"self.{attr}",
                    message=f"handler grows self.{attr} without a "
                            f"bound: overload becomes memory growth "
                            f"and timeout storms -- cap it "
                            f"(deque(maxlen=...), a len() guard, or "
                            f"serve.AdmissionController.inbox_full) "
                            f"and shed explicitly"))
    for mod in project:
        if not any(seg in mod.path for seg in _SLEEP_SCOPES):
            continue
        if not focused(project, mod.path):
            continue
        # One finding per sleep CALL SITE: nested loops both walk over
        # the same call, and sleeps in functions merely DEFINED inside
        # a loop run in another scope (_walk_same_scope stops there).
        seen_lines: set = set()
        for loop in cached_walk(mod.tree):
            if not isinstance(loop, (ast.While, ast.For, ast.AsyncFor)):
                continue
            for node in _walk_same_scope(loop):
                if isinstance(node, ast.Call):
                    callee = dotted(node.func)
                    if callee in ("time.sleep", "sleep") \
                            and node.lineno not in seen_lines:
                        seen_lines.add(node.lineno)
                        findings.append(Finding(
                            rule="PAX111", file=mod.path,
                            line=node.lineno, scope="",
                            detail=callee,
                            message="sleep-based retry loop in "
                                    "role/transport code: pace "
                                    "retries on transport timers "
                                    "with serve.Backoff (a sleeping "
                                    "loop wedges the event loop "
                                    "under congestion)"))
    return findings


register_rules(RULES, check)
