"""TPU2xx: no host syncs or retrace hazards in the drain hot path.

The run pipeline's whole win (ClientRequestArray -> Phase2aRun ->
Phase2bRange -> ChosenRun -> ClientReplyArray, one device dispatch per
event-loop drain) evaporates if anything reachable from the drain path
blocks on the device link or forces XLA to retrace. These rules walk a
name-based call graph from three root sets --

  * every actor's ``on_drain``,
  * the run-pipeline message handlers (the call targets guarded by
    ``isinstance(msg, Phase2aRun / Phase2bRange / Phase2bVotes /
    ChosenRun / ClientRequestArray / ClientReplyArray)``),
  * everything in ``ops/`` (the kernel package),

-- and flag host-synchronization idioms inside the reachable set, plus
retrace hazards inside any ``jax.jit``-ted function project-wide:

  * TPU201 -- ``block_until_ready`` in the hot path.
  * TPU202 -- ``jax.device_get`` in the hot path.
  * TPU203 -- ``np.asarray``/``np.array`` of a device value (the result
    of a ``*_async`` dispatch) in the hot path: a blocking fetch.
  * TPU204 -- ``float()``/``int()``/``bool()`` of a traced value inside
    a jitted function (forces a host sync at trace time).
  * TPU205 -- Python ``if`` on a traced value inside a jitted function
    (TracerBoolConversionError at best, silent retrace at worst).
  * TPU206 -- retrace hazards: ``jax.jit`` invoked inside a hot/jitted
    function body (fresh cache per call), or a static arg bound to a
    non-hashable (list/dict/set) literal.
  * TPU207 -- Python loop over a traced shape inside a jitted function
    (unrolls and recompiles per shape).

Intentional sync points (the drain's single fetch, explicit ``*_sync``
wrappers) carry ``# paxlint: disable=<rule>`` pragmas with their
justification -- new syncs have to declare themselves.
"""

from __future__ import annotations

import ast

from frankenpaxos_tpu.analysis.callgraph import CallGraph, project_graph
from frankenpaxos_tpu.analysis.core import (
    cached_walk,
    dotted,
    Finding,
    focused,
    import_aliases,
    Project,
    qualname_index,
    register_rules,
)

RULES = {
    "TPU201": "block_until_ready reachable from the drain hot path",
    "TPU202": "jax.device_get reachable from the drain hot path",
    "TPU203": "blocking np.asarray of a device value in the hot path",
    "TPU204": "float/int/bool coercion of a traced value in a jitted fn",
    "TPU205": "Python `if` on a traced value in a jitted fn",
    "TPU206": "jit retrace hazard (nested jit / non-hashable static)",
    "TPU207": "Python loop over a traced shape in a jitted fn",
    "TPU208": "blocking fsync/file I/O reachable from ops/ kernel code",
    "TPU209": "trace span/clock hook in ops/ kernel or jit-reachable "
              "code",
}

#: Span-emitting / clock-reading trace hooks (paxtrace, obs/): host
#: observability must stay on the actor loop -- a clock read or span
#: record inside a kernel (or anything a jitted function calls)
#: either breaks tracing under jit (traced once, never at runtime) or
#: serializes the dispatch on host work. The drain/receive spans live
#: in the transports for exactly this reason.
_TRACE_HOOK_LEAVES = frozenset({
    "trace_stage", "stage_scope", "receive_span", "timer_span",
    "drain_span", "record_stage",
})
_CLOCK_LEAVES = frozenset({
    "perf_counter", "perf_counter_ns", "monotonic", "monotonic_ns",
    "process_time", "time_ns",
})

#: Call leaves that mean blocking file I/O (the WAL's group-commit
#: surface): kernels must never reach them -- durability belongs to
#: the actor loop's drain boundary (wal/log.py), never inside a device
#: kernel where it would serialize the pipeline on disk latency.
_FILE_IO_LEAVES = frozenset({
    "open", "fsync", "fdatasync", "write_bytes", "write_text",
})

RUN_PIPELINE_MESSAGES = frozenset({
    "Phase2aRun", "Phase2bRange", "Phase2bVotes", "ChosenRun",
    "ClientRequestArray", "ClientReplyArray",
})


# --- root discovery ---------------------------------------------------------


def _roots(project: Project, graph: CallGraph) -> dict:
    """{ref: reason} for every hot-path entry point."""
    roots: dict = {}
    ops_prefix = f"{project.package}/ops/"
    for ref, info in graph.funcs.items():
        if info.name == "on_drain":
            roots[ref] = "on_drain"
        if info.module.path.startswith(ops_prefix):
            roots[ref] = "ops kernel"
    # Run-pipeline handlers: calls guarded by isinstance checks against
    # the run-pipeline message types.
    for ref, info in list(graph.funcs.items()):
        for node in cached_walk(info.node):
            if not isinstance(node, ast.If):
                continue
            matched = _isinstance_messages(node.test)
            if not matched:
                continue
            for sub in node.body:
                for call in cached_walk(sub):
                    if isinstance(call, ast.Call):
                        for callee in graph.resolve_call(info, call):
                            roots.setdefault(
                                callee,
                                f"handles {'/'.join(sorted(matched))}")
    return roots


def _isinstance_messages(test: ast.AST) -> set:
    """Run-pipeline message names matched by an isinstance() test."""
    out: set = set()
    for node in cached_walk(test):
        if isinstance(node, ast.Call) and dotted(node.func) \
                == "isinstance" and len(node.args) == 2:
            target = node.args[1]
            names = [dotted(e) for e in (
                target.elts if isinstance(target, ast.Tuple)
                else [target])]
            out.update(n.split(".")[-1] for n in names
                       if n.split(".")[-1] in RUN_PIPELINE_MESSAGES)
    return out


# --- jit discovery ----------------------------------------------------------


def _jit_info(func: ast.AST, aliases: dict) -> tuple | None:
    """(static_argnums, static_argnames) if ``func`` is jit-decorated,
    else None."""
    for dec in getattr(func, "decorator_list", ()):
        jit_call = None
        if _is_jit_name(dec, aliases):
            return ((), ())
        if isinstance(dec, ast.Call):
            if _is_jit_name(dec.func, aliases):
                jit_call = dec
            elif dotted(dec.func).split(".")[-1] == "partial" and \
                    dec.args and _is_jit_name(dec.args[0], aliases):
                jit_call = dec
        if jit_call is not None:
            return _static_args(jit_call)
    return None


def _is_jit_name(node: ast.AST, aliases: dict) -> bool:
    d = dotted(node)
    if d in ("jax.jit", "jit"):
        return d != "jit" or aliases.get("jit", "").endswith("jax.jit") \
            or aliases.get("jit") == "jax.jit"
    return aliases.get(d, "") == "jax.jit"


def _static_args(call: ast.Call) -> tuple:
    nums: tuple = ()
    names: tuple = ()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums = tuple(_int_elts(kw.value))
        elif kw.arg == "static_argnames":
            names = tuple(_str_elts(kw.value))
    return nums, names


def _int_elts(node: ast.AST) -> list:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, int)]
    return []


def _str_elts(node: ast.AST) -> list:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)]
    return []


def _traced_params(func: ast.AST, statics: tuple) -> set:
    """Parameter names that are traced under jit (not static, not
    self/cls)."""
    nums, names = statics
    args = func.args
    all_args = list(args.posonlyargs) + list(args.args)
    traced = set()
    for i, a in enumerate(all_args):
        if a.arg in ("self", "cls"):
            continue
        if i in nums or a.arg in names:
            continue
        traced.add(a.arg)
    for a in args.kwonlyargs:
        if a.arg not in names:
            traced.add(a.arg)
    return traced


def _root_names(expr: ast.AST) -> set:
    return {n.id for n in cached_walk(expr) if isinstance(n, ast.Name)}


# --- the checker ------------------------------------------------------------


def check(project: Project):
    findings: list = []
    graph = project_graph(project)
    roots = _roots(project, graph)
    reachable = graph.reachable(list(roots))

    def flag(rule, mod, node, scope, detail, message):
        findings.append(Finding(
            rule=rule, file=mod.path, line=node.lineno, scope=scope,
            detail=detail, message=message))

    # Host-sync idioms in the reachable set.
    for ref, root in reachable.items():
        info = graph.funcs[ref]
        mod = info.module
        if not focused(project, mod.path):
            continue
        via = roots.get(root)
        root_name = graph.funcs[root].qualname
        how = (f"reachable from {root_name} ({via})"
               if ref != root else f"a hot-path root ({via})")
        aliases = import_aliases(mod.tree, mod.name)
        async_locals = _async_locals(info.node)
        for node in cached_walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            leaf = d.split(".")[-1]
            if leaf == "block_until_ready":
                flag("TPU201", mod, node, info.qualname, d,
                     f"{d} blocks on the device link in code {how}; "
                     f"dispatch async and fetch off the drain path")
            elif leaf == "device_get":
                flag("TPU202", mod, node, info.qualname, d,
                     f"{d} synchronously fetches from device in code "
                     f"{how}")
            elif leaf in ("asarray", "array") and len(node.args) >= 1 \
                    and _is_numpy(d, aliases):
                arg = node.args[0]
                src = None
                if isinstance(arg, ast.Call) and \
                        dotted(arg.func).split(".")[-1].endswith("_async"):
                    src = dotted(arg.func)
                elif isinstance(arg, ast.Name) and arg.id in async_locals:
                    src = async_locals[arg.id]
                if src is not None:
                    flag("TPU203", mod, node, info.qualname,
                         f"{d}({src})",
                         f"{d} of the {src} dispatch blocks on the "
                         f"device in code {how}; fetch outside the "
                         f"drain (collector thread / flush timer)")

    # TPU208: blocking file I/O reachable from ops/ KERNEL roots
    # specifically (not from on_drain -- the WAL's one fsync per drain
    # lives exactly there by design; the rule guards the kernels).
    ops_roots = [ref for ref, reason in roots.items()
                 if reason == "ops kernel"]
    for ref, root in graph.reachable(ops_roots).items():
        info = graph.funcs[ref]
        mod = info.module
        if not focused(project, mod.path):
            continue
        root_name = graph.funcs[root].qualname
        how = (f"reachable from ops kernel {root_name}"
               if ref != root else "an ops kernel")
        for node in cached_walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            leaf = d.split(".")[-1]
            if leaf in _FILE_IO_LEAVES:
                flag("TPU208", mod, node, info.qualname, d,
                     f"{d} is blocking file I/O in code {how}; WAL "
                     f"I/O must stay on the actor loop's drain "
                     f"boundary (wal/log.py group commit), never "
                     f"inside kernel code")
            elif leaf in _TRACE_HOOK_LEAVES or _is_clock_read(d):
                flag("TPU209", mod, node, info.qualname, d,
                     f"{d} is a trace span/clock hook in code {how}; "
                     f"paxtrace spans belong to the transports and "
                     f"the actor drain (obs/), never inside kernel "
                     f"code where they serialize the dispatch on "
                     f"host work")

    # Retrace / trace-coercion hazards in jitted functions, plus nested
    # jit in hot code (project-wide: kernels are hot by definition).
    for mod in project:
        if not focused(project, mod.path):
            continue
        aliases = import_aliases(mod.tree, mod.name)
        quals = qualname_index(mod.tree)
        for func in cached_walk(mod.tree):
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            qual = quals[id(func)]
            statics = _jit_info(func, aliases)
            ref = f"{mod.path}::{qual}"
            if statics is None:
                if ref in reachable:
                    for node in _own_nodes(func):
                        if isinstance(node, ast.Call) and \
                                _is_jit_name(node.func, aliases):
                            flag("TPU206", mod, node, qual, "nested jit",
                                 "jax.jit called inside a hot-path "
                                 "function: a fresh jit wrapper per "
                                 "call retraces every time; hoist it "
                                 "to module scope")
                continue
            traced = _traced_params(func, statics)
            for node in _own_nodes(func):
                if isinstance(node, ast.Call):
                    d = dotted(node.func)
                    leaf209 = d.split(".")[-1]
                    if leaf209 in _TRACE_HOOK_LEAVES \
                            or _is_clock_read(d):
                        flag("TPU209", mod, node, qual, d,
                             f"{d} inside a jitted function: the "
                             f"hook runs once at trace time, never "
                             f"per call -- spans/clock reads are "
                             f"silently wrong under jit; emit them "
                             f"from the drain path instead")
                    if d in ("float", "int", "bool") and node.args:
                        used = _root_names(node.args[0]) & traced
                        if used:
                            flag("TPU204", mod, node, qual,
                                 f"{d}({'/'.join(sorted(used))})",
                                 f"{d}() of traced value "
                                 f"{sorted(used)} inside jit forces a "
                                 f"host sync at trace time")
                    elif _is_jit_name(node.func, aliases):
                        flag("TPU206", mod, node, qual, "nested jit",
                             "jax.jit created inside a jitted "
                             "function body retraces per call")
                elif isinstance(node, ast.If):
                    used = _root_names(node.test) & traced
                    if used and not _isinstance_test(node.test):
                        flag("TPU205", mod, node, qual,
                             f"if {'/'.join(sorted(used))}",
                             f"Python `if` on traced value "
                             f"{sorted(used)} inside jit; use "
                             f"jnp.where/lax.cond")
                elif isinstance(node, (ast.For, ast.While)):
                    it = node.iter if isinstance(node, ast.For) \
                        else node.test
                    shape_dep = any(
                        isinstance(sub, ast.Attribute)
                        and sub.attr == "shape"
                        and _root_names(sub) & traced
                        for sub in cached_walk(it))
                    if shape_dep or (_root_names(it) & traced
                                     and isinstance(node, ast.For)):
                        flag("TPU207", mod, node, qual,
                             "loop over traced value",
                             "Python loop over a traced value/shape "
                             "inside jit unrolls the trace and "
                             "recompiles per shape; use lax.scan or "
                             "static shapes")

    # Non-hashable static args at jit call sites: jax.jit(f,
    # static_argnums=...) called with a list/dict/set literal there.
    for mod in project:
        if not focused(project, mod.path):
            continue
        aliases = import_aliases(mod.tree, mod.name)
        for node in cached_walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    _is_jit_name(node.func, aliases):
                for kw in node.keywords:
                    if kw.arg in ("static_argnums", "static_argnames"):
                        continue
                    if isinstance(kw.value, (ast.List, ast.Dict,
                                             ast.Set)):
                        flag("TPU206", mod, node, "<module>",
                             f"static {kw.arg}",
                             f"non-hashable literal bound to jit "
                             f"argument {kw.arg!r}: every call "
                             f"retraces (statics must be hashable)")
    return findings


def _is_clock_read(name: str) -> bool:
    """``time.perf_counter``-style host clock reads. Bare ``time()``
    and ``<obj>.time()`` (the Summary timer) are NOT clock reads; the
    exact dotted ``time.time`` is."""
    return name.split(".")[-1] in _CLOCK_LEAVES or name == "time.time"


def _is_numpy(name: str, aliases: dict) -> bool:
    root = name.split(".")[0]
    return aliases.get(root, root) in ("numpy", "np") or root == "np"


def _isinstance_test(test: ast.AST) -> bool:
    return any(isinstance(n, ast.Call)
               and dotted(n.func) == "isinstance"
               for n in cached_walk(test))


def _own_nodes(func: ast.AST):
    """Nodes of ``func`` excluding nested function/class bodies (they
    are visited as their own scopes)."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _async_locals(func: ast.AST) -> dict:
    """Local names bound from a ``*_async(...)`` call result:
    {name: dispatch call name}."""
    out: dict = {}
    for node in cached_walk(func):
        value = None
        targets = []
        if isinstance(node, ast.Assign):
            value, targets = node.value, node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)) \
                and getattr(node, "value", None) is not None:
            value, targets = node.value, [node.target]
        if isinstance(value, ast.Call):
            d = dotted(value.func)
            if d.split(".")[-1].endswith("_async"):
                for t in targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = d
    return out


register_rules(RULES, check)
