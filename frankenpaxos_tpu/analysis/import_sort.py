"""The tooled import-sort pass (the PR 1 graduation plan).

``ruff``'s ``I`` rules gate new packages in CI, but the container this
repo grows in has no ruff binary -- so the mechanical pass that
graduates the legacy tree lives here, as part of the analysis toolkit,
with the SAME conventions pyproject.toml configures for ruff's isort:

  * sections: ``__future__`` / stdlib / third-party / first-party
    (``frankenpaxos_tpu``) / relative, one blank line between;
  * statements sorted by module name, case-insensitive
    (``case-sensitive = false``), ``import x`` before ``from x
    import`` for the same module;
  * member lists sorted case-insensitively regardless of symbol kind
    (``order-by-type = false``); duplicate from-imports of one module
    merged.

Only TOP-LEVEL import blocks are rewritten (a block = consecutive
top-level import statements; any other statement ends it), so
function-local imports and ``try:``-gated fallbacks are untouched.
Comment lines directly above a statement move with it; a statement's
trailing comment stays on its first line; statements with interior
standalone comments keep their text verbatim (only their position
changes). After rewriting, the module is re-parsed and the imported
(module, name, alias) multiset is asserted unchanged -- the pass can
reorder, never alter, the import surface.

CLI::

    python -m frankenpaxos_tpu.analysis.import_sort [--check] [paths]

``--check`` exits 1 listing files that would change (the CI gate);
without it, files are rewritten in place. Default paths: the package,
``tests/``, and top-level ``*.py``, minus the ``E402``-exempt entry
points (``__graft_entry__.py``, ``bench.py``).
"""

from __future__ import annotations

import argparse
import ast
import os
import sys

#: Must match pyproject.toml's [tool.ruff.lint.isort] known-first-party.
FIRST_PARTY = ("frankenpaxos_tpu", "tests")

#: E402-exempt entry points: they mutate sys.path before importing, so
#: their import order is load-bearing and stays hand-written.
EXCLUDED = ("__graft_entry__.py", "bench.py")

_FUTURE, _STDLIB, _THIRD, _FIRST, _LOCAL = range(5)

#: Single-line regeneration budget: the repo's prevailing style keeps
#: imports comfortably inside ruff's 100-column limit.
_WIDTH = 79


def _section(node) -> int:
    if isinstance(node, ast.ImportFrom):
        if node.level > 0:
            return _LOCAL
        module = node.module or ""
    else:
        module = node.names[0].name
    root = module.split(".")[0]
    if root == "__future__":
        return _FUTURE
    if root in FIRST_PARTY:
        return _FIRST
    if root in sys.stdlib_module_names:
        return _STDLIB
    return _THIRD


def _module_of(node) -> str:
    if isinstance(node, ast.ImportFrom):
        return "." * node.level + (node.module or "")
    return node.names[0].name


def _stmt_key(node) -> tuple:
    module = _module_of(node)
    kind = 1 if isinstance(node, ast.ImportFrom) else 0
    return (module.lower(), module, kind)


def _name_key(alias: ast.alias) -> tuple:
    return (alias.name.lower(), alias.name)


def _render_names(names) -> list:
    out = []
    for a in sorted(names, key=_name_key):
        out.append(a.name + (f" as {a.asname}" if a.asname else ""))
    return out


def _render(node, trailing: str) -> str:
    """Canonical statement text: single line when it fits, else a
    parenthesized one-per-line list with trailing comma."""
    if isinstance(node, ast.Import):
        a = node.names[0]
        line = "import " + a.name + (
            f" as {a.asname}" if a.asname else "")
        return line + trailing
    head = f"from {_module_of(node)} import "
    rendered = _render_names(node.names)
    one = head + ", ".join(rendered) + trailing
    if len(one) <= _WIDTH + (len(trailing) if trailing else 0) \
            and len(one) - len(trailing) <= _WIDTH:
        return one
    lines = [head + "(" + trailing]
    lines += [f"    {n}," for n in rendered]
    lines.append(")")
    return "\n".join(lines)


class _Entry:
    """One import statement with its attached comments and source."""

    def __init__(self, node, comments, text, verbatim):
        self.node = node
        self.comments = comments      # standalone lines above it
        self.text = text              # verbatim source (may be multiline)
        self.verbatim = verbatim      # keep text as-is (interior comments)
        first = text.split("\n")[0]
        self.trailing = ""
        if "#" in first:
            # A trailing comment on the first physical line survives
            # regeneration (``# noqa``, layout notes). Import
            # statements contain no string literals, so the first
            # ``#`` IS the comment.
            head, _, tail = first.partition("#")
            stripped = head.rstrip()
            ok = stripped.endswith("(")
            if not ok:
                try:
                    ast.parse(stripped or "pass")
                    ok = True
                except SyntaxError:
                    pass
            if ok:
                self.trailing = "  #" + tail

    def render(self) -> str:
        body = self.text if self.verbatim else _render(
            self.node, self.trailing)
        if self.comments:
            return "\n".join(self.comments + [body])
        return body


def _import_surface(tree) -> set:
    """The set of (module, name, asname) for every top-level import --
    the invariant the rewrite must preserve (merging may dedupe an
    identical double-import, so a set, not a multiset)."""
    out = set()
    for node in tree.body:
        if isinstance(node, ast.Import):
            for a in node.names:
                out.add(("", a.name, a.asname))
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                out.add(("." * node.level + (node.module or ""),
                         a.name, a.asname))
    return out


def sort_source(src: str) -> str:
    """The rewritten module source (identical when already sorted).
    Iterates to a fixpoint: moving comment-attached statements can
    reshape a block's regions, so one pass may not converge."""
    for _ in range(5):
        new = _sort_once(src)
        if new == src:
            return new
        src = new
    raise AssertionError("import-sort failed to converge")


def _sort_once(src: str) -> str:
    tree = ast.parse(src)
    before = _import_surface(tree)
    lines = src.split("\n")

    # Top-level blocks: consecutive Import/ImportFrom in body order.
    blocks: list = []
    current: list = []
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            current.append(node)
        elif current:
            blocks.append(current)
            current = []
    if current:
        blocks.append(current)

    for block in reversed(blocks):
        entries = []
        region_start = None
        for node in block:
            start = node.lineno
            comments = []
            probe = start - 1
            while probe >= 1:
                text = lines[probe - 1].strip()
                if text.startswith("#"):
                    comments.insert(0, lines[probe - 1])
                    probe -= 1
                else:
                    break
            if region_start is None:
                region_start = probe + 1
            seg = lines[node.lineno - 1:node.end_lineno]
            interior = any(
                s.strip().startswith("#") for s in seg[1:])
            if isinstance(node, ast.Import) and len(node.names) > 1 \
                    and not interior:
                # ``import os, sys`` splits into per-module entries.
                for a in node.names:
                    single = ast.Import(names=[a])
                    entries.append(_Entry(single, comments,
                                          f"import {a.name}"
                                          + (f" as {a.asname}"
                                             if a.asname else ""),
                                          False))
                    comments = []
                continue
            entries.append(_Entry(node, comments, "\n".join(seg),
                                  interior))
        region_end = block[-1].end_lineno

        # Merge duplicate from-imports of one module (non-verbatim).
        merged: dict = {}
        out_entries = []
        for e in entries:
            if isinstance(e.node, ast.ImportFrom) and not e.verbatim:
                key = (e.node.level, e.node.module)
                prior = merged.get(key)
                if prior is not None and not prior.trailing \
                        and not e.trailing and not e.comments:
                    seen = {(a.name, a.asname)
                            for a in prior.node.names}
                    prior.node.names.extend(
                        a for a in e.node.names
                        if (a.name, a.asname) not in seen)
                    continue
                merged.setdefault(key, e)
            out_entries.append(e)

        sections: dict = {}
        for e in out_entries:
            sections.setdefault(_section(e.node), []).append(e)
        rendered_sections = []
        for sec in sorted(sections):
            stmts = sorted(sections[sec],
                           key=lambda e: _stmt_key(e.node))
            rendered_sections.append(
                "\n".join(e.render() for e in stmts))
        new_region = "\n\n".join(rendered_sections)
        lines[region_start - 1:region_end] = new_region.split("\n")

    new_src = "\n".join(lines)
    new_tree = ast.parse(new_src)  # must still parse
    assert _import_surface(new_tree) == before, \
        "import-sort changed the import surface; refusing"
    return new_src


def _targets(root: str) -> list:
    out = []
    for base in ("frankenpaxos_tpu", "tests"):
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(root, base)):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py") and fn not in EXCLUDED:
                    out.append(os.path.join(dirpath, fn))
    for fn in sorted(os.listdir(root)):
        if fn.endswith(".py") and fn not in EXCLUDED:
            out.append(os.path.join(root, fn))
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m frankenpaxos_tpu.analysis.import_sort")
    parser.add_argument("paths", nargs="*",
                        help="files to sort (default: the repo)")
    parser.add_argument("--check", action="store_true",
                        help="exit 1 listing files that would change")
    parser.add_argument("--root", default=None)
    args = parser.parse_args(argv)

    root = args.root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    paths = args.paths or _targets(root)
    changed = []
    for path in paths:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        new = sort_source(src)
        if new != src:
            changed.append(path)
            if not args.check:
                with open(path, "w", encoding="utf-8") as f:
                    f.write(new)
    if args.check and changed:
        print(f"import-sort: {len(changed)} file(s) need sorting:")
        for p in changed:
            print(f"  {os.path.relpath(p, root)}")
        print("\nimport-sort: run `python -m "
              "frankenpaxos_tpu.analysis.import_sort` and commit.")
        return 1
    verb = "would sort" if args.check else "sorted"
    print(f"import-sort: {verb} {len(changed)} of {len(paths)} "
          f"file(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
