"""SARIF 2.1.0 rendering of paxlint findings.

One ``run`` with one result per finding -- the SAME finding set as the
JSON document (tests/test_analysis_cli.py proves the round trip), so
code-scanning UIs that ingest SARIF and tooling that reads
paxlint.json can never disagree. Grandfathered findings map to
``"note"`` severity (visible but non-blocking, like the baseline);
new findings map to ``"error"``.
"""

from __future__ import annotations

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def render(findings, grandfathered: set, rules: dict) -> dict:
    """The SARIF document (a JSON-ready dict) for ``findings``.
    ``grandfathered`` holds the baselined finding keys; ``rules`` maps
    every registered rule id to its one-line description."""
    used = sorted({f.rule for f in findings})
    results = [
        {
            "ruleId": f.rule,
            "level": "note" if f.key in grandfathered else "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.file},
                    "region": {"startLine": f.line},
                },
                "logicalLocations": [{"fullyQualifiedName": f.scope}],
            }],
            "partialFingerprints": {
                # The baseline's stable key: line-independent, so a
                # SARIF consumer dedupes across unrelated edits
                # exactly like the baseline does.
                "paxlintKey/v1": "|".join(f.key),
            },
            "properties": {
                "detail": f.detail,
                "baselined": f.key in grandfathered,
            },
        }
        for f in sorted(findings, key=lambda f: (f.file, f.line, f.rule))
    ]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "paxlint",
                "informationUri":
                    "docs/ANALYSIS.md",
                "rules": [
                    {
                        "id": rule,
                        "shortDescription": {"text": rules[rule]},
                    }
                    for rule in used
                ],
            }},
            "results": results,
        }],
    }
