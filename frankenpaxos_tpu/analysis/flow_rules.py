"""FLOW4xx: message-topology contracts over the paxflow graph.

The flow graph (flowgraph.py) recovers, per protocol unit, which role
sends which message to whom. These rules turn that recovered topology
into CI-gated contracts:

  * FLOW401 -- a message some role SENDS that no role anywhere in the
    project handles: the frame arrives and hits the ``unexpected
    message`` fatal (or silently pickles into a dead inbox).
  * FLOW402 -- a message some role HANDLES that nothing in the project
    ever sends or wire-encodes: dead dispatch arms rot (the handler
    executes only in a test's imagination).
  * FLOW403 -- a registered wire-codec tag whose message has no send
    or encode site anywhere: an orphan tag squats on the closed 1..255
    tag space (the scarcest wire resource) for a message that never
    crosses the wire.
  * FLOW404 -- a ``*Request`` message with no reply path (no chain of
    send edges from its handler roles back to a sender role) and no
    timer-driven resend: if the request or its effect is dropped, the
    sender hangs forever.
  * FLOW405 -- serve/lanes.py lane classification disagreeing with the
    graph: (a) a name in CLIENT_LANE_TYPE_NAMES that is sent but has
    NO codec tag -- the frame-layer classifier is tag-based, so the
    pickled frame silently rides the control lane and the bounded
    inbox can never shed it; (b) a codec-tagged client-edge message
    (sent only by Client*/Batcher roles, ``*Request*`` name) missing
    from CLIENT_LANE_TYPE_NAMES -- unshedable client traffic that
    bypasses overload admission at the frame layer.

Messages that exist only as nested payload of another sent message
(``Command`` inside ``ClientRequest``) are decoded by the outer codec,
not dispatched, so payload-only senders never trip FLOW401.
"""

from __future__ import annotations

import ast

from frankenpaxos_tpu.analysis import flowgraph
from frankenpaxos_tpu.analysis.core import (
    cached_walk,
    Finding,
    focus_touches,
    Project,
    register_rules,
)

RULES = {
    "FLOW401": "message is sent but handled by no role anywhere",
    "FLOW402": "message is handled but never sent or encoded",
    "FLOW403": "registered codec tag has no send or encode site",
    "FLOW404": "request message with no reply path and no timer resend",
    "FLOW405": "serve/lanes.py lane classification disagrees with the "
               "flow graph",
}

#: WAL record codecs (wal/records.py) declare ``message_type``/``tag``
#: like wire codecs but live in their OWN closed tag space appended to
#: disk, never sent -- they are not FLOW403's surface.
_WAL_PREFIX = "wal/"


def _transport_layer_codecs(project: Project) -> set:
    """Message keys of codecs whose class body sets
    ``transport_layer = True``: paxwire batch envelopes encoded by the
    TRANSPORT's flush planner and expanded before delivery
    (runtime/paxwire.py, Phase2bAckBatch) -- deliberately no role send
    site, so FLOW403's orphan-tag surface excludes them."""
    from frankenpaxos_tpu.analysis import codec_rules

    marked: set = set()
    for mod, cls, msg_dotted in codec_rules._codec_classes(project):
        if not any(isinstance(stmt, ast.Assign)
                   and len(stmt.targets) == 1
                   and isinstance(stmt.targets[0], ast.Name)
                   and stmt.targets[0].id == "transport_layer"
                   and isinstance(stmt.value, ast.Constant)
                   and stmt.value.value is True
                   for stmt in cls.body):
            continue
        entry = codec_rules._resolve_message_class(project, mod,
                                                   msg_dotted)
        if entry is not None:
            msg_mod, msg_cls = entry
            marked.add((msg_mod.path, msg_cls.name))
    return marked

_REQUEST_SUFFIXES = ("Request", "RequestBatch")

#: Where FLOW4xx findings anchor: message-class modules, codec
#: modules, and serve/lanes.py. Diff-aware runs skip the family's
#: project-wide graph passes when the focus closure cannot hold a
#: finding (core.focus_touches).
_FINDING_SURFACE = ("/election/", "/ingest/", "/protocols/",
                    "/reconfig/", "/runtime/", "/serve/", "/wal/",
                    "heartbeat.py")


def _lane_type_names(project: Project) -> tuple:
    """(lanes module path, line, frozenset of names) parsed from the
    CLIENT_LANE_TYPE_NAMES literal in serve/lanes.py (pure AST -- the
    analysis never imports runtime modules)."""
    path = f"{project.package}/serve/lanes.py"
    mod = project.modules.get(path)
    if mod is None:
        return path, 1, frozenset()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "CLIENT_LANE_TYPE_NAMES":
            names = {c.value for c in cached_walk(node.value)
                     if isinstance(c, ast.Constant)
                     and isinstance(c.value, str)}
            return path, node.lineno, frozenset(names)
    return path, 1, frozenset()


def _client_edge_roles(senders) -> bool:
    """Every sending role is a client-side edge role (clients and the
    batchers that front them)."""
    return bool(senders) and all(
        "Client" in r or "Batcher" in r for r in senders)


def check(project: Project):
    if not focus_touches(project, _FINDING_SURFACE):
        return []
    findings: list = []
    graphs = flowgraph.build_all(project)
    sent_any = set(flowgraph.global_sent_types(project))
    handled_any = set(flowgraph.global_handled_types(project))
    for g in graphs.values():
        for mname, info in g.messages.items():
            if info.senders:
                sent_any.add((info.module, mname))
            if info.handlers:
                handled_any.add((info.module, mname))

    lanes_path, lanes_line, lane_names = _lane_type_names(project)
    flagged_403: set = set()
    flagged_405: set = set()

    for unit in sorted(graphs):
        g = graphs[unit]
        # Role-level send graph for FLOW404's reply reachability.
        role_edges: dict = {}
        for info in g.messages.values():
            for s in info.senders:
                for h in info.handlers:
                    role_edges.setdefault(s, set()).add(h)
        # Only units that register codecs at all participate in
        # frame-lane shedding; an all-pickled protocol rides the
        # control lane uniformly, which is no DISAGREEMENT (405a).
        unit_tagged = any(m.codec_tag is not None
                          for m in g.messages.values())

        for mname in sorted(g.messages):
            info = g.messages[mname]
            key = (info.module, mname)
            real_senders = {r for r, kinds in info.senders.items()
                            if kinds - {"payload"}}

            if real_senders and not info.handlers \
                    and key not in handled_any:
                findings.append(Finding(
                    rule="FLOW401", file=info.module, line=info.line,
                    scope=mname, detail=f"{unit}:{mname}",
                    message=f"{mname} is sent by "
                            f"{'/'.join(sorted(real_senders))} but no "
                            f"role anywhere handles it: the receiver "
                            f"hits its unexpected-message fatal"))

            if info.handlers and not info.senders \
                    and key not in sent_any:
                findings.append(Finding(
                    rule="FLOW402", file=info.module, line=info.line,
                    scope=mname, detail=f"{unit}:{mname}",
                    message=f"{mname} is handled by "
                            f"{'/'.join(sorted(info.handlers))} but "
                            f"nothing ever sends it: dead dispatch "
                            f"arm"))

            if mname.endswith(_REQUEST_SUFFIXES) and info.senders \
                    and info.handlers \
                    and "timer" not in info.send_origins:
                seen: set = set()
                stack = list(info.handlers)
                while stack:
                    r = stack.pop()
                    if r in seen:
                        continue
                    seen.add(r)
                    stack.extend(role_edges.get(r, ()))
                if not (seen & set(info.senders)):
                    findings.append(Finding(
                        rule="FLOW404", file=info.module,
                        line=info.line, scope=mname,
                        detail=f"{unit}:{mname}",
                        message=f"{mname} "
                                f"({'/'.join(sorted(info.senders))} -> "
                                f"{'/'.join(sorted(info.handlers))}) "
                                f"has no reply path back to its "
                                f"sender and no timer resend: a "
                                f"dropped request hangs forever"))

            # FLOW405a: named in the client lane, but unclassifiable
            # at the frame layer (no codec tag -> pickled -> control).
            if mname in lane_names and real_senders \
                    and info.codec_tag is None and unit_tagged \
                    and key not in flagged_405:
                flagged_405.add(key)
                findings.append(Finding(
                    rule="FLOW405", file=info.module, line=info.line,
                    scope=mname, detail=f"untagged-lane:{mname}",
                    message=f"{mname} is in serve/lanes.py "
                            f"CLIENT_LANE_TYPE_NAMES but has no "
                            f"registered codec: its pickled frames "
                            f"ride the CONTROL lane, so the bounded "
                            f"inbox can never shed it (give it a "
                            f"fixed-layout codec)"))

            # FLOW405b: client-edge-shaped and tagged, but missing
            # from the lane list -- unshedable client traffic.
            if mname not in lane_names and info.codec_tag is not None \
                    and "Request" in mname \
                    and not mname.endswith("Reply") \
                    and _client_edge_roles(real_senders) \
                    and info.handlers and key not in flagged_405:
                flagged_405.add(key)
                findings.append(Finding(
                    rule="FLOW405", file=lanes_path, line=lanes_line,
                    scope="CLIENT_LANE_TYPE_NAMES",
                    detail=f"unclassified:{mname}",
                    message=f"{mname} (tag {info.codec_tag}, sent "
                            f"only by "
                            f"{'/'.join(sorted(real_senders))}) is "
                            f"client-edge traffic missing from "
                            f"CLIENT_LANE_TYPE_NAMES: it can never "
                            f"be shed under overload"))

    # FLOW403: orphan codec tags, project-wide.
    transport_layer = _transport_layer_codecs(project)
    for (mod_path, mname), tag in sorted(
            flowgraph._codec_tags(project).items()):
        if mod_path.startswith(f"{project.package}/{_WAL_PREFIX}"):
            continue
        if (mod_path, mname) in transport_layer:
            continue
        if (mod_path, mname) in sent_any:
            continue
        if (mod_path, mname) in flagged_403:
            continue
        flagged_403.add((mod_path, mname))
        mod = project.modules.get(mod_path)
        line = 1
        if mod is not None:
            for node in cached_walk(mod.tree):
                if isinstance(node, ast.ClassDef) \
                        and node.name == mname:
                    line = node.lineno
                    break
        findings.append(Finding(
            rule="FLOW403", file=mod_path, line=line, scope=mname,
            detail=f"tag:{tag}:{mname}",
            message=f"codec tag {tag} is registered for {mname} but "
                    f"nothing sends or encodes it: orphan tag in the "
                    f"closed wire tag space"))

    return findings


register_rules(RULES, check)
