"""SHAPE6xx: abstract shape/dtype interpretation over kernel code.

The ops/ kernels are jitted once and replayed per drain; XLA traces
them against concrete shapes and dtypes. Three hazard classes survive
unit tests on CPU (where retraces are cheap and x64 flags differ) and
then bite on a real TPU as retrace storms or ConcretizationErrors.
These rules catch them statically, inside every jitted function
(decorated ``@jax.jit`` / ``@functools.partial(jax.jit, ...)`` or
wrapped module-level ``f2 = jax.jit(f)``):

  * SHAPE601 -- data-dependent output shapes: ``jnp.nonzero`` /
    ``flatnonzero`` / ``argwhere`` / ``unique`` / ``compress`` /
    ``extract`` / one-argument ``jnp.where`` without a static
    ``size=``. Under jit the output shape depends on VALUES, which is
    a trace-time error (or, via host fallback, a silent sync).
  * SHAPE602 -- dtype-coercion retrace hazards: ``.astype(int/float/
    bool)`` (the builtin resolves differently under the x64 flag, so
    two hosts trace two dtypes for one kernel), and value-typed array
    creation (``jnp.array`` / ``jnp.full`` / ``jnp.arange``) without
    an explicit ``dtype=`` -- the weak dtype follows the argument's
    Python type, so an int-vs-float caller flips the traced dtype and
    retraces.
  * SHAPE603 -- shard-axis mismatches: a string axis name used in a
    collective (``lax.psum(x, axis_name="...")``) or a
    ``PartitionSpec`` that no mesh declaration, ``*_axis`` parameter
    binding, or partition constant in the project ever declares --
    a typo'd axis name fails only when the sharded path finally runs
    on a multi-chip mesh.
"""

from __future__ import annotations

import ast

from frankenpaxos_tpu.analysis.core import (
    cached_walk,
    dotted,
    Finding,
    focused,
    import_aliases,
    Project,
    qualname_index,
    register_rules,
)
from frankenpaxos_tpu.analysis.hotpath_rules import (
    _is_jit_name,
    _jit_info,
    _own_nodes,
)

RULES = {
    "SHAPE601": "data-dependent output shape in a jitted fn "
                "(nonzero/unique/1-arg where without size=)",
    "SHAPE602": "dtype-coercion retrace hazard in a jitted fn "
                "(builtin astype / value-typed creation without "
                "dtype=)",
    "SHAPE603": "shard axis name used but declared by no mesh, "
                "*_axis binding, or partition constant",
}

_DATA_DEP_LEAVES = frozenset({
    "nonzero", "flatnonzero", "argwhere", "unique", "compress",
    "extract",
})

_VALUE_TYPED_CREATORS = frozenset({"array", "full", "arange"})

_COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "ppermute",
    "axis_index", "psum_scatter", "all_to_all",
})

_PSPEC_NAMES = frozenset({"PartitionSpec", "P"})


def _is_jnp(name: str, aliases: dict) -> bool:
    root = name.split(".")[0]
    target = aliases.get(root, root)
    return target in ("jax.numpy", "jnp") or root == "jnp" \
        or target.endswith(".numpy")


def _kw(node: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in node.keywords)


def _jitted_functions(mod, aliases: dict):
    """(qualname, FunctionDef) for decorator-jitted functions plus
    module-level ``wrapped = jax.jit(local_fn, ...)`` targets."""
    quals = qualname_index(mod.tree)
    by_name: dict = {}
    for node in cached_walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, node)
            if _jit_info(node, aliases) is not None:
                yield quals[id(node)], node
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and _is_jit_name(node.value.func, aliases) \
                and node.value.args:
            target = dotted(node.value.args[0])
            fn = by_name.get(target.split(".")[-1])
            if fn is not None and _jit_info(fn, aliases) is None:
                yield quals[id(fn)], fn


def _declared_axes(mod, aliases: dict) -> set:
    """Axis names this module declares: Mesh constructions,
    ``axis_names=`` keywords, ``mesh.shape["..."]`` subscripts,
    ``*_axis`` parameter defaults and keyword bindings, and strings in
    module-level ``*PARTITION*``/``*AXES*`` constants."""
    out: set = set()
    for node in cached_walk(mod.tree):
        if isinstance(node, ast.Call):
            leaf = dotted(node.func).split(".")[-1]
            if leaf in ("Mesh", "make_mesh"):
                for arg in list(node.args) + [
                        kw.value for kw in node.keywords]:
                    out.update(c.value for c in cached_walk(arg)
                               if isinstance(c, ast.Constant)
                               and isinstance(c.value, str))
            for kw in node.keywords:
                if kw.arg and (kw.arg == "axis_names"
                               or kw.arg.endswith("_axis")):
                    out.update(c.value for c in cached_walk(kw.value)
                               if isinstance(c, ast.Constant)
                               and isinstance(c.value, str))
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.value, ast.Attribute) \
                and node.value.attr == "shape" \
                and isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str):
            out.add(node.slice.value)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            pos = list(args.posonlyargs) + list(args.args)
            for a, default in zip(pos[len(pos) - len(args.defaults):],
                                  args.defaults):
                if a.arg.endswith("_axis") \
                        and isinstance(default, ast.Constant) \
                        and isinstance(default.value, str):
                    out.add(default.value)
            for a, default in zip(args.kwonlyargs, args.kw_defaults):
                if default is not None and a.arg.endswith("_axis") \
                        and isinstance(default, ast.Constant) \
                        and isinstance(default.value, str):
                    out.add(default.value)
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and any(k in node.targets[0].id.upper()
                        for k in ("PARTITION", "AXES", "AXIS")):
            out.update(c.value for c in cached_walk(node.value)
                       if isinstance(c, ast.Constant)
                       and isinstance(c.value, str))
    return out


def _used_axes(mod) -> list:
    """(axis name, lineno, context) literals this module consumes:
    collectives' ``axis_name=`` and PartitionSpec positional args."""
    out: list = []
    for node in cached_walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        leaf = dotted(node.func).split(".")[-1]
        if leaf in _COLLECTIVES:
            for kw in node.keywords:
                if kw.arg == "axis_name":
                    for c in cached_walk(kw.value):
                        if isinstance(c, ast.Constant) \
                                and isinstance(c.value, str):
                            out.append((c.value, node.lineno, leaf))
        elif leaf in _PSPEC_NAMES:
            for arg in node.args:
                for c in cached_walk(arg):
                    if isinstance(c, ast.Constant) \
                            and isinstance(c.value, str):
                        out.append((c.value, node.lineno, leaf))
    return out


def check(project: Project):
    findings: list = []

    # SHAPE601/602 inside every jitted function.
    for mod in project:
        if not focused(project, mod.path):
            continue
        aliases = import_aliases(mod.tree, mod.name)
        for qual, fn in _jitted_functions(mod, aliases):
            for node in _own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                leaf = d.split(".")[-1]
                if leaf in _DATA_DEP_LEAVES and _is_jnp(d, aliases) \
                        and not _kw(node, "size"):
                    findings.append(Finding(
                        rule="SHAPE601", file=mod.path,
                        line=node.lineno, scope=qual, detail=d,
                        message=f"{d} without size= inside a jitted "
                                f"function: the output shape depends "
                                f"on runtime values, which cannot "
                                f"trace (pass size=/fill_value=)"))
                elif leaf == "where" and _is_jnp(d, aliases) \
                        and len(node.args) == 1 \
                        and not _kw(node, "size"):
                    findings.append(Finding(
                        rule="SHAPE601", file=mod.path,
                        line=node.lineno, scope=qual, detail="where/1",
                        message="one-argument jnp.where inside a "
                                "jitted function has a data-dependent "
                                "output shape; use the three-argument "
                                "form or pass size="))
                elif leaf == "astype" and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Name) \
                            and arg.id in ("int", "float", "bool"):
                        findings.append(Finding(
                            rule="SHAPE602", file=mod.path,
                            line=node.lineno, scope=qual,
                            detail=f"astype:{arg.id}",
                            message=f"astype({arg.id}) inside a "
                                    f"jitted function resolves "
                                    f"through the x64 flag: two "
                                    f"hosts trace two dtypes for one "
                                    f"kernel -- name the dtype "
                                    f"explicitly (jnp.int32, ...)"))
                elif leaf in _VALUE_TYPED_CREATORS \
                        and _is_jnp(d, aliases) \
                        and not _kw(node, "dtype"):
                    findings.append(Finding(
                        rule="SHAPE602", file=mod.path,
                        line=node.lineno, scope=qual, detail=d,
                        message=f"{d} without dtype= inside a jitted "
                                f"function: the weak dtype follows "
                                f"the argument's Python type, so an "
                                f"int-vs-float caller retraces the "
                                f"kernel -- pin dtype= explicitly"))

    # SHAPE603 project-wide: axis-name vocabulary.
    declared: set = set()
    per_mod: dict = {}
    for mod in project:
        aliases = import_aliases(mod.tree, mod.name)
        per_mod[mod.path] = _declared_axes(mod, aliases)
        declared |= per_mod[mod.path]
    if declared:
        for mod in project:
            if not focused(project, mod.path):
                continue
            for axis, lineno, ctx in _used_axes(mod):
                if axis not in declared:
                    findings.append(Finding(
                        rule="SHAPE603", file=mod.path, line=lineno,
                        scope="<module>", detail=f"{ctx}:{axis}",
                        message=f"axis name {axis!r} used in {ctx} is "
                                f"declared by no mesh, *_axis "
                                f"binding, or partition constant "
                                f"anywhere in the project: typo'd "
                                f"shard axes fail only on a real "
                                f"multi-chip mesh"))
    return findings


register_rules(RULES, check)
