"""PAX1xx: the single-threaded actor/transport contract.

Reference behavior: every role is an ``Actor`` whose ``receive``/
``on_drain``/timer callbacks run serially on ONE event loop
(NettyTcpTransport.scala:240's single ``NioEventLoopGroup``; the sim
transport runs actors inline). The contract is what lets a protocol run
unchanged in production, simulation, and visualization -- so handler
code must never block, spawn, or synchronize:

  * PAX101 -- no ``threading``/``multiprocessing`` use inside handlers.
  * PAX102 -- no lock creation or ``.acquire()`` inside handlers.
  * PAX103 -- no blocking ``time.sleep`` inside handlers.
  * PAX104 -- timers only via the transport (``self.timer``): no
    ``threading.Timer``, ``loop.call_later``, or ``asyncio`` scheduling
    anywhere in an actor class.
  * PAX105 -- no module-level mutable state referenced from more than
    one actor class (actors colocated in one process -- supernode mode,
    sims -- must not share state behind the transport's back).
  * PAX106 -- no ``send``/``broadcast``/``flush`` from code that runs
    off the event loop (thread targets); post back with
    ``loop.call_soon_threadsafe`` instead.

"Handlers" are ``receive``/``on_drain`` plus everything reachable from
them through ``self.*()`` calls, nested defs, and callbacks passed to
``self.timer`` -- construction-time code (``__init__``) is exempt for
PAX101-103 because the reference itself spawns infrastructure there
(and sends Phase1as), but PAX104 applies class-wide.
"""

from __future__ import annotations

import ast

from frankenpaxos_tpu.analysis.core import (
    cached_walk,
    dotted,
    Finding,
    focused,
    import_aliases,
    Module,
    Project,
    register_rules,
)

RULES = {
    "PAX101": "threading/multiprocessing use inside an actor handler",
    "PAX102": "lock creation or acquire inside an actor handler",
    "PAX103": "blocking time.sleep inside an actor handler",
    "PAX104": "timer not created via the transport inside an actor",
    "PAX105": "module-level mutable state shared across actor classes",
    "PAX106": "send/broadcast/flush from off-event-loop code",
}

_HANDLER_SEEDS = ("receive", "on_drain")
_MUTABLE_CALLS = {"dict", "list", "set", "defaultdict", "deque",
                  "OrderedDict", "Counter"}
_SEND_METHODS = {"send", "send_no_flush", "broadcast", "flush", "reply"}


def _class_index(project: Project) -> dict:
    """class name -> (Module, ClassDef, [base names]) across the
    project (name-keyed; duplicate names keep the first, which is fine
    for the Actor hierarchy)."""
    out: dict = {}
    for mod in project:
        for node in cached_walk(mod.tree):
            if isinstance(node, ast.ClassDef) and node.name not in out:
                out[node.name] = (
                    mod, node, [dotted(b).split(".")[-1]
                                for b in node.bases])
    return out


def _actor_classes(project: Project) -> list:
    """Every class transitively deriving from Actor: (Module, ClassDef)."""
    index = _class_index(project)

    def is_actor(name: str, seen: set) -> bool:
        if name == "Actor":
            return True
        if name in seen or name not in index:
            return False
        seen.add(name)
        return any(is_actor(b, seen) for b in index[name][2])

    return [(mod, node) for name, (mod, node, bases) in index.items()
            if name != "Actor" and is_actor(name, set())]


def _methods(cls: ast.ClassDef) -> dict:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _timer_callbacks(func: ast.AST) -> list:
    """Names of methods/functions passed as the callback to
    ``self.timer(name, delay, f)``."""
    out = []
    for node in cached_walk(func):
        if isinstance(node, ast.Call) and dotted(node.func) in (
                "self.timer",):
            for arg in list(node.args) + [kw.value for kw in
                                          node.keywords]:
                name = dotted(arg)
                if name.startswith("self."):
                    out.append(name.split(".", 1)[1])
                elif isinstance(arg, ast.Name):
                    out.append(arg.id)
    return out


def _handler_closure(cls: ast.ClassDef) -> dict:
    """Handler methods: seeds + self-call/timer-callback closure, plus
    bound-method REFERENCES (``handlers = {Phase1a: self._handle_...}``
    dispatch tables pass handlers as values, not calls). Returns
    {method name: node}."""
    methods = _methods(cls)
    frontier = [m for m in _HANDLER_SEEDS if m in methods]
    closure: dict = {}
    while frontier:
        name = frontier.pop()
        if name in closure or name not in methods:
            continue
        closure[name] = methods[name]
        for node in cached_walk(methods[name]):
            if isinstance(node, ast.Call):
                called = dotted(node.func)
                if called.startswith("self.") and called.count(".") == 1:
                    frontier.append(called.split(".", 1)[1])
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self" \
                    and node.attr in methods:
                frontier.append(node.attr)
        frontier.extend(_timer_callbacks(methods[name]))
    return closure


def _thread_targets(cls: ast.ClassDef, methods: dict) -> list:
    """Functions that run OFF the event loop: anything passed as
    ``target=`` to a Thread (or submitted to an executor), plus their
    self-call closure. Returns [(name, node)]."""
    roots: list = []
    nested: dict = {}
    for node in cached_walk(cls):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested[node.name] = node
    for node in cached_walk(cls):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name.endswith("Thread") or name.endswith(".submit"):
            candidates = [kw.value for kw in node.keywords
                          if kw.arg == "target"]
            if name.endswith(".submit") and node.args:
                candidates.append(node.args[0])
            for cand in candidates:
                cn = dotted(cand)
                cn = cn.split(".", 1)[1] if cn.startswith("self.") else cn
                if cn in nested:
                    roots.append(cn)
    out: list = []
    seen: set = set()
    while roots:
        name = roots.pop()
        if name in seen or name not in nested:
            continue
        seen.add(name)
        out.append((name, nested[name]))
        for node in cached_walk(nested[name]):
            if isinstance(node, ast.Call):
                called = dotted(node.func)
                if called.startswith("self.") and called.count(".") == 1:
                    roots.append(called.split(".", 1)[1])
                elif called in nested:
                    roots.append(called)
    return out


def _module_refs(mod: Module) -> dict:
    """alias -> top-level module it came from ("threading", "time"...)."""
    out = {}
    for alias, target in import_aliases(mod.tree, mod.name).items():
        out[alias] = target.split(".")[0]
    return out


def check(project: Project):
    findings: list = []
    actors = _actor_classes(project)
    per_module_actors: dict = {}
    for mod, cls in actors:
        if not focused(project, mod.path):
            continue
        per_module_actors.setdefault(mod.path, []).append(cls)
        refs = _module_refs(mod)

        def flag(rule, node, scope, detail, message):
            findings.append(Finding(
                rule=rule, file=mod.path, line=node.lineno,
                scope=scope, detail=detail, message=message))

        handlers = _handler_closure(cls)
        for name, func in handlers.items():
            scope = f"{cls.name}.{name}"
            for node in cached_walk(func):
                if not isinstance(node, (ast.Call, ast.Attribute,
                                         ast.Name)):
                    continue
                d = dotted(node)
                root = d.split(".")[0]
                resolved = refs.get(root, root)
                if isinstance(node, ast.Call):
                    if resolved in ("threading", "multiprocessing"):
                        flag("PAX101", node, scope, d,
                             f"handler uses {resolved} ({d}); actors are "
                             f"single-threaded -- stage work and use "
                             f"on_drain or transport timers")
                    if (d.endswith(".acquire")
                            or d.split(".")[-1] in ("Lock", "RLock",
                                                    "Semaphore",
                                                    "Condition")):
                        flag("PAX102", node, scope, d,
                             f"handler takes/creates a lock ({d}); the "
                             f"event loop already serializes handlers")
                    leaf = d.split(".")[-1]
                    if leaf == "sleep" and resolved == "time":
                        flag("PAX103", node, scope, d,
                             "handler blocks in time.sleep; use a "
                             "transport timer instead")
                elif isinstance(node, ast.Name) and isinstance(
                        node.ctx, ast.Load):
                    if refs.get(node.id) in ("threading",
                                             "multiprocessing") \
                            and node.id != "TYPE_CHECKING":
                        flag("PAX101", node, scope, node.id,
                             f"handler references {refs[node.id]} "
                             f"symbol {node.id}")

        # PAX104: class-wide (timers wired at construction count too).
        for node in cached_walk(cls):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node)
            leaf = d.split(".")[-1]
            if d in ("threading.Timer",) or leaf in ("call_later",
                                                     "call_at"):
                scope = cls.name
                for m, fn in _methods(cls).items():
                    if fn.lineno <= node.lineno <= getattr(
                            fn, "end_lineno", fn.lineno):
                        scope = f"{cls.name}.{m}"
                        break
                findings.append(Finding(
                    rule="PAX104", file=mod.path, line=node.lineno,
                    scope=scope, detail=d,
                    message=f"timer created via {d}; actors must use "
                            f"self.timer(...) so sims/viz can control "
                            f"time"))

        # PAX106: sends from thread targets.
        for name, func in _thread_targets(cls, _methods(cls)):
            for node in cached_walk(func):
                if isinstance(node, ast.Call):
                    d = dotted(node.func)
                    if (d.startswith("self.")
                            and d.split(".")[-1] in _SEND_METHODS
                            and d.count(".") == 1):
                        findings.append(Finding(
                            rule="PAX106", file=mod.path,
                            line=node.lineno,
                            scope=f"{cls.name}.{name}", detail=d,
                            message=f"{d} called from off-loop code "
                                    f"({name} runs on a worker thread); "
                                    f"post results back with "
                                    f"loop.call_soon_threadsafe"))

    # PAX105: module-level mutable state shared across actor classes.
    for path, classes in per_module_actors.items():
        if len(classes) < 2:
            continue
        mod = project.modules[path]
        mutables: dict = {}
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                v = node.value
                is_mut = isinstance(v, (ast.List, ast.Dict, ast.Set)) \
                    or (isinstance(v, ast.Call)
                        and dotted(v.func).split(".")[-1]
                        in _MUTABLE_CALLS)
                if is_mut:
                    mutables[node.targets[0].id] = node
        if not mutables:
            continue
        users: dict = {}
        for cls in classes:
            for node in cached_walk(cls):
                if isinstance(node, ast.Name) and isinstance(
                        node.ctx, ast.Load) and node.id in mutables:
                    users.setdefault(node.id, set()).add(cls.name)
        for name, classes_using in users.items():
            if len(classes_using) >= 2:
                node = mutables[name]
                findings.append(Finding(
                    rule="PAX105", file=path, line=node.lineno,
                    scope="<module>", detail=name,
                    message=f"module-level mutable {name!r} is "
                            f"referenced by actor classes "
                            f"{sorted(classes_using)}; shared state "
                            f"must flow through messages"))
    return findings


register_rules(RULES, check)
