"""PAX110: acceptor-set reads must flow through the epoch store.

Reconfig-wired roles (reconfig/, docs/RECONFIG.md) resolve acceptor
membership per SLOT through their ``EpochStore``; a handler that reads
the static config's acceptor lists (``config.acceptor_addresses``, the
``quorum_grid()`` factory) bypasses the store and silently pins the
pre-reconfiguration membership -- fanning proposals to dead members,
counting votes under the wrong spec, or recovering with the wrong
quorum after a handover.

The rule is SELF-SCOPING: it applies exactly to Actor subclasses that
assign ``self.epochs`` in ``__init__`` (the epoch-store-backed roles).
Roles of epoch-frozen protocols never assign the attribute and are
untouched. Flagged reads inside handlers (``receive``/``on_drain`` and
everything reachable from them, per the PAX1xx closure) must either
route through the store or carry a justifying
``# paxlint: disable=PAX110`` (e.g. the flexible-grid branch, the
one-shot dict-tracker migration).
"""

from __future__ import annotations

import ast

from frankenpaxos_tpu.analysis.actor_rules import (
    _actor_classes,
    _handler_closure,
)
from frankenpaxos_tpu.analysis.core import (
    cached_walk,
    dotted,
    Finding,
    focused,
    Project,
    register_rules,
)

RULES = {
    "PAX110": "acceptor-set/QuorumSpec read bypassing the epoch store "
              "in a protocol handler",
}

#: Attribute reads / calls that resolve acceptor membership outside
#: the store.
_BYPASS_ATTRS = ("acceptor_addresses",)
_BYPASS_CALLS = ("quorum_grid",)


def _assigns_epoch_store(cls: ast.ClassDef) -> bool:
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "__init__":
            for sub in cached_walk(node):
                targets = []
                if isinstance(sub, ast.Assign):
                    targets = sub.targets
                elif isinstance(sub, ast.AnnAssign):
                    targets = [sub.target]
                for target in targets:
                    if isinstance(target, ast.Attribute) \
                            and isinstance(target.value, ast.Name) \
                            and target.value.id == "self" \
                            and target.attr == "epochs":
                        return True
    return False


def check(project: Project):
    findings: list = []
    for mod, cls in _actor_classes(project):
        if not focused(project, mod.path):
            continue
        if not _assigns_epoch_store(cls):
            continue
        for name, func in _handler_closure(cls).items():
            scope = f"{cls.name}.{name}"
            for node in cached_walk(func):
                if isinstance(node, ast.Attribute) \
                        and node.attr in _BYPASS_ATTRS:
                    d = dotted(node)
                    findings.append(Finding(
                        rule="PAX110", file=mod.path,
                        line=node.lineno, scope=scope, detail=d,
                        message=f"handler reads {d}: acceptor "
                                f"membership must resolve through the "
                                f"epoch store (self.epochs) so "
                                f"committed reconfigurations reach "
                                f"every path"))
                elif isinstance(node, ast.Call) \
                        and dotted(node.func).split(".")[-1] \
                        in _BYPASS_CALLS:
                    d = dotted(node.func)
                    findings.append(Finding(
                        rule="PAX110", file=mod.path,
                        line=node.lineno, scope=scope, detail=d,
                        message=f"handler calls {d}(): quorum "
                                f"construction must resolve through "
                                f"the epoch store (self.epochs)"))
    return findings


register_rules(RULES, check)
