"""OWN11xx: buffer ownership & escape analysis for the zero-copy planes.

The zero-copy machinery -- paxwire's deferred writev flush backlog,
paxingest's column views over raw frame bytes, WAL raw-copy value
segments, ``LazyValueArray`` throughout the run pipeline -- shares one
invariant no other family checks: WHO owns a buffer, for HOW LONG, and
WHEN it may be mutated. Every rule here tracks the provenance of
buffer-typed values (``core.BUFFER_VIEW_CALLS``: ``scan_frames`` /
``fpx_ingest_scan`` / ``fpx_value_columns`` / ``memoryview`` / wire-sink
parser outputs / ``lazy_values`` segments, plus the ctypes export
calls) through local aliases, helper params (the callgraph's
``escaping_params`` fixpoint), and container stores.

  * OWN1101 -- a view over a transport receive buffer escapes its
    dispatch scope: stored on ``self``, closed over by a timer/resend
    callback, appended to a container that outlives the drain, or
    passed to a helper whose param escapes. The transport compacts and
    reuses the backing bytearray between drains, so the view silently
    goes stale (or pins the buffer).
  * OWN1102 -- payload/message bytes mutated AFTER being queued for a
    deferred send: paxwire flush-backlog entries and ``_wal_send``-held
    replies are read at writev/fsync time, not enqueue time, so
    in-place mutation after enqueue corrupts frames/records.
  * OWN1103 -- a mutable raw segment (``bytearray`` carved from wire
    ``_put_value_array`` output, an ingest canonical value segment, a
    WAL record payload) aliased into a SECOND long-lived structure
    without ``bytes()``/``copy()`` while some handler mutates one of
    them -- the ALIAS10xx idea lifted from message objects to byte
    planes.
  * OWN1104 -- a ``ctypes.from_buffer``/``cast`` export whose lifetime
    is not provably bounded: it escapes the function, or the backing
    buffer is resized/compacted while the export is live (no ``del``
    in between) -- the PR 8 BufferError/pinned-bytearray class.
  * OWN1105 -- a wire-sink parser output escaping the sink handler
    un-copied: the paxingest parsers document their column outputs as
    views over the frame payload (docs/TRANSPORT.md "ownership
    contract"), so staging one past the dispatch needs ``to_owned()``
    / ``bytes()`` first.

Scope: the zero-copy planes (``runtime/``, ``ingest/``, ``wal/``,
``native/``, ``serve/``, ``ops/``) plus protocol roles
(``protocols/``, ``reconfig/``, ``geo/``). Justified exceptions carry
``# paxlint: disable=OWN110x`` with the invariant that bounds the
lifetime (e.g. "callers del the export before any resize").
"""

from __future__ import annotations

import ast

from frankenpaxos_tpu.analysis.actor_rules import _methods
from frankenpaxos_tpu.analysis.callgraph import (
    _bound_param,
    _param_names,
    _passed_params,
    project_graph,
)
from frankenpaxos_tpu.analysis.core import (
    buffer_locals,
    BUFFER_VIEW_CALLS,
    cached_walk,
    call_name,
    dotted,
    Finding,
    focused,
    is_sanitizer_call,
    own_scope_walk,
    Project,
    qualname_index,
    register_rules,
)

RULES = {
    "OWN1101": "a view over a transport receive buffer escapes its "
               "dispatch scope (the backing bytearray is compacted "
               "and reused)",
    "OWN1102": "payload/message mutated after being queued for a "
               "deferred send (flush backlog / _wal_send holds are "
               "read at writev/fsync time)",
    "OWN1103": "a mutable raw segment aliased into a second "
               "long-lived structure without a copy while a handler "
               "mutates it",
    "OWN1104": "a ctypes buffer export whose lifetime is not bounded "
               "before buffer resize/compaction",
    "OWN1105": "a wire-sink parser output (documented as a view) "
               "escapes the sink handler un-copied",
}

_SCOPES = ("/runtime/", "/ingest/", "/wal/", "/native/", "/serve/",
           "/ops/", "/protocols/", "/reconfig/", "/geo/")

_SEND_NAMES = frozenset({"send", "send_no_flush", "_wal_send",
                         "broadcast", "send_batch"})

#: In-place mutators that CORRUPT a queued payload (consumption-style
#: mutators -- pop/clear/remove -- are how senders drain their own
#: staging lists and are deliberately not flagged).
_QUEUE_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "add", "insert",
    "update", "setdefault", "sort", "reverse",
})

#: Sources whose result is a MUTABLE raw segment (OWN1103): a fresh
#: bytearray, or an encoder that builds into one.
_RAW_SEGMENT_SOURCES = frozenset({
    "bytearray", "encode_value_array", "_put_value_array",
})

#: ctypes export constructors (OWN1104). ``from_buffer_copy`` copies
#: and is exempt; a ``cast`` of a constant (the null-pointer idiom)
#: is exempt at the call site.
_EXPORT_LEAVES = frozenset({"from_buffer", "cast", "_as_u8p_view"})


def _in_scope(path: str) -> bool:
    return any(seg in path for seg in _SCOPES)


def _functions(mod) -> list:
    """Every (qualname, node) def in the module, outermost first."""
    quals = qualname_index(mod.tree)
    return [(quals[id(n)], n) for n in cached_walk(mod.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _is_self_attr(node: ast.AST) -> bool:
    """``self.X`` / ``self.X[k]`` / deeper chains rooted at self."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in ("self", "cls"):
            return True
        node = node.value
    return False


def _mentions(expr: ast.AST, names: set) -> set:
    """Which of ``names`` does ``expr`` mention OUTSIDE an ownership
    sanitizer call (``bytes(v)``, ``v.tobytes()``, ``v.to_owned()``,
    ``rows.tolist()``...)?"""
    found: set = set()

    def visit(node):
        if is_sanitizer_call(node):
            return
        if isinstance(node, ast.Name) and node.id in names:
            found.add(node.id)
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(expr)
    return found


def _container_store_args(node: ast.AST):
    """If ``node`` is ``self.X.append(v)`` / extend / add /
    setdefault-style store into self state, yield (field expr, args)."""
    if isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr in ("append", "appendleft", "extend", "add",
                              "insert", "setdefault", "push") and \
            _is_self_attr(node.func.value):
        return node.args
    return ()


def _stmts_in_order(func: ast.AST) -> list:
    """Every statement inside ``func`` (excluding nested defs'
    bodies), in source order -- the straight-line approximation the
    after-enqueue rules use."""
    out: list = []

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(child, ast.stmt):
                out.append(child)
            visit(child)

    visit(func)
    out.sort(key=lambda s: s.lineno)
    return out


def _mutation_target(stmt: ast.stmt, mutators: frozenset) -> str | None:
    """The plain local name ``stmt`` mutates in place, if any:
    ``v.append(..)``, ``v[k] = ..``, ``v += ..``, ``del v[..]``."""
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        call = stmt.value
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr in mutators and \
                isinstance(call.func.value, ast.Name):
            return call.func.value.id
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            if isinstance(t, ast.Subscript) and \
                    isinstance(t.value, ast.Name):
                return t.value.id
    if isinstance(stmt, ast.AugAssign) and \
            isinstance(stmt.target, ast.Name):
        return stmt.target.id
    if isinstance(stmt, ast.AugAssign) and \
            isinstance(stmt.target, ast.Subscript) and \
            isinstance(stmt.target.value, ast.Name):
        return stmt.target.value.id
    if isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            if isinstance(t, ast.Subscript) and \
                    isinstance(t.value, ast.Name):
                return t.value.id
    return None


# --- OWN1101: receive-buffer views escaping the dispatch scope --------------


def _check_view_escapes(project, graph, escaping, mod, qual, func,
                        findings) -> None:
    views = buffer_locals(func, BUFFER_VIEW_CALLS)
    if not views:
        return
    names = set(views)

    def flag(node, name, why):
        src = views[name][0]
        findings.append(Finding(
            rule="OWN1101", file=mod.path, line=node.lineno,
            scope=qual, detail=f"{name}<-{src}",
            message=f"view '{name}' (from {src}) over a receive "
                    f"buffer {why}; the transport compacts/reuses "
                    f"the backing bytearray after the dispatch -- "
                    f"copy with bytes() before it outlives the "
                    f"drain"))

    info_ref = f"{mod.path}::{qual}"
    info = graph.funcs.get(info_ref)
    for node in own_scope_walk(func):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if _is_self_attr(target):
                    for name in _mentions(node.value, names):
                        flag(node, name, "is stored on self")
        elif isinstance(node, ast.Call):
            for arg in _container_store_args(node):
                for name in _mentions(arg, names):
                    flag(node, name,
                         "is appended to a container on self")
            leaf = call_name(node).split(".")[-1]
            if info is not None and leaf not in _SEND_NAMES and \
                    not is_sanitizer_call(node):
                passed = _passed_params(node, names)
                if passed:
                    for callee in graph.resolve_call(info, node):
                        if graph.funcs[callee].name in _SEND_NAMES:
                            continue
                        cp = _param_names(graph.funcs[callee].node)
                        for pos, kw, name in passed:
                            t = _bound_param(cp, pos, kw)
                            if t and t in escaping.get(callee, ()):
                                flag(node, name,
                                     f"escapes through helper "
                                     f"{graph.funcs[callee].name}() "
                                     f"(its '{t}' param is stored)")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)) and node is not func:
            for inner in cached_walk(node):
                if isinstance(inner, ast.Name) and inner.id in names:
                    flag(inner, inner.id,
                         "is captured by a nested callback closure")
                    break


# --- OWN1102: mutation after deferred-send enqueue --------------------------


def _all_unsanitized_names(expr: ast.AST) -> set:
    """Every plain name ``expr`` mentions outside a sanitizer call --
    the message itself, or any value embedded in its construction."""
    found: set = set()

    def visit(node):
        if is_sanitizer_call(node):
            return
        if isinstance(node, ast.Name):
            found.add(node.id)
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(expr)
    return found


def _check_queued_mutation(mod, qual, func, findings) -> None:
    stmts = _stmts_in_order(func)
    mutable = set(buffer_locals(func, BUFFER_VIEW_CALLS)) | \
        set(buffer_locals(func, _RAW_SEGMENT_SOURCES))
    queued: dict = {}  # name -> (send leaf, line)
    for stmt in stmts:
        for node in cached_walk(stmt):
            if isinstance(node, ast.Call):
                leaf = call_name(node).split(".")[-1]
                if leaf in _SEND_NAMES:
                    # Skip the destination arg of send(dst, msg)-shaped
                    # calls; everything reachable from the message arg
                    # is held by reference until the flush/fsync.
                    args = node.args[1:] if len(node.args) > 1 \
                        else node.args
                    for arg in args:
                        for name in _all_unsanitized_names(arg):
                            queued.setdefault(
                                name, (leaf, node.lineno))
        target = _mutation_target(stmt, _QUEUE_MUTATORS)
        if target is not None and target in queued:
            leaf, line = queued[target]
            if isinstance(stmt, ast.AugAssign) and \
                    target not in mutable:
                # ``buf += ...`` on immutable bytes REBINDS -- only a
                # provenly-mutable buffer mutates in place.
                continue
            findings.append(Finding(
                rule="OWN1102", file=mod.path, line=stmt.lineno,
                scope=qual, detail=f"{target}@{leaf}",
                message=f"'{target}' is mutated after being queued "
                        f"for deferred send via {leaf}() at line "
                        f"{line}; backlog entries are read at "
                        f"writev/fsync time, not enqueue time -- "
                        f"queue a copy or build a fresh buffer"))


# --- OWN1103: raw segments double-aliased into mutated state ----------------


def _check_segment_aliasing(mod, cls, findings) -> None:
    methods = _methods(cls)
    mutated_fields: set = set()
    for func in methods.values():
        for node in cached_walk(func):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _QUEUE_MUTATORS and \
                    _is_self_attr(node.func.value):
                field = _self_root_field(node.func.value)
                if field:
                    mutated_fields.add(field)
            elif isinstance(node, (ast.AugAssign, ast.Assign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) and \
                            _is_self_attr(t.value):
                        field = _self_root_field(t.value)
                        if field:
                            mutated_fields.add(field)
    for name, func in methods.items():
        segments = buffer_locals(func, _RAW_SEGMENT_SOURCES)
        if not segments:
            continue
        stores: dict = {}  # local -> [(field, node)]
        for node in cached_walk(func):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if _is_self_attr(target):
                        field = _self_root_field(target)
                        for local in _mentions(node.value,
                                               set(segments)):
                            stores.setdefault(local, []).append(
                                (field, node))
            elif isinstance(node, ast.Call):
                for arg in _container_store_args(node):
                    field = _self_root_field(node.func.value)
                    for local in _mentions(arg, set(segments)):
                        stores.setdefault(local, []).append(
                            (field, node))
        for local, sites in stores.items():
            if len(sites) < 2:
                continue
            fields = {f for f, _ in sites if f}
            if not (fields & mutated_fields):
                continue
            src = segments[local][0]
            node = sites[1][1]
            findings.append(Finding(
                rule="OWN1103", file=mod.path, line=node.lineno,
                scope=f"{cls.name}.{name}",
                detail=f"{local}<-{src}",
                message=f"mutable raw segment '{local}' (from {src}) "
                        f"is aliased into {len(sites)} long-lived "
                        f"structures ({', '.join(sorted(fields))}) "
                        f"and a handler mutates "
                        f"{', '.join(sorted(fields & mutated_fields))}"
                        f" -- store a bytes() copy so the aliases "
                        f"cannot diverge"))


def _self_root_field(node: ast.AST) -> str | None:
    """The field name X of a ``self.X...`` chain."""
    field = None
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and \
                    node.value.id in ("self", "cls"):
                return node.attr
            field = node.attr
        node = node.value
    return field


# --- OWN1104: unbounded ctypes exports --------------------------------------


def _export_bindings(func: ast.AST) -> dict:
    """name -> (backing buffer name or None, line) for locals bound to
    a ctypes export call (incl. tuple-unpacked keepalive pairs)."""
    out: dict = {}
    for node in own_scope_walk(func):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        call = node.value
        leaf = call_name(call).split(".")[-1]
        if leaf not in _EXPORT_LEAVES:
            continue
        if leaf == "cast" and call.args and \
                isinstance(call.args[0], ast.Constant):
            continue  # the null-pointer idiom: cast(0, ...)
        backing = None
        if call.args and isinstance(call.args[0], ast.Name):
            backing = call.args[0].id
        names = []
        target = node.targets[0] if len(node.targets) == 1 else None
        if isinstance(target, ast.Name):
            names = [target.id]
        elif isinstance(target, ast.Tuple):
            names = [e.id for e in target.elts
                     if isinstance(e, ast.Name)]
        for n in names:
            out[n] = (backing, node.lineno)
    return out


def _check_ctypes_exports(mod, qual, func, findings) -> None:
    exports = _export_bindings(func)
    direct_return = None
    for node in own_scope_walk(func):
        if isinstance(node, ast.Return) and \
                isinstance(node.value, (ast.Call, ast.Tuple)):
            for sub in cached_walk(node.value):
                if isinstance(sub, ast.Call):
                    leaf = call_name(sub).split(".")[-1]
                    if leaf in _EXPORT_LEAVES and not (
                            leaf == "cast" and sub.args and
                            isinstance(sub.args[0], ast.Constant)):
                        direct_return = node
                        break
    names = set(exports)

    def flag(node, name, why):
        findings.append(Finding(
            rule="OWN1104", file=mod.path, line=node.lineno,
            scope=qual, detail=name,
            message=f"ctypes buffer export {name} {why}; a live "
                    f"export pins the bytearray (resize raises "
                    f"BufferError) or dangles after reallocation -- "
                    f"del it before the buffer can resize, or "
                    f"from_buffer_copy()"))

    if direct_return is not None:
        flag(direct_return, "<return value>",
             "is returned without a lifetime bound")
    if not names:
        return
    # (a) escapes: returned / stored on self / appended.
    for node in own_scope_walk(func):
        if isinstance(node, ast.Return) and node.value is not None:
            for name in _mentions(node.value, names):
                flag(node, f"'{name}'", "is returned without a "
                     "lifetime bound")
        elif isinstance(node, ast.Assign):
            for target in node.targets:
                if _is_self_attr(target):
                    for name in _mentions(node.value, names):
                        flag(node, f"'{name}'", "is stored on self")
        elif isinstance(node, ast.Call):
            for arg in _container_store_args(node):
                for name in _mentions(arg, names):
                    flag(node, f"'{name}'",
                         "is appended to a container on self")
    # (b) the backing buffer is resized while the export is live.
    live: dict = dict(exports)  # name -> (backing, line)
    for stmt in _stmts_in_order(func):
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    live.pop(t.id, None)
            continue
        target = _mutation_target(stmt, frozenset(
            {"extend", "append", "clear", "pop", "resize"}))
        if target is None:
            continue
        for name, (backing, line) in list(live.items()):
            if backing == target and stmt.lineno > line:
                flag(stmt, f"'{name}'",
                     f"is still live (bound at line {line}) when its "
                     f"backing buffer '{backing}' is resized")
                live.pop(name)


# --- OWN1105: sink parser outputs escaping the sink handler -----------------


def _wire_sink_handlers(cls: ast.ClassDef) -> set:
    """Method names registered as wire-sink handlers:
    ``wire_sinks = {TAG: (parser, self._handle_x)}`` (or the handler
    directly as the value)."""
    out: set = set()
    for node in cached_walk(cls):
        target_ok = False
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Attribute) and
                        t.attr == "wire_sinks") or \
                        (isinstance(t, ast.Name) and
                         t.id == "wire_sinks"):
                    target_ok = True
        if not target_ok or not isinstance(node.value, ast.Dict):
            continue
        for value in node.value.values:
            exprs = value.elts if isinstance(value, ast.Tuple) \
                else [value]
            for e in exprs:
                if isinstance(e, ast.Attribute) and \
                        isinstance(e.value, ast.Name) and \
                        e.value.id == "self":
                    out.add(e.attr)
    return out


def _check_sink_escapes(project, graph, escaping, mod, cls,
                        findings) -> None:
    handlers = _wire_sink_handlers(cls)
    if not handlers:
        return
    methods = _methods(cls)
    for hname in sorted(handlers):
        func = methods.get(hname)
        if func is None:
            continue
        # The transport calls a sink handler as ``handler(src,
        # parsed)``: only the LAST param is the parser output (src is
        # an address, not a buffer).
        all_params = _param_names(func)
        params = set(all_params[-1:])
        if not params:
            continue
        qual = f"{cls.name}.{hname}"
        info = graph.funcs.get(f"{mod.path}::{qual}")

        def flag(node, name, why):
            findings.append(Finding(
                rule="OWN1105", file=mod.path, line=node.lineno,
                scope=qual, detail=name,
                message=f"wire-sink parser output '{name}' {why}; "
                        f"sink parser outputs are views over the "
                        f"frame payload (docs/TRANSPORT.md ownership "
                        f"contract) -- copy (to_owned()/bytes()) "
                        f"before it outlives the dispatch"))

        for node in cached_walk(func):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if _is_self_attr(target):
                        for name in _mentions(node.value, params):
                            flag(node, name, "is stored on self")
            elif isinstance(node, ast.Call):
                for arg in _container_store_args(node):
                    for name in _mentions(arg, params):
                        flag(node, name,
                             "is staged in a container that outlives "
                             "the dispatch")
                leaf = call_name(node).split(".")[-1]
                if info is not None and leaf not in _SEND_NAMES and \
                        not is_sanitizer_call(node):
                    passed = _passed_params(node, params)
                    for callee in (graph.resolve_call(info, node)
                                   if passed else ()):
                        if graph.funcs[callee].name in _SEND_NAMES:
                            continue
                        cp = _param_names(graph.funcs[callee].node)
                        for pos, kw, name in passed:
                            t = _bound_param(cp, pos, kw)
                            if t and t in escaping.get(callee, ()):
                                flag(node, name,
                                     f"escapes through helper "
                                     f"{graph.funcs[callee].name}()")
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.Lambda)) \
                    and node is not func:
                for inner in cached_walk(node):
                    if isinstance(inner, ast.Name) and \
                            inner.id in params:
                        flag(inner, inner.id,
                             "is captured by a nested callback "
                             "closure")
                        break


# --- the checker ------------------------------------------------------------


def check(project: Project):
    findings: list = []
    graph = project_graph(project)
    escaping = graph.escaping_params()
    for mod in project:
        if not _in_scope(mod.path) or not focused(project, mod.path):
            continue
        for qual, func in _functions(mod):
            _check_view_escapes(project, graph, escaping, mod, qual,
                                func, findings)
            _check_queued_mutation(mod, qual, func, findings)
            _check_ctypes_exports(mod, qual, func, findings)
        for node in cached_walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                _check_segment_aliasing(mod, node, findings)
                _check_sink_escapes(project, graph, escaping, mod,
                                    node, findings)
    return findings


register_rules(RULES, check)
