"""paxflow: whole-program role x message flow graphs, one per protocol.

Every protocol package under ``protocols/`` is a *unit*: either a
directory package (``multipaxos/``) or a single module plus its
optional ``<name>_wire.py`` sibling. For each unit this module
recovers, by pure AST analysis (nothing imported or executed):

  * **roles** -- every ``Actor`` subclass (name-based base-chain walk,
    like the rest of paxlint);
  * **messages** -- the unit's wire dataclasses, plus any shared
    message (reconfig/, serve/) its roles send or handle;
  * **send edges** -- ``send`` / ``send_no_flush`` / ``broadcast`` /
    ``_wal_send`` call sites, resolved through direct construction,
    function-local aliases, sender-helper parameter flow
    (``self._send_to_owning_leaders(Recover(...), slot)``), factory
    parameters (craq's ``self._start(pseudonym, lambda cid:
    Write(...), ...)``), ``dataclasses.replace`` of a known message,
    typed forwarding of handler parameters (annotations and
    ``isinstance`` narrowing), and unbatch loops (``for reply in
    batch.batch: self.send(...)`` typed through the container field's
    element annotation); messages constructed *inside* another sent
    message (``TailRead(ReadBatch(...))``) get a ``payload`` edge --
    they cross the wire, but as nested payload;
  * **receive edges** -- ``isinstance`` dispatch chains, dispatch
    tables (dict or ``(Class, label, handler)`` lists), and parameter
    annotations, tracked along the *message-parameter flow* from
    ``receive`` so payload-struct ``isinstance`` tests (a replica
    walking its log) don't read as wire handlers;
  * **origins** -- whether a send fires from a ``receive`` handler, the
    ``on_drain`` boundary, a transport timer callback (resends), or a
    construction/API path;
  * **codec tags** -- the wire-codec registry entries resolved to the
    unit's messages (reusing codec_rules' import-accurate resolution).

The graph is the machine-checked form of "which role sends which
message to whom, and what replies": FLOW4xx (flow_rules.py) and DUR5xx
(durability_rules.py) gate on it in CI, and the committed
``docs/flowgraphs/*.json`` + ``.dot`` artifacts are the per-protocol
porting checklist for the run-pipeline unification refactor
(ROADMAP.md). JSON emission is deterministic (sorted keys, sorted
edge lists) so the artifacts are diff-stable.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os

from frankenpaxos_tpu.analysis import codec_rules
from frankenpaxos_tpu.analysis.core import (
    cached_walk,
    dotted,
    import_aliases,
    Module,
    Project,
    qualname_index,
)

#: Send entry points (the actor API plus the durable deferred-send
#: alias). Values classify the edge kind in the emitted graph.
SEND_KINDS = {
    "send": "send",
    "send_no_flush": "send",
    "broadcast": "broadcast",
    "_wal_send": "wal_send",
}

#: Protocol-tree modules that are not protocol units of their own.
_NON_UNIT_STEMS = frozenset({"__init__", "driver_util", "baseline_wire"})

#: Dataclass-name suffixes that are configuration, not wire messages.
_NON_MESSAGE_SUFFIXES = ("Config", "Options")

#: The role scans walk the same function bodies once per extraction
#: pass; the shared memo turns repeat traversals into list iteration.
_walk = cached_walk


def _unwrap_replace(arg: ast.AST) -> ast.AST:
    """See through ``dataclasses.replace(msg, ...)``: the sent value
    has the first argument's message type."""
    while isinstance(arg, ast.Call) \
            and dotted(arg.func).split(".")[-1] == "replace" \
            and arg.args:
        arg = arg.args[0]
    return arg


@dataclasses.dataclass
class MessageInfo:
    name: str
    module: str                # defining module path
    line: int
    external: bool             # defined outside the unit (reconfig/serve)
    codec_tag: int | None = None
    # role name -> set of edge kinds ("send"/"broadcast"/"wal_send")
    senders: dict = dataclasses.field(default_factory=dict)
    # role name -> set of handler function qualnames
    handlers: dict = dataclasses.field(default_factory=dict)
    # (module path, line) per send site, for findings
    send_sites: list = dataclasses.field(default_factory=list)
    # origins of send sites: subset of {handler, drain, timer, api}
    send_origins: set = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class RoleInfo:
    name: str
    module: str
    line: int
    handles: set = dataclasses.field(default_factory=set)
    sends: set = dataclasses.field(default_factory=set)
    # handler function qualname -> set of message names it dispatches
    handler_funcs: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class FlowGraph:
    unit: str
    modules: list
    roles: dict                # name -> RoleInfo
    messages: dict             # name -> MessageInfo

    def edges(self) -> list:
        """(sender role, message, handler role, kind) tuples, sorted.
        Handler role "?" marks a message sent but handled by no role in
        the unit (cross-unit or dead -- FLOW401's surface). Payload
        kinds (nested constructions) with no handler are omitted:
        every protocol nests Command/CommandId inside its requests,
        and those structs are decoded by the outer codec, not
        dispatched."""
        out = []
        for name in sorted(self.messages):
            info = self.messages[name]
            handlers = sorted(info.handlers) or ["?"]
            for sender in sorted(info.senders):
                for kind in sorted(info.senders[sender]):
                    for h in handlers:
                        if kind == "payload" and h == "?":
                            continue
                        out.append((sender, name, h, kind))
        return out


# --- unit discovery ---------------------------------------------------------


def unit_modules(project: Project) -> dict:
    """{unit name: [Module, ...]} for every protocol unit."""
    units: dict = {}
    base = f"{project.package}/protocols/"
    for mod in project:
        if not mod.path.startswith(base):
            continue
        rest = mod.path[len(base):]
        if "/" in rest:
            unit = rest.split("/", 1)[0]
        else:
            stem = rest[:-len(".py")]
            if stem in _NON_UNIT_STEMS:
                continue
            unit = stem[:-len("_wire")] if stem.endswith("_wire") else stem
        units.setdefault(unit, []).append(mod)
    return {unit: sorted(mods, key=lambda m: m.path)
            for unit, mods in sorted(units.items())}


def _class_index(project: Project) -> dict:
    """class name -> [(Module, ClassDef)] across the whole project.
    Cached on the project (three rule families consult it)."""
    cached = getattr(project, "_flow_class_index", None)
    if cached is not None:
        return cached
    out: dict = {}
    for mod in project:
        for node in _walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                out.setdefault(node.name, []).append((mod, node))
    project._flow_class_index = out
    return out


def _module_namespace(project: Project, mod: Module) -> "_Namespace":
    """A single-module _Namespace, cached on the project -- the
    project-wide scans (sends, handlers, durability) all need one per
    module and must not rebuild the import resolution each time."""
    cache = getattr(project, "_flow_mod_ns", None)
    if cache is None:
        cache = project._flow_mod_ns = {}
    ns = cache.get(mod.path)
    if ns is None:
        ns = cache[mod.path] = _Namespace(project, [mod])
    return ns


def _is_actor(name: str, classes: dict, seen: set | None = None) -> bool:
    """Does class ``name``'s base chain (name-keyed, project-wide)
    reach ``Actor``?"""
    if name == "Actor":
        return True
    seen = seen or set()
    if name in seen or name not in classes:
        return False
    seen.add(name)
    for _, node in classes[name]:
        for base in node.bases:
            if _is_actor(dotted(base).split(".")[-1], classes, seen):
                return True
    return False


def _is_message_class(node: ast.ClassDef) -> bool:
    if not codec_rules._is_dataclass(node):
        return False
    if node.name.startswith("_"):
        return False
    return not node.name.endswith(_NON_MESSAGE_SUFFIXES)


# --- per-unit message namespace ---------------------------------------------


class _Namespace:
    """Message-class resolution for one unit: local definitions plus
    imports of dataclasses from elsewhere in the project (reconfig/,
    serve/, a sibling protocol)."""

    def __init__(self, project: Project, mods: list):
        self.project = project
        self.unit_paths = {m.path for m in mods}
        # name -> (Module, ClassDef) for unit-defined messages.
        self.local: dict = {}
        for mod in mods:
            for node in _walk(mod.tree):
                if isinstance(node, ast.ClassDef) \
                        and _is_message_class(node):
                    self.local.setdefault(node.name, (mod, node))
        # per-module import resolution cache: path -> {name: (mod, cls)}
        self._imported: dict = {}
        for mod in mods:
            table: dict = {}
            for alias, target in import_aliases(
                    mod.tree, mod.name).items():
                if "." not in target:
                    continue
                found = self._resolve_imported(target)
                if found is not None and _is_message_class(found[1]):
                    table[alias] = found
            self._imported[mod.path] = table

    def _resolve_imported(self, qualified: str):
        cache = getattr(self.project, "_flow_import_cache", None)
        if cache is None:
            cache = self.project._flow_import_cache = {}
        if qualified in cache:
            return cache[qualified]
        result = None
        parts = qualified.split(".")
        for split in range(len(parts) - 1, 0, -1):
            mod = self.project.by_name.get(".".join(parts[:split]))
            if mod is not None and split == len(parts) - 1:
                result = codec_rules._class_in_module(
                    self.project, mod, parts[-1])
                break
        cache[qualified] = result
        return result

    def resolve(self, mod: Module, name: str):
        """(Module, ClassDef) for a message-class reference ``name``
        as written in ``mod``; None when it isn't a message class."""
        leaf = name.split(".")[-1]
        table = self._imported.get(mod.path, {})
        if leaf in table:
            return table[leaf]
        if leaf in self.local:
            return self.local[leaf]
        return None

    def field_elem(self, found, field: str):
        """(Module, ClassDef) of the element type of a container
        field (``batch: tuple[ClientReply, ...]``) on the resolved
        message class ``found``; None when the annotation names no
        message class. Drives the unbatch-loop idiom (``for reply in
        message.batch: self.send(dst, reply)``)."""
        def_mod, cls = found
        for node in cls.body:
            if not (isinstance(node, ast.AnnAssign)
                    and isinstance(node.target, ast.Name)
                    and node.target.id == field):
                continue
            for sub in ast.walk(node.annotation):
                if isinstance(sub, (ast.Name, ast.Attribute)):
                    d = dotted(sub)
                    if d.split(".")[-1] in ("tuple", "Tuple", "list",
                                            "List", "frozenset", "set",
                                            "Optional", "Sequence"):
                        continue
                    hit = self.resolve(def_mod, d) if d else None
                    if hit is None and d:
                        hit = self.local.get(d.split(".")[-1])
                    if hit is not None:
                        return hit
        return None


# --- per-role extraction ----------------------------------------------------


class _RoleScan:
    """One Actor subclass: methods, the self-call graph, the message-
    parameter flow from receive, timer callbacks, and send sites."""

    def __init__(self, ns: _Namespace, mod: Module, cls: ast.ClassDef,
                 quals: dict):
        self.ns = ns
        self.mod = mod
        self.cls = cls
        self.quals = quals
        self.methods: dict = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        # method name -> called self-method names
        self.self_calls: dict = {
            name: self._called_methods(fn)
            for name, fn in self.methods.items()}
        # method name -> set of its params that reach a send call's
        # message position (sender helpers), computed to fixpoint.
        self.sender_params: dict = self._sender_params()
        # method name -> params CALLED with the result sent (factory
        # parameters: craq's ``_start(pseudonym, make_request, ...)``).
        self.factory_params: dict = self._factory_params()
        # method name -> message-parameter name (param-flow closure
        # from receive; the dispatch surface for handler extraction)
        self.msg_params: dict = self._message_params()
        # methods registered as transport timer callbacks
        self.timer_callbacks: set = self._timer_callbacks()
        # origin classification roots
        self.origins: dict = self._origins()

    # -- plumbing --
    def _called_methods(self, fn) -> set:
        out = set()
        for node in _walk(fn):
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                parts = d.split(".")
                if len(parts) == 2 and parts[0] in ("self", "cls") \
                        and parts[1] in self.methods:
                    out.add(parts[1])
        return out

    def _closure(self, roots) -> set:
        seen: set = set()
        stack = [r for r in roots if r in self.methods]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self.self_calls.get(cur, ()))
        return seen

    @staticmethod
    def _params(fn) -> list:
        return [a.arg for a in fn.args.args if a.arg != "self"]

    def _sender_params(self) -> dict:
        """Fixpoint: params of each method that flow into the message
        position of a send (directly, or via another sender helper)."""
        flows: dict = {name: set() for name in self.methods}
        changed = True
        while changed:
            changed = False
            for name, fn in self.methods.items():
                params = set(self._params(fn))
                for node in _walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    d = dotted(node.func).split(".")
                    leaf = d[-1]
                    if leaf in SEND_KINDS:
                        for arg in node.args:
                            if isinstance(arg, ast.Name) \
                                    and arg.id in params \
                                    and arg.id not in flows[name]:
                                flows[name].add(arg.id)
                                changed = True
                    elif len(d) == 2 and d[0] == "self" \
                            and d[1] in self.methods:
                        callee_params = self._params(self.methods[d[1]])
                        for pos, arg in enumerate(node.args):
                            if pos < len(callee_params) \
                                    and callee_params[pos] \
                                    in flows[d[1]] \
                                    and isinstance(arg, ast.Name) \
                                    and arg.id in params \
                                    and arg.id not in flows[name]:
                                flows[name].add(arg.id)
                                changed = True
        return flows

    def _factory_params(self) -> dict:
        """Params whose CALL RESULT reaches a send's message position:
        directly (``send(dst, make(...))``) or via a local
        (``request = make(cid); ... send(dst, request)``). Lambda
        arguments bound to these params at call sites carry messages."""
        out: dict = {name: set() for name in self.methods}
        for name, fn in self.methods.items():
            params = set(self._params(fn))
            sent_locals: set = set()
            for node in _walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if dotted(node.func).split(".")[-1] not in SEND_KINDS:
                    continue
                for arg in node.args:
                    arg = _unwrap_replace(arg)
                    if isinstance(arg, ast.Name):
                        sent_locals.add(arg.id)
                    elif isinstance(arg, ast.Call) \
                            and isinstance(arg.func, ast.Name) \
                            and arg.func.id in params:
                        out[name].add(arg.func.id)
            for node in _walk(fn):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call) \
                        and isinstance(node.value.func, ast.Name) \
                        and node.value.func.id in params:
                    for t in node.targets:
                        if isinstance(t, ast.Name) \
                                and t.id in sent_locals:
                            out[name].add(node.value.func.id)
        return out

    def _message_params(self) -> dict:
        """{method name: message param name} along the receive flow.

        ``receive(self, src, message)`` seeds the flow; a call that
        passes the current message param positionally extends it to
        the callee's matching parameter. Dispatch-table handler values
        (``{Klass: self._f}`` / ``[(Klass, label, self._f)]``) get
        their LAST parameter, matching the (src, message) convention.
        """
        out: dict = {}
        recv = self.methods.get("receive")
        if recv is None:
            return out
        params = self._params(recv)
        if not params:
            return out
        out["receive"] = params[-1]
        stack = ["receive"]
        while stack:
            cur = stack.pop()
            fn = self.methods[cur]
            msg = out[cur]
            for node in _walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func).split(".")
                if len(d) == 2 and d[0] == "self" \
                        and d[1] in self.methods and d[1] not in out:
                    callee_params = self._params(self.methods[d[1]])
                    for pos, arg in enumerate(node.args):
                        if isinstance(arg, ast.Name) and arg.id == msg \
                                and pos < len(callee_params):
                            out[d[1]] = callee_params[pos]
                            stack.append(d[1])
            for table_cls, handler in self._dispatch_entries(fn):
                if handler in self.methods and handler not in out:
                    callee_params = self._params(self.methods[handler])
                    if callee_params:
                        out[handler] = callee_params[-1]
                        stack.append(handler)
        return out

    def _dispatch_entries(self, fn):
        """(class dotted name, self-method name | None) pairs from
        dispatch tables: dict literals ``{Klass: self._f}`` and
        list/tuple literals ``(Klass, ..., self._f)``. A lambda value
        (``Phase2aAnyAck: lambda s, m: None`` -- an explicit ack sink)
        yields None: the message is handled, by the enclosing method."""
        for node in _walk(fn):
            if isinstance(node, ast.Dict):
                for key, value in zip(node.keys, node.values):
                    k = dotted(key) if key is not None else ""
                    if not k:
                        continue
                    if isinstance(value, ast.Lambda):
                        yield k, None
                        continue
                    v = dotted(value).split(".")
                    if len(v) == 2 and v[0] == "self":
                        yield k, v[1]
            elif isinstance(node, (ast.Tuple, ast.List)) \
                    and len(node.elts) >= 2:
                k = dotted(node.elts[0])
                if not k:
                    continue
                if isinstance(node.elts[-1], ast.Lambda):
                    yield k, None
                    continue
                v = dotted(node.elts[-1]).split(".")
                if len(v) == 2 and v[0] == "self":
                    yield k, v[1]

    def _timer_callbacks(self) -> set:
        out: set = set()
        for fn in self.methods.values():
            for node in _walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if dotted(node.func).split(".")[-1] != "timer":
                    continue
                for arg in list(node.args) + [
                        kw.value for kw in node.keywords]:
                    d = dotted(arg).split(".")
                    if len(d) == 2 and d[0] == "self" \
                            and d[1] in self.methods:
                        out.add(d[1])
                    elif isinstance(arg, ast.Lambda):
                        for sub in ast.walk(arg.body):
                            if isinstance(sub, ast.Call):
                                sd = dotted(sub.func).split(".")
                                if len(sd) == 2 and sd[0] == "self" \
                                        and sd[1] in self.methods:
                                    out.add(sd[1])
        return out

    def _origins(self) -> dict:
        """{method name: set of origins} -- which execution context
        reaches each method (handler / drain / timer / api)."""
        out: dict = {name: set() for name in self.methods}
        roots = [("handler", ["receive"]
                  + [m for m in self.msg_params if m != "receive"]),
                 ("drain", ["on_drain"]),
                 ("timer", sorted(self.timer_callbacks))]
        rooted: set = set()
        for origin, seeds in roots:
            closure = self._closure(seeds)
            rooted |= closure
            for name in closure:
                out[name].add(origin)
        for name in self.methods:
            if name not in rooted:
                out[name].add("api")
        return out

    # -- extraction --
    def handled(self) -> dict:
        """{message name: set of handler method qualnames}."""
        out: dict = {}

        def note(clsname: str, fn_name: str):
            found = self.ns.resolve(self.mod, clsname)
            if found is None:
                return
            qual = f"{self.cls.name}.{fn_name}"
            out.setdefault(found[1].name, set()).add(qual)

        for fn_name, msg_param in self.msg_params.items():
            fn = self.methods[fn_name]
            # Annotation of the message parameter itself.
            for a in fn.args.args:
                if a.arg == msg_param and a.annotation is not None:
                    ann = dotted(a.annotation)
                    if ann:
                        note(ann, fn_name)
            for node in _walk(fn):
                if isinstance(node, ast.Call) \
                        and dotted(node.func) == "isinstance" \
                        and len(node.args) == 2 \
                        and isinstance(node.args[0], ast.Name) \
                        and node.args[0].id == msg_param:
                    target = node.args[1]
                    elts = target.elts if isinstance(
                        target, ast.Tuple) else [target]
                    for e in elts:
                        d = dotted(e)
                        if d:
                            note(d, fn_name)
                elif isinstance(node, ast.Compare) \
                        and isinstance(node.left, ast.Call) \
                        and dotted(node.left.func) == "type" \
                        and len(node.left.args) == 1 \
                        and isinstance(node.left.args[0], ast.Name) \
                        and node.left.args[0].id == msg_param:
                    for comp in node.comparators:
                        d = dotted(comp)
                        if d:
                            note(d, fn_name)
            for table_cls, handler in self._dispatch_entries(fn):
                target = handler if handler in self.methods else fn_name
                note(table_cls, target)
        return out

    def sent(self) -> list:
        """(message name, kind, origin set, module path, line) per
        send site. Kind ``payload`` marks a message constructed inside
        another sent message's expression (nested wire payload)."""
        out: list = []
        for fn_name, fn in self.methods.items():
            origins = self.origins.get(fn_name, {"api"})
            local_types = self._local_message_types(fn)
            typed = self._typed_params(fn)
            self._add_unbatch_types(fn, local_types, typed)
            timer_spans = self._local_timer_spans(fn)

            def site_origins(node):
                # A send inside a nested def registered as a timer
                # callback fires when the TIMER fires (resend loops).
                for lo, hi in timer_spans:
                    if lo <= node.lineno <= hi:
                        return {"timer"}
                return origins

            def emit(arg, node, kind):
                for name, nested in self._arg_message(
                        arg, local_types, typed):
                    out.append((name, "payload" if nested else kind,
                                site_origins(node), self.mod.path,
                                node.lineno))

            for node in _walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func).split(".")
                leaf = d[-1]
                if leaf in SEND_KINDS:
                    for arg in node.args:
                        emit(arg, node, SEND_KINDS[leaf])
                elif len(d) == 2 and d[0] == "self" \
                        and d[1] in self.methods:
                    flows = self.sender_params.get(d[1], set())
                    factories = self.factory_params.get(d[1], set())
                    callee_params = self._params(self.methods[d[1]])
                    for pos, arg in enumerate(node.args):
                        if pos >= len(callee_params):
                            break
                        if callee_params[pos] in flows:
                            emit(arg, node, "send")
                        if callee_params[pos] in factories \
                                and isinstance(arg, ast.Lambda):
                            emit(arg.body, node, "send")
        return out

    def _local_timer_spans(self, fn) -> list:
        """(lineno, end_lineno) spans of nested defs registered as
        transport timer callbacks inside ``fn`` -- the ubiquitous
        client idiom ``def resend(): self.send(...)`` +
        ``self.timer(..., resend)``."""
        nested = {n.name: n for n in _walk(fn)
                  if isinstance(n, ast.FunctionDef) and n is not fn}
        spans: list = []
        for node in _walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if dotted(node.func).split(".")[-1] != "timer":
                continue
            for arg in list(node.args) + [
                    kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in nested:
                    d = nested[arg.id]
                    spans.append((d.lineno,
                                  getattr(d, "end_lineno", d.lineno)))
        return spans

    def _arg_message(self, arg, local_types: dict, typed: dict):
        """(message name, nested) pairs an argument expression may
        carry: the outer value itself, plus any message constructed
        inside it (wire payload of the outer message)."""
        outer: set = set()
        top = _unwrap_replace(arg)
        if isinstance(top, ast.Call):
            found = self.ns.resolve(self.mod, dotted(top.func))
            if found is not None:
                outer.add(found[1].name)
        elif isinstance(top, ast.Name):
            if top.id in local_types:
                outer.add(local_types[top.id])
            outer |= typed.get(top.id, set())
        for name in sorted(outer):
            yield name, False
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Call):
                found = self.ns.resolve(self.mod, dotted(sub.func))
                if found is not None and found[1].name not in outer:
                    yield found[1].name, True

    def _local_message_types(self, fn) -> dict:
        """{local var: message name} for vars assigned a constructed
        message in this function."""
        out: dict = {}
        for node in _walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                found = self.ns.resolve(self.mod,
                                        dotted(node.value.func))
                if found is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            out[t.id] = found[1].name
        return out

    def _typed_params(self, fn) -> dict:
        """{name: set of message names} from parameter annotations and
        flow-insensitive ``isinstance`` narrowing (typed forwarding: a
        handler re-sending or unbatching its own inbound message)."""
        out: dict = {}
        for a in fn.args.args:
            if a.annotation is None or a.arg == "self":
                continue
            found = self.ns.resolve(self.mod, dotted(a.annotation))
            if found is not None:
                out.setdefault(a.arg, set()).add(found[1].name)
        for node in _walk(fn):
            if isinstance(node, ast.Call) \
                    and dotted(node.func) == "isinstance" \
                    and len(node.args) == 2 \
                    and isinstance(node.args[0], ast.Name):
                target = node.args[1]
                elts = target.elts if isinstance(
                    target, ast.Tuple) else [target]
                for e in elts:
                    found = self.ns.resolve(self.mod, dotted(e))
                    if found is not None:
                        out.setdefault(node.args[0].id, set()).add(
                            found[1].name)
        return out

    def _add_unbatch_types(self, fn, local_types: dict,
                           typed: dict) -> None:
        """Type for-loop targets iterating (a) a known message's
        container field through the field's element annotation (the
        proxy unbatch idiom: ``for reply in message.batch:
        send(...)``) or (b) a local list typed by annotation
        (``replies: list[ClientReply] = []``) or by what gets
        ``.append``-ed to it."""
        local_elems: dict = {}
        for node in _walk(fn):
            if isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                for sub in ast.walk(node.annotation):
                    if isinstance(sub, (ast.Name, ast.Attribute)):
                        found = self.ns.resolve(self.mod, dotted(sub))
                        if found is not None:
                            local_elems.setdefault(
                                node.target.id, set()).add(
                                found[1].name)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "append" \
                    and isinstance(node.func.value, ast.Name) \
                    and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Call):
                    found = self.ns.resolve(self.mod,
                                            dotted(arg.func))
                    if found is not None:
                        local_elems.setdefault(
                            node.func.value.id, set()).add(
                            found[1].name)
        for node in _walk(fn):
            if not isinstance(node, ast.For) \
                    or not isinstance(node.target, ast.Name):
                continue
            if isinstance(node.iter, ast.Name):
                elems = local_elems.get(node.iter.id, set())
                if elems:
                    typed.setdefault(node.target.id, set()).update(
                        elems)
                continue
            if not (isinstance(node.iter, ast.Attribute)
                    and isinstance(node.iter.value, ast.Name)):
                continue
            src = node.iter.value.id
            cand: set = set(typed.get(src, ()))
            if src in local_types:
                cand.add(local_types[src])
            for cname in cand:
                found = self.ns.resolve(self.mod, cname) \
                    or self.ns.local.get(cname)
                if found is None:
                    continue
                elem = self.ns.field_elem(found, node.iter.attr)
                if elem is not None:
                    typed.setdefault(node.target.id, set()).add(
                        elem[1].name)


# --- graph construction -----------------------------------------------------


def _codec_tags(project: Project) -> dict:
    """{(defining module path, message name): tag} for every codec.
    Memoized on the project -- build_all and the FLOW4xx passes both
    need it and the resolution walks every codec module."""
    cached = getattr(project, "_flow_codec_tags", None)
    if cached is not None:
        return cached
    out: dict = {}
    for mod, cls, msg_dotted in codec_rules._codec_classes(project):
        entry = codec_rules._resolve_message_class(project, mod,
                                                   msg_dotted)
        if entry is None:
            continue
        msg_mod, msg_cls = entry
        tag = None
        for stmt in cls.body:
            if isinstance(stmt, ast.Assign) \
                    and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id == "tag" \
                    and isinstance(stmt.value, ast.Constant):
                tag = stmt.value.value
        out[(msg_mod.path, msg_cls.name)] = tag
    project._flow_codec_tags = out
    return out


def build(project: Project, unit: str, mods: list,
          classes: dict, tags: dict) -> FlowGraph:
    ns = _Namespace(project, mods)
    roles: dict = {}
    messages: dict = {}

    def message_info(found) -> MessageInfo:
        # Messages are keyed by bare name within a unit; when two
        # same-named classes from different modules both appear, the
        # FIRST wins -- and the unit-local seed below runs first, so a
        # unit's own definition always shadows an imported name twin.
        mod, cls = found
        info = messages.get(cls.name)
        if info is None:
            info = messages[cls.name] = MessageInfo(
                name=cls.name, module=mod.path, line=cls.lineno,
                external=mod.path not in ns.unit_paths,
                codec_tag=tags.get((mod.path, cls.name)))
        return info

    # Seed with unit-defined messages so dead classes still appear.
    for name in sorted(ns.local):
        message_info(ns.local[name])

    for mod in mods:
        quals = qualname_index(mod.tree)
        for node in mod.tree.body:
            if not isinstance(node, ast.ClassDef) \
                    or not _is_actor(node.name, classes):
                continue
            scan = _RoleScan(ns, mod, node, quals)
            role = roles.setdefault(node.name, RoleInfo(
                name=node.name, module=mod.path, line=node.lineno))
            for msg_name, funcs in scan.handled().items():
                found = ns.resolve(mod, msg_name) \
                    or ns.local.get(msg_name)
                if found is None:
                    continue
                info = message_info(found)
                info.handlers.setdefault(node.name, set()).update(funcs)
                role.handles.add(info.name)
                for fn in funcs:
                    role.handler_funcs.setdefault(fn, set()).add(
                        info.name)
            for msg_name, kind, origins, path, line in scan.sent():
                found = ns.resolve(mod, msg_name) \
                    or ns.local.get(msg_name)
                if found is None:
                    continue
                info = message_info(found)
                info.senders.setdefault(node.name, set()).add(kind)
                info.send_sites.append((path, line))
                info.send_origins |= origins
                role.sends.add(info.name)
    return FlowGraph(unit=unit, modules=[m.path for m in mods],
                     roles=roles, messages=messages)


def _inherit_roles(graphs: dict, classes: dict) -> None:
    """Merge base-class behavior into subclass roles ACROSS units:
    ``GcBPaxosLeader(BPaxosLeader)`` handles and sends everything its
    simplebpaxos base does, but that behavior was scanned into the
    simplebpaxos graph. Without the merge, derived protocols look
    like dead shells (no reply paths -- FLOW404 false positives)."""
    role_home: dict = {}
    for unit, g in graphs.items():
        for rname in g.roles:
            role_home.setdefault(rname, (unit, g))

    def base_chain(name: str, seen: set) -> list:
        out = []
        for _, node in classes.get(name, ()):
            for b in node.bases:
                bname = dotted(b).split(".")[-1]
                if bname not in seen:
                    seen.add(bname)
                    out.append(bname)
                    out.extend(base_chain(bname, seen))
        return out

    for unit, g in graphs.items():
        for rname, role in list(g.roles.items()):
            for bname in base_chain(rname, {rname}):
                home = role_home.get(bname)
                if home is None or home[1] is g:
                    continue
                src_g = home[1]
                src_role = src_g.roles[bname]
                for mname in src_role.handles | src_role.sends:
                    src_info = src_g.messages[mname]
                    info = g.messages.get(mname)
                    if info is None:
                        info = g.messages[mname] = MessageInfo(
                            name=mname, module=src_info.module,
                            line=src_info.line, external=True,
                            codec_tag=src_info.codec_tag)
                    if mname in src_role.handles:
                        info.handlers.setdefault(rname, set()).update(
                            src_info.handlers.get(bname, ()))
                        role.handles.add(mname)
                    if bname in src_info.senders \
                            and mname in src_role.sends:
                        info.senders.setdefault(rname, set()).update(
                            src_info.senders[bname])
                        info.send_origins |= src_info.send_origins
                        role.sends.add(mname)


def build_all(project: Project) -> dict:
    """{unit name: FlowGraph} for every protocol unit. Cached on the
    project instance -- three rule families and the artifact emitter
    all consume the same graphs."""
    cached = getattr(project, "_flowgraphs", None)
    if cached is not None:
        return cached
    classes = _class_index(project)
    tags = _codec_tags(project)
    graphs = {unit: build(project, unit, mods, classes, tags)
              for unit, mods in unit_modules(project).items()}
    _inherit_roles(graphs, classes)
    project._flowgraphs = graphs
    return graphs


# --- project-wide send scan (FLOW403's surface) ------------------------------


def global_sent_types(project: Project) -> dict:
    """{(defining module path, message name): [(module, line), ...]}
    for every message-class send OR wire-encode site anywhere in the
    project: serve/ and reconfig/ roles send protocol messages, and
    admin edges (bench/chaos.py) put messages on the wire via
    ``serializer.to_bytes(...)`` without a transport send. Nested
    constructions count -- a message wrapped inside another sent
    message still crosses the wire as payload."""
    cached = getattr(project, "_flow_global_sent", None)
    if cached is not None:
        return cached
    leaves = set(SEND_KINDS) | {"to_bytes"}
    out: dict = {}
    for mod in project:
        ns = _module_namespace(project, mod)
        for func in _walk(mod.tree):
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            local_types: dict = {}
            for node in _walk(func):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    found = ns.resolve(mod, dotted(node.value.func))
                    if found is not None:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                local_types[t.id] = found
            for node in _walk(func):
                if not isinstance(node, ast.Call):
                    continue
                if dotted(node.func).split(".")[-1] not in leaves:
                    continue
                for arg in node.args:
                    hits = []
                    top = _unwrap_replace(arg)
                    if isinstance(top, ast.Name) \
                            and top.id in local_types:
                        hits.append(local_types[top.id])
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Call):
                            found = ns.resolve(mod, dotted(sub.func))
                            if found is not None:
                                hits.append(found)
                    for fmod, fcls in hits:
                        out.setdefault((fmod.path, fcls.name),
                                       []).append((mod.path,
                                                   node.lineno))
    project._flow_global_sent = out
    return out


def global_handled_types(project: Project) -> dict:
    """{(defining module path, message name): set of handler quals}
    for every Actor handler ANYWHERE in the project. Actors outside
    the protocol tree (election/, reconfig/, serve/) handle messages
    protocol roles send -- FLOW401 must see those handlers."""
    cached = getattr(project, "_flow_global_handled", None)
    if cached is not None:
        return cached
    classes = _class_index(project)
    out: dict = {}
    for mod in project:
        ns = _module_namespace(project, mod)
        quals = qualname_index(mod.tree)
        for node in mod.tree.body:
            if not isinstance(node, ast.ClassDef) \
                    or not _is_actor(node.name, classes):
                continue
            scan = _RoleScan(ns, mod, node, quals)
            for msg_name, funcs in scan.handled().items():
                found = ns.resolve(mod, msg_name) \
                    or ns.local.get(msg_name)
                if found is None:
                    continue
                out.setdefault((found[0].path, found[1].name),
                               set()).update(funcs)
    project._flow_global_handled = out
    return out


# --- artifact emission ------------------------------------------------------

#: Bump when the JSON schema changes; the staleness gate compares
#: regenerated bytes, so a version mismatch reads as stale.
SCHEMA_VERSION = 1


def to_json(graph: FlowGraph) -> dict:
    roles = {}
    for name in sorted(graph.roles):
        r = graph.roles[name]
        roles[name] = {
            "module": r.module,
            "handles": sorted(r.handles),
            "sends": sorted(r.sends),
        }
    messages = {}
    for name in sorted(graph.messages):
        m = graph.messages[name]
        messages[name] = {
            "module": m.module,
            "external": m.external,
            "codec_tag": m.codec_tag,
            "senders": {role: sorted(kinds) for role, kinds
                        in sorted(m.senders.items())},
            "handlers": {role: sorted(funcs) for role, funcs
                         in sorted(m.handlers.items())},
            "timer_resent": "timer" in m.send_origins,
        }
    return {
        "schema_version": SCHEMA_VERSION,
        "protocol": graph.unit,
        "modules": sorted(graph.modules),
        "roles": roles,
        "messages": messages,
        "edges": [
            {"from": s, "message": m, "to": h, "kind": k}
            for s, m, h, k in graph.edges()],
    }


def to_dot(graph: FlowGraph) -> str:
    """A role-level digraph; edges labeled with message names.
    Parallel edges between one role pair collapse into one label."""
    pairs: dict = {}
    for sender, msg, handler, kind in graph.edges():
        key = (sender, handler)
        pairs.setdefault(key, set()).add(
            msg + ("*" if kind == "wal_send" else ""))
    lines = [f'digraph "{graph.unit}" {{',
             "  rankdir=LR;",
             '  node [shape=box, fontname="monospace"];']
    for role in sorted(graph.roles):
        lines.append(f'  "{role}";')
    if any(h == "?" for _, h in pairs):
        lines.append('  "?" [shape=ellipse, style=dashed, '
                     'label="(no in-unit handler)"];')
    for (sender, handler) in sorted(pairs):
        label = "\\n".join(sorted(pairs[(sender, handler)]))
        lines.append(f'  "{sender}" -> "{handler}" '
                     f'[label="{label}", fontname="monospace", '
                     f'fontsize=9];')
    lines.append("}")
    lines.append("")
    return "\n".join(lines)


def render(project: Project) -> dict:
    """{relative artifact path: content} for every protocol unit."""
    out: dict = {}
    for unit, graph in sorted(build_all(project).items()):
        payload = json.dumps(to_json(graph), indent=1,
                             sort_keys=True) + "\n"
        out[f"{unit}.json"] = payload
        out[f"{unit}.dot"] = to_dot(graph)
    return out


def write_artifacts(project: Project, out_dir: str) -> list:
    os.makedirs(out_dir, exist_ok=True)
    expected = render(project)
    written = []
    for rel, content in expected.items():
        path = os.path.join(out_dir, rel)
        with open(path, "w", encoding="utf-8") as f:
            f.write(content)
        written.append(path)
    # A renamed/removed protocol must not leave an orphan artifact
    # behind (check_artifacts flags them as stale).
    for rel in sorted(os.listdir(out_dir)):
        if rel.endswith((".json", ".dot")) and rel not in expected:
            os.remove(os.path.join(out_dir, rel))
    return written


def check_artifacts(project: Project, out_dir: str) -> list:
    """Stale/missing/orphan artifact relative paths (empty = fresh).
    Orphans -- committed artifacts no registered protocol produces
    anymore (a removed or renamed unit) -- count as stale too."""
    expected = render(project)
    stale = []
    for rel, content in expected.items():
        path = os.path.join(out_dir, rel)
        try:
            with open(path, encoding="utf-8") as f:
                on_disk = f.read()
        except OSError:
            stale.append(rel + " (missing)")
            continue
        if on_disk != content:
            stale.append(rel)
    try:
        on_disk_files = sorted(os.listdir(out_dir))
    except OSError:
        on_disk_files = []
    for rel in on_disk_files:
        if rel.endswith((".json", ".dot")) and rel not in expected:
            stale.append(rel + " (orphan)")
    return stale
