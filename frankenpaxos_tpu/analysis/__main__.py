"""``python -m frankenpaxos_tpu.analysis``: run paxlint, exit-code
gated.

Exit 0 when every finding is grandfathered in the baseline (or there
are none); exit 1 on any new finding. See docs/ANALYSIS.md.

``--write-flowgraphs`` regenerates the committed per-protocol
role x message flow-graph artifacts under docs/flowgraphs/ (paxflow);
``--check-flowgraphs`` exits 1 when the committed artifacts are stale
against the source tree (the CI freshness gate).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from frankenpaxos_tpu.analysis import (
    baseline as baseline_mod,
    diff as diff_mod,
    flowgraph,
    sarif as sarif_mod,
)
from frankenpaxos_tpu.analysis.core import (
    _ensure_loaded,
    Project,
    RULES,
    run_rules,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m frankenpaxos_tpu.analysis",
        description="paxlint: actor-contract / TPU-hot-path / "
                    "wire-codec static analysis")
    parser.add_argument(
        "--root", default=None,
        help="repository root (default: auto-detected from this "
             "package's location)")
    parser.add_argument(
        "--baseline", default=None,
        help="baseline file (default: <root>/.paxlint-baseline.json)")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="regenerate the baseline from current findings and exit 0")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding as new")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print every rule ID with its description and exit")
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="finding output format: human text (default), one JSON "
             "document with file/line/rule/scope/detail/message/"
             "baselined records, or a SARIF 2.1.0 document with the "
             "identical finding set (the CI lint job uploads both as "
             "artifacts)")
    parser.add_argument(
        "--output", default=None,
        help="write the JSON finding document to this file; works on "
             "its own (stdout keeps the human report -- how the CI "
             "lint job produces its artifact) or with --format=json "
             "(stdout carries the same JSON)")
    parser.add_argument(
        "--sarif-output", default=None,
        help="write the SARIF document to this file (same finding set "
             "as --output; the CI lint job uploads paxlint.sarif "
             "alongside paxlint.json)")
    parser.add_argument(
        "--changed-since", default=None, metavar="REF",
        help="diff-aware mode: only report findings in modules changed "
             "since the git REF plus everything that (transitively) "
             "imports them; the full project still parses, so the "
             "result equals a full run restricted to that closure")
    parser.add_argument(
        "--write-flowgraphs", action="store_true",
        help="regenerate docs/flowgraphs/*.{json,dot} (paxflow "
             "artifacts) and exit 0")
    parser.add_argument(
        "--check-flowgraphs", action="store_true",
        help="exit 1 if the committed docs/flowgraphs artifacts are "
             "stale against the source tree")
    parser.add_argument(
        "--flowgraph-dir", default=None,
        help="artifact directory (default: <root>/docs/flowgraphs)")
    args = parser.parse_args(argv)

    if args.list_rules:
        _ensure_loaded()
        for rule in sorted(RULES):
            print(f"{rule}  {RULES[rule]}")
        return 0

    root = args.root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    baseline_path = args.baseline or os.path.join(
        root, ".paxlint-baseline.json")
    flowgraph_dir = args.flowgraph_dir or os.path.join(
        root, "docs", "flowgraphs")

    if args.write_flowgraphs:
        written = flowgraph.write_artifacts(Project(root), flowgraph_dir)
        print(f"paxflow: wrote {len(written)} artifact(s) to "
              f"{flowgraph_dir}")
        return 0

    if args.check_flowgraphs:
        stale = flowgraph.check_artifacts(Project(root), flowgraph_dir)
        if stale:
            print(f"paxflow: {len(stale)} stale flow-graph artifact(s) "
                  f"in {flowgraph_dir}:")
            for rel in stale:
                print(f"  {rel}")
            print("\npaxflow: regenerate with `python -m "
                  "frankenpaxos_tpu.analysis --write-flowgraphs` and "
                  "commit the result.")
            return 1
        print(f"paxflow: OK -- docs/flowgraphs artifacts are fresh")
        return 0

    project = Project(root)
    if args.changed_since:
        changed = diff_mod.changed_paths(root, args.changed_since)
        project.focus = diff_mod.affected_closure(project, changed)
        scope = ("everything (out-of-package change)"
                 if project.focus is None
                 else f"{len(project.focus)} affected module(s)")
        print(f"paxlint: diff-aware -- {len(changed)} changed path(s) "
              f"since {args.changed_since}, checking {scope}",
              # keep stdout machine-readable for the document formats
              file=sys.stdout if args.format == "text" else sys.stderr)
    findings = run_rules(project)

    if args.write_baseline:
        baseline_mod.write(baseline_path, findings)
        print(f"paxlint: wrote {len(findings)} grandfathered finding(s) "
              f"to {baseline_path}")
        return 0

    entries = [] if args.no_baseline else baseline_mod.load(baseline_path)
    new, old, stale = baseline_mod.split(findings, entries)

    grandfathered = {f.key for f in old}
    if args.format == "sarif" or args.sarif_output:
        sarif_doc = sarif_mod.render(findings, grandfathered, RULES)
        sarif_text = json.dumps(sarif_doc, indent=1, sort_keys=True)
        if args.sarif_output:
            with open(args.sarif_output, "w", encoding="utf-8") as out:
                out.write(sarif_text + "\n")
        if args.format == "sarif":
            print(sarif_text)
            return 1 if new else 0

    if args.format == "json" or args.output:
        document = {
            "files_checked": len(project.modules),
            "new": len(new),
            "grandfathered": len(old),
            "stale_baseline_entries": [list(k) for k in stale],
            "findings": [
                {
                    "file": f.file,
                    "line": f.line,
                    "rule": f.rule,
                    "scope": f.scope,
                    "detail": f.detail,
                    "message": f.message,
                    "baselined": f.key in grandfathered,
                }
                for f in sorted(findings,
                                key=lambda f: (f.file, f.line, f.rule))
            ],
        }
        text = json.dumps(document, indent=1, sort_keys=True)
        if args.output:
            with open(args.output, "w", encoding="utf-8") as out:
                out.write(text + "\n")
        if args.format == "json":
            print(text)
            return 1 if new else 0

    if old:
        print(f"paxlint: {len(old)} grandfathered finding(s) "
              f"(baselined in {os.path.basename(baseline_path)}):")
        for f in old:
            print(f"  [baseline] {f.rule} {f.file} "
                  f"[{f.scope}] {f.detail}")
    if stale:
        print(f"paxlint: {len(stale)} stale baseline entr(y/ies) -- "
              f"the finding no longer exists; prune with "
              f"--write-baseline:")
        for k in stale:
            print(f"  [stale] {' '.join(k)}")
    if new:
        print(f"paxlint: {len(new)} NEW finding(s):")
        for f in new:
            print(f"  {f.render()}")
        print("\npaxlint: fix the finding, add a justified "
              "`# paxlint: disable=<rule>` pragma, or (last resort) "
              "re-baseline with --write-baseline.")
        return 1
    checked = len(project.modules)
    print(f"paxlint: OK -- {checked} files, "
          f"{len(old)} grandfathered, 0 new findings")
    return 0


if __name__ == "__main__":
    sys.exit(main())
