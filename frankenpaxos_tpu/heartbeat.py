"""Heartbeat failure detector.

Reference behavior: heartbeat/Participant.scala:72-209. Every participant
pings the others; a pong resets that peer's retry count, updates an EWMA
estimate of network delay, and schedules the next ping after
``success_period``; a missing pong retries after ``fail_period`` and
after ``num_retries`` consecutive misses the peer is deemed dead. The
``alive`` set and delay estimates feed ThriftySystem.Closest.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Sequence

from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport


@dataclasses.dataclass(frozen=True)
class Ping:
    index: int       # index of the *target* in the sender's address list
    nanotime: int


@dataclasses.dataclass(frozen=True)
class Pong:
    index: int
    nanotime: int


@dataclasses.dataclass(frozen=True)
class HeartbeatOptions:
    """Mimics TCP keepalive's interval/time/retry knobs
    (Participant.scala:38-60).

    ``adaptive=True`` derives each peer's fail deadline from OBSERVED
    round-trip times instead of the fixed ``fail_period_s``: a
    Jacobson/Karels estimator (geo.RttEstimator, EWMA + mean
    deviation) per peer, with the deadline at ``srtt + 4 * dev``
    clamped to ``[min_fail_period_s, max_fail_period_s]``. Fixed
    deadlines false-positive the moment links have real latency and
    jitter (a WAN brownout under GeoTopology blows straight through
    any constant chosen for the fast path -- tests/test_geo.py);
    ``fail_period_s`` remains the deadline until the first pong."""

    fail_period_s: float = 5.0
    success_period_s: float = 10.0
    num_retries: int = 3
    network_delay_alpha: float = 0.9
    adaptive: bool = False
    min_fail_period_s: float = 0.01
    max_fail_period_s: float = 120.0
    # Until the first pong there is no RTT sample, so adaptive mode
    # starts CONSERVATIVE (TCP's initial-RTO discipline) instead of
    # trusting a constant that may sit below the real RTT.
    initial_fail_period_s: float = 1.0


class HeartbeatParticipant(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, addresses: Sequence[Address],
                 options: HeartbeatOptions = HeartbeatOptions(),
                 clock: Callable[[], int] = time.monotonic_ns):
        super().__init__(address, transport, logger)
        logger.check_le(0, options.network_delay_alpha)
        logger.check_le(options.network_delay_alpha, 1)
        self.addresses = list(addresses)
        self.options = options
        self.clock = clock
        self.num_retries = [0] * len(self.addresses)
        self.network_delay_nanos: dict[int, float] = {}
        if options.adaptive:
            from frankenpaxos_tpu.geo.rtt import RttEstimator

            self.rtt_estimators = [RttEstimator()
                                   for _ in self.addresses]
        else:
            self.rtt_estimators = None
        self.alive: set[Address] = set(self.addresses)
        initial_fail_s = (max(options.fail_period_s,
                              options.initial_fail_period_s)
                          if options.adaptive else options.fail_period_s)
        self.fail_timers = [
            self.timer(f"fail-{a}", initial_fail_s,
                       lambda i=i: self._fail(i))
            for i, a in enumerate(self.addresses)]
        self.success_timers = [
            self.timer(f"success-{a}", options.success_period_s,
                       lambda i=i: self._succeed(i))
            for i, a in enumerate(self.addresses)]
        for i, a in enumerate(self.addresses):
            self.send(a, Ping(index=i, nanotime=self.clock()))
            self.fail_timers[i].start()

    def receive(self, src: Address, message) -> None:
        if isinstance(message, Ping):
            self.send(src, Pong(index=message.index,
                                nanotime=message.nanotime))
        elif isinstance(message, Pong):
            self._handle_pong(message)
        else:
            self.logger.fatal(f"unexpected heartbeat message {message!r}")

    def _handle_pong(self, pong: Pong) -> None:
        rtt_nanos = self.clock() - pong.nanotime
        delay = rtt_nanos / 2
        alpha = self.options.network_delay_alpha
        old = self.network_delay_nanos.get(pong.index)
        self.network_delay_nanos[pong.index] = (
            delay if old is None else alpha * delay + (1 - alpha) * old)
        if self.rtt_estimators is not None:
            # Jitter-tolerant deadlines (geo.RttEstimator): retune the
            # peer's fail timer to srtt + 4*dev before its next start,
            # so one WAN jitter spike no longer burns a retry.
            estimator = self.rtt_estimators[pong.index]
            estimator.observe(rtt_nanos / 1e9)
            self.fail_timers[pong.index].set_delay(min(
                self.options.max_fail_period_s,
                max(self.options.min_fail_period_s,
                    estimator.timeout(self.options.fail_period_s))))
        self.alive.add(self.addresses[pong.index])
        self.num_retries[pong.index] = 0
        self.fail_timers[pong.index].stop()
        self.success_timers[pong.index].start()

    def _fail(self, index: int) -> None:
        self.num_retries[index] += 1
        if self.num_retries[index] >= self.options.num_retries:
            self.alive.discard(self.addresses[index])
        self.send(self.addresses[index],
                  Ping(index=index, nanotime=self.clock()))
        self.fail_timers[index].start()

    def _succeed(self, index: int) -> None:
        self.send(self.addresses[index],
                  Ping(index=index, nanotime=self.clock()))
        self.fail_timers[index].start()

    # Callable only from the same event loop (Participant.scala:186-208).
    def unsafe_alive(self) -> set[Address]:
        return set(self.alive)

    def unsafe_network_delay(self) -> dict[Address, float]:
        """Seconds of estimated one-way delay; infinity for dead peers."""
        delays = {}
        for i, a in enumerate(self.addresses):
            nanos = self.network_delay_nanos.get(i)
            if nanos is not None and a in self.alive:
                delays[a] = nanos / 1e9
            else:
                delays[a] = float("inf")
        return delays
