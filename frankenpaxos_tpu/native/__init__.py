"""ctypes loader for the native wire codec (with pure-Python fallback).

Compiles ``codec.cpp`` with g++ on first use (cached as
``libfpxcodec.so`` next to the source; rebuilds when the source is
newer). Every entry point has a NumPy/struct fallback so the framework
runs where no compiler exists.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "codec.cpp")
_LIB = os.path.join(_DIR, "libfpxcodec.so")
_LEN = struct.Struct(">I")

_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> None:
    subprocess.run(
        ["g++", "-O3", "-shared", "-fPIC", "-o", _LIB, _SRC],
        check=True, capture_output=True)


def load() -> Optional[ctypes.CDLL]:
    """The codec library, building it if needed; None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    try:
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            _build()
        lib = ctypes.CDLL(_LIB)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.fpx_encode_frame.restype = ctypes.c_longlong
        lib.fpx_encode_frame.argtypes = [
            u8p, ctypes.c_uint32, u8p, ctypes.c_uint32, u8p,
            ctypes.c_uint64]
        lib.fpx_encode_frames.restype = ctypes.c_longlong
        lib.fpx_encode_frames.argtypes = [
            u8p, ctypes.c_uint32, u8p, ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint32, u8p, ctypes.c_uint64]
        lib.fpx_scan_frames.restype = ctypes.c_longlong
        lib.fpx_scan_frames.argtypes = [
            u8p, ctypes.c_uint64, u64p, ctypes.c_uint32, u64p]
        lib.fpx_pack_votes.restype = ctypes.c_longlong
        lib.fpx_pack_votes.argtypes = [
            i32p, i32p, i32p, ctypes.c_uint32, u8p, ctypes.c_uint64]
        lib.fpx_unpack_votes.restype = ctypes.c_longlong
        lib.fpx_unpack_votes.argtypes = [
            u8p, ctypes.c_uint64, i32p, i32p, i32p, ctypes.c_uint32]
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.fpx_pack_votes2.restype = ctypes.c_longlong
        lib.fpx_pack_votes2.argtypes = [
            i64p, i32p, ctypes.c_uint32, u8p, ctypes.c_uint64]
        lib.fpx_unpack_votes2.restype = ctypes.c_longlong
        lib.fpx_unpack_votes2.argtypes = [
            u8p, ctypes.c_uint64, i64p, i32p, ctypes.c_uint32]
        _lib = lib
    except (OSError, subprocess.CalledProcessError):
        _load_failed = True
    return _lib


def _as_u8p(buf) -> ctypes.POINTER(ctypes.c_uint8):  # type: ignore[misc]
    return (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf) if buf else \
        ctypes.cast(0, ctypes.POINTER(ctypes.c_uint8))


def encode_frame(header: bytes, payload: bytes) -> bytes:
    """One wire frame: [u32 total][u32 hlen][header][payload]."""
    lib = load()
    if lib is None:
        inner = _LEN.pack(len(header)) + header + payload
        return _LEN.pack(len(inner)) + inner
    out = (ctypes.c_uint8 * (12 + len(header) + len(payload)))()
    n = lib.fpx_encode_frame(_as_u8p(header), len(header),
                             _as_u8p(payload), len(payload), out, len(out))
    if n == -2:
        raise ValueError("frame exceeds the 10 MiB cap")
    assert n >= 0
    return bytes(out[:n])


def encode_frames(header: bytes, payloads: list[bytes]) -> bytes:
    """Coalesce many same-header frames into one write buffer."""
    lib = load()
    if lib is None:
        return b"".join(encode_frame(header, p) for p in payloads)
    blob = b"".join(payloads)
    lens = (ctypes.c_uint32 * len(payloads))(*[len(p) for p in payloads])
    cap = sum(12 + len(header) + len(p) for p in payloads)
    out = (ctypes.c_uint8 * max(cap, 1))()
    n = lib.fpx_encode_frames(_as_u8p(header), len(header), _as_u8p(blob),
                              lens, len(payloads), out, len(out))
    if n == -2:
        raise ValueError("frame exceeds the 10 MiB cap")
    assert n >= 0
    return bytes(out[:n])


def scan_frames(buf: bytes, max_frames: int = 4096
                ) -> tuple[list[tuple[int, int]], int]:
    """Complete frames' (start, end) inner offsets + consumed bytes."""
    lib = load()
    if lib is None:
        frames, pos = [], 0
        while pos + 4 <= len(buf):
            (inner,) = _LEN.unpack_from(buf, pos)
            if pos + 4 + inner > len(buf):
                break
            frames.append((pos + 4, pos + 4 + inner))
            pos += 4 + inner
        return frames, pos
    offsets = (ctypes.c_uint64 * (2 * max_frames))()
    consumed = ctypes.c_uint64()
    n = lib.fpx_scan_frames(_as_u8p(buf), len(buf), offsets, max_frames,
                            ctypes.byref(consumed))
    if n == -2:
        raise ValueError("frame exceeds the 10 MiB cap")
    return ([(offsets[2 * i], offsets[2 * i + 1]) for i in range(n)],
            consumed.value)


def pack_votes(slots: np.ndarray, nodes: np.ndarray,
               rounds: np.ndarray) -> bytes:
    """Phase2b vote batch -> bytes (feeds TpuQuorumChecker directly)."""
    slots = np.ascontiguousarray(slots, dtype=np.int32)
    nodes = np.ascontiguousarray(nodes, dtype=np.int32)
    rounds = np.ascontiguousarray(rounds, dtype=np.int32)
    lib = load()
    if lib is None:
        out = np.empty((slots.shape[0], 3), dtype="<i4")
        out[:, 0], out[:, 1], out[:, 2] = slots, nodes, rounds
        return struct.pack("<I", slots.shape[0]) + out.tobytes()
    n = slots.shape[0]
    out = (ctypes.c_uint8 * (4 + 12 * n))()
    written = lib.fpx_pack_votes(
        slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        nodes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        rounds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n, out, len(out))
    assert written == len(out)
    return bytes(out)


# Packed 12-byte (i64 slot, i32 round) records -- the Phase2bVotes
# payload entry. Slots are i64 to match the rest of the wire (the
# Phase2b/Phase2bRange codecs carry '<q' slots).
_VOTE2_DTYPE = np.dtype([("slot", "<i8"), ("round", "<i4")])


def pack_votes2(slots: np.ndarray, rounds: np.ndarray) -> bytes:
    """Single-acceptor vote batch -> bytes (Phase2bVotes payload): two
    columns only -- the acceptor identity rides the message header, so
    no dead node column on the wire."""
    slots = np.ascontiguousarray(slots, dtype=np.int64)
    rounds = np.ascontiguousarray(rounds, dtype=np.int32)
    lib = load()
    if lib is None:
        out = np.empty(slots.shape[0], dtype=_VOTE2_DTYPE)
        out["slot"], out["round"] = slots, rounds
        return struct.pack("<I", slots.shape[0]) + out.tobytes()
    n = slots.shape[0]
    out = (ctypes.c_uint8 * (4 + 12 * n))()
    written = lib.fpx_pack_votes2(
        slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        rounds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n, out, len(out))
    assert written == len(out)
    return bytes(out)


def _check_count(buf: bytes, record_size: int) -> int:
    """Validate a [u32 count][count * record] payload's framing WITHOUT
    allocating anything proportional to the claimed count; returns the
    count. Raising here (ValueError) is the defense against hostile
    counts (a u32 count of 0xFFFFFFFF would otherwise drive a ~48 GB
    numpy allocation before any bounds check ran)."""
    if len(buf) < 4:
        raise ValueError("malformed vote batch: short count header")
    (n,) = struct.unpack_from("<I", buf, 0)
    if len(buf) < 4 + record_size * n:
        raise ValueError(
            f"malformed vote batch: count {n} exceeds payload "
            f"({len(buf)} bytes)")
    return n


def check_votes2(buf: bytes) -> int:
    """Validate a packed Phase2bVotes payload; returns the count. The
    message codec calls this inside decode so a malformed payload is
    dropped by the transport's corrupt-frame guard, never reaching an
    actor."""
    return _check_count(buf, _VOTE2_DTYPE.itemsize)


def unpack_votes2(buf: bytes) -> tuple[np.ndarray, np.ndarray]:
    n = check_votes2(buf)
    lib = load()
    if lib is None:
        rec = np.frombuffer(buf, dtype=_VOTE2_DTYPE, count=n, offset=4)
        return rec["slot"].copy(), rec["round"].copy()
    slots = np.empty(n, dtype=np.int64)
    rounds = np.empty(n, dtype=np.int32)
    got = lib.fpx_unpack_votes2(
        _as_u8p(buf), len(buf),
        slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        rounds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n)
    if got < 0:
        raise ValueError("malformed vote batch")
    return slots, rounds


def unpack_votes(buf: bytes) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    n = _check_count(buf, 12)  # 3 x i32 records
    lib = load()
    if lib is None:
        flat = np.frombuffer(buf, dtype="<i4", count=3 * n, offset=4)
        triples = flat.reshape(n, 3)
        return (triples[:, 0].copy(), triples[:, 1].copy(),
                triples[:, 2].copy())
    slots = np.empty(n, dtype=np.int32)
    nodes = np.empty(n, dtype=np.int32)
    rounds = np.empty(n, dtype=np.int32)
    got = lib.fpx_unpack_votes(
        _as_u8p(buf), len(buf),
        slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        nodes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        rounds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n)
    if got < 0:
        raise ValueError("malformed vote batch")
    return slots, nodes, rounds
