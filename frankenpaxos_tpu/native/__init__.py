"""ctypes loader for the native wire codec (with pure-Python fallback).

Compiles ``codec.cpp`` with g++ on first use (cached as
``libfpxcodec.so`` next to the source; rebuilds when the source is
newer). Every entry point has a NumPy/struct fallback so the framework
runs where no compiler exists.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "codec.cpp")
_LIB = os.path.join(_DIR, "libfpxcodec.so")
_LEN = struct.Struct(">I")

_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> None:
    subprocess.run(
        ["g++", "-O3", "-shared", "-fPIC", "-o", _LIB, _SRC],
        check=True, capture_output=True)


def load() -> Optional[ctypes.CDLL]:
    """The codec library, building it if needed; None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    try:
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            _build()
        lib = ctypes.CDLL(_LIB)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.fpx_encode_frame.restype = ctypes.c_longlong
        lib.fpx_encode_frame.argtypes = [
            u8p, ctypes.c_uint32, u8p, ctypes.c_uint32, u8p,
            ctypes.c_uint64]
        lib.fpx_encode_frames.restype = ctypes.c_longlong
        lib.fpx_encode_frames.argtypes = [
            u8p, ctypes.c_uint32, u8p, ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint32, u8p, ctypes.c_uint64]
        lib.fpx_scan_frames.restype = ctypes.c_longlong
        lib.fpx_scan_frames.argtypes = [
            u8p, ctypes.c_uint64, u64p, ctypes.c_uint32, u64p]
        lib.fpx_batch_header.restype = ctypes.c_longlong
        lib.fpx_batch_header.argtypes = [
            ctypes.c_uint8, ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint32, u8p, ctypes.c_uint64]
        lib.fpx_scan_batch.restype = ctypes.c_longlong
        lib.fpx_scan_batch.argtypes = [
            u8p, ctypes.c_uint64, u64p, ctypes.c_uint32]
        lib.fpx_pack_votes.restype = ctypes.c_longlong
        lib.fpx_pack_votes.argtypes = [
            i32p, i32p, i32p, ctypes.c_uint32, u8p, ctypes.c_uint64]
        lib.fpx_unpack_votes.restype = ctypes.c_longlong
        lib.fpx_unpack_votes.argtypes = [
            u8p, ctypes.c_uint64, i32p, i32p, i32p, ctypes.c_uint32]
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.fpx_pack_votes2.restype = ctypes.c_longlong
        lib.fpx_pack_votes2.argtypes = [
            i64p, i32p, ctypes.c_uint32, u8p, ctypes.c_uint64]
        lib.fpx_unpack_votes2.restype = ctypes.c_longlong
        lib.fpx_unpack_votes2.argtypes = [
            u8p, ctypes.c_uint64, i64p, i32p, ctypes.c_uint32]
        lib.fpx_ingest_scan.restype = ctypes.c_longlong
        lib.fpx_ingest_scan.argtypes = [
            u8p, ctypes.c_uint64, u8p, ctypes.c_uint64, u64p, i64p,
            ctypes.c_uint32]
        lib.fpx_value_columns.restype = ctypes.c_longlong
        lib.fpx_value_columns.argtypes = [
            u8p, ctypes.c_uint64, i64p, ctypes.c_uint32,
            ctypes.c_uint32]
        lib.fpx_reply_columns.restype = ctypes.c_longlong
        lib.fpx_reply_columns.argtypes = [
            u8p, ctypes.c_uint64, i64p, ctypes.c_uint32]
        _lib = lib
    except (OSError, subprocess.CalledProcessError):
        _load_failed = True
    return _lib


def _as_u8p(buf) -> ctypes.POINTER(ctypes.c_uint8):  # type: ignore[misc]
    return (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf) if buf else \
        ctypes.cast(0, ctypes.POINTER(ctypes.c_uint8))


_U8P = ctypes.POINTER(ctypes.c_uint8)


# Deliberately exports a (pointer, keepalive) pair: every call site
# dels BOTH immediately after the native call, before any buffer
# resize/compaction can run (the BufferError class PR 8 closed).
def _as_u8p_view(buf, offset: int = 0):  # paxlint: disable=OWN1104
    """READ-ONLY pointer to ``buf[offset:]`` WITHOUT copying the buffer
    (the `_as_u8p` copy was the receive path's quadratic cost: every
    4096-frame scan pass re-copied the whole inbound buffer). Returns
    ``(pointer, keepalive)`` -- the caller must hold ``keepalive`` for
    the duration of the native call and drop it before mutating ``buf``
    (a live ``from_buffer`` export makes ``bytearray`` resizes raise
    BufferError)."""
    n = len(buf) - offset
    if n <= 0:
        return ctypes.cast(0, _U8P), None
    if isinstance(buf, (bytearray, memoryview)):
        # The ARRAY OBJECT itself is the pointer argument (ctypes
        # accepts arrays where POINTER(c_uint8) is declared). Never
        # ``ctypes.cast`` it: the cast pointer participates in a
        # reference cycle, so the buffer export would survive until a
        # gc pass and any bytearray resize in between would raise
        # BufferError. Dropping the array releases it immediately.
        arr = (ctypes.c_uint8 * n).from_buffer(buf, offset)
        return arr, arr
    # bytes (immutable): c_char_p points at the object's internal
    # storage; no copy, kept alive by holding the bytes object itself.
    base = ctypes.cast(ctypes.c_char_p(buf), ctypes.c_void_p).value
    return ctypes.cast(ctypes.c_void_p(base + offset), _U8P), buf


def encode_frame(header: bytes, payload: bytes) -> bytes:
    """One wire frame: [u32 total][u32 hlen][header][payload]."""
    lib = load()
    if lib is None:
        inner = _LEN.pack(len(header)) + header + payload
        return _LEN.pack(len(inner)) + inner
    out = (ctypes.c_uint8 * (12 + len(header) + len(payload)))()
    n = lib.fpx_encode_frame(_as_u8p(header), len(header),
                             _as_u8p(payload), len(payload), out, len(out))
    if n == -2:
        raise ValueError("frame exceeds the 10 MiB cap")
    assert n >= 0
    return bytes(out[:n])


def encode_frames(header: bytes, payloads: list[bytes]) -> bytes:
    """Coalesce many same-header frames into one write buffer."""
    lib = load()
    if lib is None:
        return b"".join(encode_frame(header, p) for p in payloads)
    blob = b"".join(payloads)
    lens = (ctypes.c_uint32 * len(payloads))(*[len(p) for p in payloads])
    cap = sum(12 + len(header) + len(p) for p in payloads)
    out = (ctypes.c_uint8 * max(cap, 1))()
    n = lib.fpx_encode_frames(_as_u8p(header), len(header), _as_u8p(blob),
                              lens, len(payloads), out, len(out))
    if n == -2:
        raise ValueError("frame exceeds the 10 MiB cap")
    assert n >= 0
    return bytes(out[:n])


def scan_frames(buf, max_frames: int = 4096, offset: int = 0
                ) -> tuple[list[tuple[int, int]], int]:
    """Complete frames' (start, end) inner offsets + consumed cursor.

    ``buf`` may be bytes, bytearray, or a memoryview; the scan starts
    at ``offset`` and NEVER copies the buffer (the transport keeps an
    offset cursor into its growing inbound bytearray instead of
    re-slicing per pass). Returned offsets and the consumed cursor are
    ABSOLUTE positions in ``buf``."""
    lib = load()
    if lib is None:
        frames, pos, end = [], offset, len(buf)
        while pos + 4 <= end and len(frames) < max_frames:
            (inner,) = _LEN.unpack_from(buf, pos)
            if inner > 10 * 1024 * 1024:
                raise ValueError("frame exceeds the 10 MiB cap")
            if pos + 4 + inner > end:
                break
            frames.append((pos + 4, pos + 4 + inner))
            pos += 4 + inner
        return frames, pos
    offsets = (ctypes.c_uint64 * (2 * max_frames))()
    consumed = ctypes.c_uint64()
    ptr, keepalive = _as_u8p_view(buf, offset)
    try:
        n = lib.fpx_scan_frames(ptr, len(buf) - offset, offsets,
                                max_frames, ctypes.byref(consumed))
    finally:
        del ptr, keepalive  # release the buffer export before returning
    if n == -2:
        raise ValueError("frame exceeds the 10 MiB cap")
    return ([(offset + offsets[2 * i], offset + offsets[2 * i + 1])
             for i in range(n)],
            offset + consumed.value)


# --- paxwire batch frames ---------------------------------------------------
# One batch frame carries a whole drain's same-type messages to a peer:
#   [0x00][batch tag - 128][u32le count][count * u32le seg_len][segments]
# The header (everything before the segments) is built in ONE native
# call; the segments ride as raw scatter/gather slices (sendmsg) or one
# join -- either way the bytes on the wire are identical.

_U32LE = struct.Struct("<I")


def batch_header(tag: int, seg_lens) -> bytes:
    """The batch payload header for extended-page wire ``tag`` over
    segments of the given lengths (the vectorized encode: one dispatch
    per drain's batch, not one struct.pack per message)."""
    n = len(seg_lens)
    lib = load()
    if lib is None:
        out = bytearray(2 + 4 + 4 * n)
        out[0] = 0
        out[1] = tag - 128
        _U32LE.pack_into(out, 2, n)
        pos = 6
        for seg_len in seg_lens:
            _U32LE.pack_into(out, pos, seg_len)
            pos += 4
        return bytes(out)
    lens = (ctypes.c_uint32 * n)(*seg_lens)
    out = (ctypes.c_uint8 * (6 + 4 * n))()
    written = lib.fpx_batch_header(tag - 128, lens, n, out, len(out))
    assert written == len(out)
    return bytes(out)


def scan_batch(buf, at: int, max_segs: int = 1 << 20
               ) -> list[tuple[int, int]]:
    """Segment (start, end) offsets of a batch payload whose u32 count
    sits at ``buf[at:]`` (the two leading tag bytes already consumed).
    Raises ValueError on a malformed table -- the containment channel
    for torn/corrupt batch frames (count or lengths exceeding the
    payload, trailing garbage)."""
    lib = load()
    n_left = len(buf) - at
    if lib is None:
        if n_left < 4:
            raise ValueError("malformed batch frame: short count header")
        (n,) = _U32LE.unpack_from(buf, at)
        if n > max_segs or 4 + 4 * n > n_left:
            raise ValueError(
                f"malformed batch frame: count {n} exceeds payload")
        pos = at + 4 + 4 * n
        segs = []
        for i in range(n):
            (seg_len,) = _U32LE.unpack_from(buf, at + 4 + 4 * i)
            if pos + seg_len > len(buf):
                raise ValueError(
                    "malformed batch frame: segment overruns payload")
            segs.append((pos, pos + seg_len))
            pos += seg_len
        if pos != len(buf):
            raise ValueError("malformed batch frame: trailing garbage")
        return segs
    # Cap the offsets table by what the payload could possibly hold so
    # a hostile count can never size a huge allocation.
    cap = min(max_segs, max(n_left // 4, 1))
    offsets = (ctypes.c_uint64 * (2 * cap))()
    ptr, keepalive = _as_u8p_view(buf, at)
    try:
        n = lib.fpx_scan_batch(ptr, n_left, offsets, cap)
    finally:
        del ptr, keepalive
    if n < 0:
        raise ValueError("malformed batch frame")
    return [(at + offsets[2 * i], at + offsets[2 * i + 1])
            for i in range(n)]


# --- paxingest column scans (ingest/, docs/TRANSPORT.md) --------------------
# The zero-object decode path: a ClientFrameBatch payload scans ONCE into
# (a) the run pipeline's value-array segment (LazyValueArray.raw layout,
# deduped first-seen address table) and (b) SoA descriptor columns
# (addr_idx, pseudonym, client_id, value_off, value_len) -- no
# per-message Python object between recv() and the leader's Phase2aRun.
# Contract shared by native and fallback: ValueError = torn/corrupt
# (the transport's corrupt-frame containment channel); None = well-formed
# but unsupported shape (mixed tags, exotic address kinds, trailing
# bytes) -- the caller falls back to ordinary per-message decode.

_CLIENT_REQUEST_TAG = 4    # multipaxos ClientRequest
_CLIENT_ARRAY_TAG = 115    # multipaxos ClientRequestArray (coalesced)
_MAX_INGEST_ADDRS = 4096   # codec.cpp kMaxIngestAddrs (parity)
_COLS = 5  # addr_idx, pseudonym, client_id, value_off, value_len
_I64X2 = struct.Struct("<qq")


def _py_ingest_scan(buf, at: int, max_cmds: int = 1 << 20):
    n_left = len(buf) - at
    if n_left < 4:
        raise ValueError("malformed batch frame: short count header")
    (n,) = _U32LE.unpack_from(buf, at)
    if 4 + 4 * n > n_left:
        raise ValueError(
            f"malformed batch frame: count {n} exceeds payload")
    # The same effective cap the native wrapper sizes its buffers by
    # (bit-for-bit verdict parity; see ingest_scan).
    max_cmds = min(max_cmds, n_left // 20 + 8)
    if n > max_cmds:
        return None
    rows: list = []
    addr_spans: list = []   # each unique address's raw bytes
    addr_index: dict = {}   # raw bytes -> index
    seg_at = at + 4 + 4 * n
    for i in range(n):
        (seg_len,) = _U32LE.unpack_from(buf, at + 4 + 4 * i)
        if seg_at + seg_len > len(buf):
            raise ValueError(
                "malformed batch frame: segment overruns payload")
        if seg_len < 2:
            raise ValueError("malformed ingest segment: too short")
        tag = buf[seg_at]
        if tag not in (_CLIENT_REQUEST_TAG, _CLIENT_ARRAY_TAG):
            return None
        kind = buf[seg_at + 1]
        if seg_len < 6:
            raise ValueError("malformed ingest segment: short address")
        (alen,) = _U32LE.unpack_from(buf, seg_at + 2)
        a_end = 6 + alen
        if kind == 1:
            a_end += 4
        elif kind not in (0, 2):
            return None
        if a_end > seg_len:
            raise ValueError("malformed ingest segment: short address")
        araw = bytes(buf[seg_at + 1:seg_at + a_end])
        idx = addr_index.get(araw)
        if idx is None:
            if len(addr_spans) == _MAX_INGEST_ADDRS:
                return None  # mirrors codec.cpp kMaxIngestAddrs
            idx = len(addr_spans)
            addr_index[araw] = idx
            addr_spans.append(araw)
        if tag == _CLIENT_REQUEST_TAG:
            entry_at, n_entries = a_end, 1
        else:
            if a_end + 4 > seg_len:
                raise ValueError(
                    "malformed ingest segment: short array count")
            (n_entries,) = _U32LE.unpack_from(buf, seg_at + a_end)
            entry_at = a_end + 4
        for _ in range(n_entries):
            if entry_at + 20 > seg_len:
                raise ValueError(
                    "malformed ingest segment: short command")
            (vlen,) = _U32LE.unpack_from(buf,
                                         seg_at + entry_at + 16)
            if entry_at + 20 + vlen > seg_len:
                raise ValueError(
                    "malformed ingest segment: value overruns segment")
            if len(rows) == max_cmds:
                return None
            pseudonym, client_id = _I64X2.unpack_from(
                buf, seg_at + entry_at)
            rows.append((idx, pseudonym, client_id,
                         seg_at + entry_at + 20, vlen))
            entry_at += 20 + vlen
        if entry_at != seg_len:
            return None  # trailing bytes: let the codec decide
        seg_at += seg_len
    if seg_at != len(buf):
        raise ValueError("malformed batch frame: trailing garbage")
    cols = np.asarray(rows, dtype=np.int64).reshape(-1, _COLS)
    out = bytearray()
    out += _U32LE.pack(len(addr_spans))
    for araw in addr_spans:
        out += araw
    for idx, pseudonym, client_id, voff, vlen in rows:
        out.append(1)
        out += _U32LE.pack(1)
        out += _U32LE.pack(idx)
        out += _I64X2.pack(pseudonym, client_id)
        out += _U32LE.pack(vlen)
        out += buf[voff:voff + vlen]
    return bytes(out), cols


def ingest_scan(buf, at: int = 2, max_cmds: int = 1 << 20):
    """Scan a ClientFrameBatch payload (``buf[at:]`` starts at the u32
    segment count) into ``(value_array_raw, columns)`` in one pass, or
    None when the batch's shape is unsupported. Raises ValueError on a
    torn/corrupt table -- the corrupt-frame containment channel."""
    lib = load()
    if lib is None:
        return _py_ingest_scan(buf, at, max_cmds)
    n_left = len(buf) - at
    if n_left < 4:
        raise ValueError("malformed batch frame: short count header")
    (n_segs,) = _U32LE.unpack_from(buf, at)
    if 4 + 4 * n_segs > n_left:
        raise ValueError(
            f"malformed batch frame: count {n_segs} exceeds payload")
    # Capacity bound: every command consumes >= 20 payload bytes (its
    # fixed entry header), so n_left // 20 can never under-size. The
    # output segment adds <= 9 bytes of body header per command plus
    # the (deduped) address table, covered by the same bound.
    cap = min(max_cmds, n_left // 20 + 8)
    cols = np.empty((cap, _COLS), dtype=np.int64)
    out = (ctypes.c_uint8 * (n_left + 32 * cap + 64))()
    out_len = ctypes.c_uint64()
    ptr, keepalive = _as_u8p_view(buf, at)
    try:
        n = lib.fpx_ingest_scan(
            ptr, n_left, out, len(out), ctypes.byref(out_len),
            cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), cap)
    finally:
        del ptr, keepalive
    if n == -1:
        raise ValueError("malformed ingest batch frame")
    if n < 0:
        return None  # -3 unsupported shape (-2 cannot happen: cap sized)
    # Offsets were computed relative to buf[at:]; make them absolute.
    cols = cols[:n]
    cols[:, 3] += at
    return bytes(out[:out_len.value]), cols


def _py_value_columns(raw, n: int):
    cols = np.empty((n, _COLS), dtype=np.int64)
    if len(raw) < 4:
        raise ValueError("malformed value array: short table header")
    (t,) = _U32LE.unpack_from(raw, 0)
    at = 4
    for _ in range(t):
        if at + 5 > len(raw):
            raise ValueError("malformed value array: torn address table")
        kind = raw[at]
        (alen,) = _U32LE.unpack_from(raw, at + 1)
        at += 5 + alen
        if kind == 1:
            at += 4
        elif kind not in (0, 2):
            return None
        if at > len(raw):
            raise ValueError("malformed value array: torn address table")
    for i in range(n):
        if at + 1 > len(raw):
            raise ValueError("malformed value array: torn body")
        if raw[at] != 1:
            return None  # noop or exotic value
        if at + 5 > len(raw):
            raise ValueError("malformed value array: torn body")
        (k,) = _U32LE.unpack_from(raw, at + 1)
        if k != 1:
            return None  # multi-command batch
        if at + 29 > len(raw):
            raise ValueError("malformed value array: torn entry")
        (idx,) = _U32LE.unpack_from(raw, at + 5)
        if idx >= t:
            raise ValueError("malformed value array: address index")
        pseudonym, client_id = _I64X2.unpack_from(raw, at + 9)
        (vlen,) = _U32LE.unpack_from(raw, at + 25)
        if at + 29 + vlen > len(raw):
            raise ValueError("malformed value array: value overrun")
        cols[i] = (idx, pseudonym, client_id, at + 29, vlen)
        at += 29 + vlen
    if at != len(raw):
        raise ValueError("malformed value array: trailing garbage")
    return cols


def value_columns(raw, n: int, max_cmds: int = 1 << 20):
    """SoA descriptor columns from a value-array raw segment
    (LazyValueArray.raw): per entry (addr_idx, pseudonym, client_id,
    value_off, value_len), offsets absolute into ``raw``. None when the
    segment holds anything but one-command batches (noops, wide
    batches); ValueError on corruption."""
    lib = load()
    if n > max_cmds:
        return None
    if lib is None:
        return _py_value_columns(raw, n)
    cols = np.empty((max(n, 1), _COLS), dtype=np.int64)
    ptr, keepalive = _as_u8p_view(raw, 0)
    try:
        got = lib.fpx_value_columns(
            ptr, len(raw),
            cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n, n)
    finally:
        del ptr, keepalive
    if got == -1:
        raise ValueError("malformed value array")
    if got < 0:
        return None
    return cols[:n]


_REPLY_ENTRY_HDR = struct.Struct("<qqq")  # pseudonym, client_id, slot


def _py_reply_columns(buf, at: int, max_replies: int):
    n_left = len(buf) - at
    if n_left < 4:
        raise ValueError("malformed reply array: short count header")
    (n,) = struct.unpack_from("<i", buf, at)
    if n < 0 or 4 + 28 * n > n_left:
        raise ValueError(
            f"malformed reply array: count {n} exceeds payload")
    if n > max_replies:
        return None
    cols = np.empty((n, _COLS), dtype=np.int64)
    pos = at + 4
    for i in range(n):
        if pos + 28 > len(buf):
            raise ValueError("malformed reply array: torn entry")
        pseudonym, client_id, slot = _REPLY_ENTRY_HDR.unpack_from(
            buf, pos)
        (rlen,) = _U32LE.unpack_from(buf, pos + 24)
        if pos + 28 + rlen > len(buf):
            raise ValueError(
                "malformed reply array: result overruns payload")
        cols[i] = (pseudonym, client_id, slot, pos + 28, rlen)
        pos += 28 + rlen
    if pos != len(buf):
        raise ValueError("malformed reply array: trailing garbage")
    return cols


def reply_columns(buf, at: int = 1, max_replies: int = 1 << 20):
    """A ClientReplyArray payload's entries as (n, 5) int64 SoA columns
    of (pseudonym, client_id, slot, result_off, result_len) -- the
    RETURN-path twin of :func:`ingest_scan`. ``buf[at:]`` starts at the
    i32 entry count (the leading tag byte consumed by the caller);
    offsets are absolute into ``buf``. None when the count exceeds
    ``max_replies``; ValueError on a torn/corrupt payload (the
    corrupt-frame containment channel)."""
    lib = load()
    if lib is None:
        return _py_reply_columns(buf, at, max_replies)
    n_left = len(buf) - at
    # Capacity bound mirrors the native pre-cap check: every entry
    # consumes >= 28 payload bytes.
    cap = min(max_replies, max(n_left, 0) // 28 + 1)
    cols = np.empty((cap, _COLS), dtype=np.int64)
    ptr, keepalive = _as_u8p_view(buf, at)
    try:
        n = lib.fpx_reply_columns(
            ptr, n_left,
            cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), cap)
    finally:
        del ptr, keepalive
    if n == -1:
        raise ValueError("malformed reply array")
    if n < 0:
        return None  # -2: count past max_replies
    cols = cols[:n]
    cols[:, 3] += at
    return cols


def pack_votes(slots: np.ndarray, nodes: np.ndarray,
               rounds: np.ndarray) -> bytes:
    """Phase2b vote batch -> bytes (feeds TpuQuorumChecker directly)."""
    slots = np.ascontiguousarray(slots, dtype=np.int32)
    nodes = np.ascontiguousarray(nodes, dtype=np.int32)
    rounds = np.ascontiguousarray(rounds, dtype=np.int32)
    lib = load()
    if lib is None:
        out = np.empty((slots.shape[0], 3), dtype="<i4")
        out[:, 0], out[:, 1], out[:, 2] = slots, nodes, rounds
        return struct.pack("<I", slots.shape[0]) + out.tobytes()
    n = slots.shape[0]
    out = (ctypes.c_uint8 * (4 + 12 * n))()
    written = lib.fpx_pack_votes(
        slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        nodes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        rounds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n, out, len(out))
    assert written == len(out)
    return bytes(out)


# Packed 12-byte (i64 slot, i32 round) records -- the Phase2bVotes
# payload entry. Slots are i64 to match the rest of the wire (the
# Phase2b/Phase2bRange codecs carry '<q' slots).
_VOTE2_DTYPE = np.dtype([("slot", "<i8"), ("round", "<i4")])


def pack_votes2(slots: np.ndarray, rounds: np.ndarray) -> bytes:
    """Single-acceptor vote batch -> bytes (Phase2bVotes payload): two
    columns only -- the acceptor identity rides the message header, so
    no dead node column on the wire."""
    slots = np.ascontiguousarray(slots, dtype=np.int64)
    rounds = np.ascontiguousarray(rounds, dtype=np.int32)
    lib = load()
    if lib is None:
        out = np.empty(slots.shape[0], dtype=_VOTE2_DTYPE)
        out["slot"], out["round"] = slots, rounds
        return struct.pack("<I", slots.shape[0]) + out.tobytes()
    n = slots.shape[0]
    out = (ctypes.c_uint8 * (4 + 12 * n))()
    written = lib.fpx_pack_votes2(
        slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        rounds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n, out, len(out))
    assert written == len(out)
    return bytes(out)


def _check_count(buf: bytes, record_size: int) -> int:
    """Validate a [u32 count][count * record] payload's framing WITHOUT
    allocating anything proportional to the claimed count; returns the
    count. Raising here (ValueError) is the defense against hostile
    counts (a u32 count of 0xFFFFFFFF would otherwise drive a ~48 GB
    numpy allocation before any bounds check ran)."""
    if len(buf) < 4:
        raise ValueError("malformed vote batch: short count header")
    (n,) = struct.unpack_from("<I", buf, 0)
    if len(buf) < 4 + record_size * n:
        raise ValueError(
            f"malformed vote batch: count {n} exceeds payload "
            f"({len(buf)} bytes)")
    return n


def check_votes2(buf: bytes) -> int:
    """Validate a packed Phase2bVotes payload; returns the count. The
    message codec calls this inside decode so a malformed payload is
    dropped by the transport's corrupt-frame guard, never reaching an
    actor."""
    return _check_count(buf, _VOTE2_DTYPE.itemsize)


def unpack_votes2(buf: bytes) -> tuple[np.ndarray, np.ndarray]:
    n = check_votes2(buf)
    lib = load()
    if lib is None:
        rec = np.frombuffer(buf, dtype=_VOTE2_DTYPE, count=n, offset=4)
        return rec["slot"].copy(), rec["round"].copy()
    slots = np.empty(n, dtype=np.int64)
    rounds = np.empty(n, dtype=np.int32)
    got = lib.fpx_unpack_votes2(
        _as_u8p(buf), len(buf),
        slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        rounds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n)
    if got < 0:
        raise ValueError("malformed vote batch")
    return slots, rounds


def unpack_votes(buf: bytes) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    n = _check_count(buf, 12)  # 3 x i32 records
    lib = load()
    if lib is None:
        flat = np.frombuffer(buf, dtype="<i4", count=3 * n, offset=4)
        triples = flat.reshape(n, 3)
        return (triples[:, 0].copy(), triples[:, 1].copy(),
                triples[:, 2].copy())
    slots = np.empty(n, dtype=np.int32)
    nodes = np.empty(n, dtype=np.int32)
    rounds = np.empty(n, dtype=np.int32)
    got = lib.fpx_unpack_votes(
        _as_u8p(buf), len(buf),
        slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        nodes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        rounds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n)
    if got < 0:
        raise ValueError("malformed vote batch")
    return slots, nodes, rounds
