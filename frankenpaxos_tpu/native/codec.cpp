// Native wire codec for the frankenpaxos_tpu transport hot path.
//
// The reference's only performance-critical native-adjacent component is
// its Netty NIO TCP stack (SURVEY.md section 0; build.sbt:31): framing and
// byte shuffling on the JVM's native transport. This is our equivalent:
// a small C++ library (loaded via ctypes) implementing
//
//   * length-prefixed frame encoding/decoding compatible with
//     runtime/tcp_transport.py's format:
//       [u32 total][u32 header_len][header "host:port"][payload]
//     including batch encoding (coalesce many frames into one write
//     buffer, the send_no_flush/flush path), and
//
//   * the Phase2b vote-batch codec: pack/unpack arrays of
//     (slot, acceptor, round) int32 triples -- the wire format that feeds
//     TpuQuorumChecker.record_and_check without any per-message Python
//     object churn.
//
// Build: g++ -O3 -shared -fPIC codec.cpp -o libfpxcodec.so (done lazily by
// native/__init__.py, cached next to the source).

#include <cstdint>
#include <cstring>

namespace {

inline void put_u32_be(uint8_t* p, uint32_t x) {
  p[0] = static_cast<uint8_t>(x >> 24);
  p[1] = static_cast<uint8_t>(x >> 16);
  p[2] = static_cast<uint8_t>(x >> 8);
  p[3] = static_cast<uint8_t>(x);
}

inline uint32_t get_u32_be(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 24) |
         (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | static_cast<uint32_t>(p[3]);
}

constexpr uint32_t kMaxFrame = 10 * 1024 * 1024;  // NettyTcpTransport's cap

}  // namespace

extern "C" {

// Encode one frame into `out`. Returns bytes written, or -1 if `out_cap`
// is too small, or -2 if the frame would exceed the 10 MiB cap.
long long fpx_encode_frame(const uint8_t* header, uint32_t header_len,
                           const uint8_t* payload, uint32_t payload_len,
                           uint8_t* out, uint64_t out_cap) {
  const uint64_t inner = 4ull + header_len + payload_len;
  const uint64_t total = 4ull + inner;
  if (inner > kMaxFrame) return -2;
  if (total > out_cap) return -1;
  put_u32_be(out, static_cast<uint32_t>(inner));
  put_u32_be(out + 4, header_len);
  std::memcpy(out + 8, header, header_len);
  std::memcpy(out + 8 + header_len, payload, payload_len);
  return static_cast<long long>(total);
}

// Coalesce `n` frames (shared header) into one buffer. `payloads` is a
// contiguous blob; `payload_lens[i]` gives each payload's length. Returns
// total bytes written or -1/-2 as above.
long long fpx_encode_frames(const uint8_t* header, uint32_t header_len,
                            const uint8_t* payloads,
                            const uint32_t* payload_lens, uint32_t n,
                            uint8_t* out, uint64_t out_cap) {
  uint64_t written = 0;
  uint64_t offset = 0;
  for (uint32_t i = 0; i < n; ++i) {
    long long r =
        fpx_encode_frame(header, header_len, payloads + offset,
                         payload_lens[i], out + written, out_cap - written);
    if (r < 0) return r;
    written += static_cast<uint64_t>(r);
    offset += payload_lens[i];
  }
  return static_cast<long long>(written);
}

// Scan `buf` for complete frames. Writes up to `max_frames` (start, end)
// byte offsets of each frame's inner region (header_len prefix included)
// into `offsets` (2 entries per frame). Returns the number of complete
// frames found; `*consumed` is set to the end of the last complete frame.
long long fpx_scan_frames(const uint8_t* buf, uint64_t len,
                          uint64_t* offsets, uint32_t max_frames,
                          uint64_t* consumed) {
  uint64_t pos = 0;
  uint32_t found = 0;
  while (found < max_frames && pos + 4 <= len) {
    const uint32_t inner = get_u32_be(buf + pos);
    if (inner > kMaxFrame) return -2;
    if (pos + 4 + inner > len) break;
    offsets[2 * found] = pos + 4;
    offsets[2 * found + 1] = pos + 4 + inner;
    pos += 4ull + inner;
    ++found;
  }
  *consumed = pos;
  return found;
}

// --- paxwire batch frames ---------------------------------------------------
// A batch frame coalesces a drain's same-type messages to one peer into
// ONE wire frame. Its payload is
//   [0x00][batch tag - 128][u32le count][count * u32le seg_len][segments]
// (the leading two bytes are a normal extended-page wire tag, so the
// frame-layer lane classifier in serve/lanes.py reads batch frames like
// any other codec'd message -- no decode needed to shed or spare them).
// The segments are the messages' ordinary wire payloads, copied raw: a
// run/reply-array whose value bytes are LazyValueArray segments is
// batched without ever re-materializing a value.

// Write the batch payload HEADER (escape, tag byte, count, lens) in one
// call -- the vectorized replacement for count * struct.pack on the hot
// flush path. Returns bytes written or -1 if out_cap is too small.
long long fpx_batch_header(uint8_t tag_byte, const uint32_t* seg_lens,
                           uint32_t n, uint8_t* out, uint64_t out_cap) {
  const uint64_t total = 2ull + 4ull + 4ull * n;
  if (total > out_cap) return -1;
  out[0] = 0;  // extended-page escape
  out[1] = tag_byte;
  std::memcpy(out + 2, &n, 4);  // little-endian like every codec field
  std::memcpy(out + 6, seg_lens, 4ull * n);
  return static_cast<long long>(total);
}

// Scan a batch payload's segment table. `buf` points AT the u32 count
// (the 0x00 + tag bytes already consumed); writes (start, end) offsets
// relative to `buf` into `offsets` (2 per segment). Returns the segment
// count, or -1 if the table is malformed (count/lens exceeding `len` --
// the containment contract: a torn or hostile batch frame must fail
// validation here, before any consumer trusts a length).
long long fpx_scan_batch(const uint8_t* buf, uint64_t len,
                         uint64_t* offsets, uint32_t max_segs) {
  if (len < 4) return -1;
  uint32_t n;
  std::memcpy(&n, buf, 4);
  if (n > max_segs) return -1;
  if (4ull + 4ull * n > len) return -1;
  uint64_t at = 4ull + 4ull * n;
  for (uint32_t i = 0; i < n; ++i) {
    uint32_t seg_len;
    std::memcpy(&seg_len, buf + 4 + 4ull * i, 4);
    if (at + seg_len > len) return -1;
    offsets[2 * i] = at;
    offsets[2 * i + 1] = at + seg_len;
    at += seg_len;
  }
  if (at != len) return -1;  // trailing garbage = torn/corrupt frame
  return n;
}

// --- paxingest: wire-to-run-pipeline column scan ----------------------------
// The zero-object decode path (frankenpaxos_tpu/ingest/, docs/TRANSPORT.md):
// a ClientFrameBatch arriving on the wire scans ONCE into SoA columns and
// the run pipeline's value-array segment, so no per-message Python object
// (Command/ClientRequest/CommandId) ever materializes between recv() and
// the leader's Phase2aRun.
//
// Input: the batch payload with the two leading tag bytes consumed (`buf`
// points AT the u32 segment count, exactly like fpx_scan_batch). Every
// segment must be a client-write payload, either shape:
//   tag 4 (ClientRequest):
//     [0x04][address][i64 pseudonym][i64 client_id][u32 len][cmd bytes]
//   tag 115 (ClientRequestArray -- the coalescing client's shape; ONE
//   address covers all its commands):
//     [0x73][address][i32 n][n * (i64 pseudonym, i64 id, u32 len, bytes)]
//   address = [u8 kind][u32 len][bytes]([i32 port] when kind == 1)
//
// Output:
//   * `out` receives the RUN-PIPELINE VALUE ARRAY segment -- the exact
//     byte layout multipaxos/wire.py's _put_value_array produces for a
//     one-CommandBatch-per-command run (deduped address table in
//     first-seen order, then per-command bodies). A LazyValueArray over
//     these bytes re-encodes as a raw copy all the way to the acceptors.
//   * `cols` receives n rows of 5 int64 columns: (addr_idx, pseudonym,
//     client_id, value_off, value_len), value offsets ABSOLUTE into
//     `buf` -- the descriptor the reply path consumes without decoding.
//
// Returns the command count; -1 = malformed (torn/corrupt -- the caller
// surfaces ValueError through the transport's corrupt-frame guard);
// -2 = out_cap too small; -3 = well-formed but unsupported shape (mixed
// tags, exotic address kind, trailing bytes): the caller falls back to
// the ordinary per-message decode, which defines the semantics.

namespace {
constexpr uint32_t kMaxIngestAddrs = 4096;
}

long long fpx_ingest_scan(const uint8_t* buf, uint64_t len, uint8_t* out,
                          uint64_t out_cap, uint64_t* out_len,
                          int64_t* cols, uint32_t max_cmds) {
  if (len < 4) return -1;
  uint32_t n;
  std::memcpy(&n, buf, 4);
  // Corruption checks strictly before shape checks (the Python
  // fallback mirrors this order bit-for-bit).
  if (4ull + 4ull * n > len) return -1;
  if (n > max_cmds) return -3;
  // Segment table (same validation contract as fpx_scan_batch).
  uint64_t at = 4ull + 4ull * n;
  // Pass A: validate every segment, dedup addresses by raw bytes.
  uint64_t addr_off[kMaxIngestAddrs];
  uint64_t addr_len[kMaxIngestAddrs];
  uint32_t n_addrs = 0;
  uint64_t table_bytes = 0;
  uint64_t body_bytes = 0;
  uint64_t seg_at = at;
  uint64_t cmds = 0;
  for (uint32_t i = 0; i < n; ++i) {
    uint32_t seg_len;
    std::memcpy(&seg_len, buf + 4 + 4ull * i, 4);
    if (seg_at + seg_len > len) return -1;
    const uint8_t* seg = buf + seg_at;
    if (seg_len < 2) return -1;
    const uint8_t tag = seg[0];
    if (tag != 4 && tag != 115) return -3;  // not a client write
    const uint8_t kind = seg[1];
    if (seg_len < 1 + 5) return -1;
    uint32_t alen;
    std::memcpy(&alen, seg + 2, 4);
    uint64_t a_end = 1ull + 5ull + alen;  // past [kind][len][bytes]
    if (kind == 1) {
      a_end += 4;  // [i32 port]
    } else if (kind != 0 && kind != 2) {
      return -3;  // unknown address kind: let Python decode decide
    }
    if (a_end > seg_len) return -1;
    // Dedup the address raw bytes [1, a_end).
    const uint64_t araw_len = a_end - 1;
    uint32_t idx = n_addrs;
    for (uint32_t a = 0; a < n_addrs; ++a) {
      if (addr_len[a] == araw_len
          && std::memcmp(buf + addr_off[a], seg + 1, araw_len) == 0) {
        idx = a;
        break;
      }
    }
    if (idx == n_addrs) {
      if (n_addrs == kMaxIngestAddrs) return -3;
      addr_off[n_addrs] = seg_at + 1;
      addr_len[n_addrs] = araw_len;
      table_bytes += araw_len;
      ++n_addrs;
    }
    uint64_t entry_at;   // first (pseudonym, id, len, bytes) entry
    uint64_t n_entries;
    if (tag == 4) {
      entry_at = a_end;
      n_entries = 1;
    } else {
      if (a_end + 4 > seg_len) return -1;
      uint32_t k;
      std::memcpy(&k, seg + a_end, 4);
      entry_at = a_end + 4;
      n_entries = k;
    }
    for (uint64_t e = 0; e < n_entries; ++e) {
      if (entry_at + 20 > seg_len) return -1;
      uint32_t vlen;
      std::memcpy(&vlen, seg + entry_at + 16, 4);
      if (entry_at + 20ull + vlen > seg_len) return -1;
      if (cmds == max_cmds) return -3;
      // body entry: [u8 1][i32 1][i32 idx][i64 pseudonym][i64 id]
      //             [u32 vlen][payload]
      body_bytes += 1 + 4 + 20 + 4 + vlen;
      cols[5ull * cmds + 0] = idx;
      int64_t pseudonym, client_id;
      std::memcpy(&pseudonym, seg + entry_at, 8);
      std::memcpy(&client_id, seg + entry_at + 8, 8);
      cols[5ull * cmds + 1] = pseudonym;
      cols[5ull * cmds + 2] = client_id;
      cols[5ull * cmds + 3] =
          static_cast<int64_t>(seg_at + entry_at + 20);
      cols[5ull * cmds + 4] = vlen;
      ++cmds;
      entry_at += 20ull + vlen;
    }
    if (entry_at != seg_len) return -3;  // trailing bytes
    seg_at += seg_len;
  }
  if (seg_at != len) return -1;  // trailing garbage = torn/corrupt
  const uint64_t total = 4 + table_bytes + body_bytes;
  if (total > out_cap) return -2;
  // Pass B: write [i32 t][addresses][bodies].
  std::memcpy(out, &n_addrs, 4);
  uint64_t w = 4;
  for (uint32_t a = 0; a < n_addrs; ++a) {
    std::memcpy(out + w, buf + addr_off[a], addr_len[a]);
    w += addr_len[a];
  }
  const uint32_t one = 1;
  for (uint64_t i = 0; i < cmds; ++i) {
    out[w] = 1;
    std::memcpy(out + w + 1, &one, 4);
    const uint32_t idx = static_cast<uint32_t>(cols[5ull * i + 0]);
    std::memcpy(out + w + 5, &idx, 4);
    std::memcpy(out + w + 9, &cols[5ull * i + 1], 8);
    std::memcpy(out + w + 17, &cols[5ull * i + 2], 8);
    const uint32_t vlen = static_cast<uint32_t>(cols[5ull * i + 4]);
    std::memcpy(out + w + 25, &vlen, 4);
    std::memcpy(out + w + 29, buf + cols[5ull * i + 3], vlen);
    w += 29ull + vlen;
  }
  *out_len = w;
  return static_cast<long long>(cmds);
}

// Columns from a VALUE-ARRAY raw segment (LazyValueArray.raw: the layout
// fpx_ingest_scan emits and _put_value_array writes). Supports exactly
// the ingest-plane shape -- every entry a one-command CommandBatch --
// and returns -3 for anything else (noops, multi-command batches) so
// consumers fall back to the decoding path. Value offsets are ABSOLUTE
// into `buf`. `n` is the declared entry count (LazyValueArray.n).
long long fpx_value_columns(const uint8_t* buf, uint64_t len, int64_t* cols,
                            uint32_t max_cmds, uint32_t n) {
  if (len < 4 || n > max_cmds) return n > max_cmds ? -3 : -1;
  uint32_t t;
  std::memcpy(&t, buf, 4);
  uint64_t at = 4;
  // Walk the address table to find where bodies start.
  for (uint32_t a = 0; a < t; ++a) {
    if (at + 5 > len) return -1;
    const uint8_t kind = buf[at];
    uint32_t alen;
    std::memcpy(&alen, buf + at + 1, 4);
    at += 5ull + alen;
    if (kind == 1) at += 4;
    else if (kind != 0 && kind != 2) return -3;
    if (at > len) return -1;
  }
  for (uint32_t i = 0; i < n; ++i) {
    if (at + 1 > len) return -1;
    if (buf[at] != 1) return -3;  // noop or exotic value
    if (at + 5 > len) return -1;
    uint32_t k;
    std::memcpy(&k, buf + at + 1, 4);
    if (k != 1) return -3;  // multi-command batch
    if (at + 5 + 20 + 4 > len) return -1;
    uint32_t idx;
    std::memcpy(&idx, buf + at + 5, 4);
    if (idx >= t) return -1;
    int64_t pseudonym, client_id;
    std::memcpy(&pseudonym, buf + at + 9, 8);
    std::memcpy(&client_id, buf + at + 17, 8);
    uint32_t vlen;
    std::memcpy(&vlen, buf + at + 25, 4);
    if (at + 29ull + vlen > len) return -1;
    cols[5ull * i + 0] = idx;
    cols[5ull * i + 1] = pseudonym;
    cols[5ull * i + 2] = client_id;
    cols[5ull * i + 3] = static_cast<int64_t>(at + 29);
    cols[5ull * i + 4] = vlen;
    at += 29ull + vlen;
  }
  if (at != len) return -1;
  return n;
}

// --- paxfan reply columns (ingest/columns.py, docs/TRANSPORT.md) -----------
// A ClientReplyArray payload's entries as SoA columns -- the RETURN-path
// twin of fpx_ingest_scan. ``buf`` starts at the i32 entry count (the
// leading tag byte already consumed by the caller). Entry layout
// (protocols/multipaxos/wire.py ClientReplyArrayCodec, tag 118):
//   [i64 pseudonym][i64 client_id][i64 slot][u32 result_len][result]
// cols rows are (pseudonym, client_id, slot, result_off, result_len),
// offsets relative to ``buf``. Returns n >= 0 on success, -1 on a
// torn/corrupt payload, -2 when the count exceeds the caller's cap.
long long fpx_reply_columns(const uint8_t* buf, uint64_t len, int64_t* cols,
                            uint32_t cap) {
  if (len < 4) return -1;
  int32_t n_signed;
  std::memcpy(&n_signed, buf, 4);
  if (n_signed < 0) return -1;
  const uint32_t n = static_cast<uint32_t>(n_signed);
  // Every entry consumes >= 28 bytes, so a count past len / 28 is torn
  // regardless of cap -- checked BEFORE the cap so hostile counts are
  // corruption, not a silent fallback.
  if (4ull + 28ull * n > len) return -1;
  if (n > cap) return -2;
  uint64_t at = 4;
  for (uint32_t i = 0; i < n; ++i) {
    if (at + 28 > len) return -1;
    int64_t pseudonym, client_id, slot;
    std::memcpy(&pseudonym, buf + at, 8);
    std::memcpy(&client_id, buf + at + 8, 8);
    std::memcpy(&slot, buf + at + 16, 8);
    uint32_t rlen;
    std::memcpy(&rlen, buf + at + 24, 4);
    if (at + 28ull + rlen > len) return -1;
    cols[5ull * i + 0] = pseudonym;
    cols[5ull * i + 1] = client_id;
    cols[5ull * i + 2] = slot;
    cols[5ull * i + 3] = static_cast<int64_t>(at + 28);
    cols[5ull * i + 4] = rlen;
    at += 28ull + rlen;
  }
  if (at != len) return -1;
  return n;
}

// --- Phase2b vote-batch codec ---------------------------------------------
// Wire layout: [u32 count][count * (i32 slot, i32 node, i32 round)] with
// little-endian fixed-width ints (the host side hands these straight to
// TpuQuorumChecker as numpy arrays).

long long fpx_pack_votes(const int32_t* slots, const int32_t* nodes,
                         const int32_t* rounds, uint32_t n, uint8_t* out,
                         uint64_t out_cap) {
  const uint64_t total = 4ull + 12ull * n;
  if (total > out_cap) return -1;
  std::memcpy(out, &n, 4);
  int32_t* p = reinterpret_cast<int32_t*>(out + 4);
  for (uint32_t i = 0; i < n; ++i) {
    p[3 * i] = slots[i];
    p[3 * i + 1] = nodes[i];
    p[3 * i + 2] = rounds[i];
  }
  return static_cast<long long>(total);
}

// Returns the vote count, filling the three output arrays (each with
// capacity `cap`), or -1 on malformed input.
long long fpx_unpack_votes(const uint8_t* buf, uint64_t len, int32_t* slots,
                           int32_t* nodes, int32_t* rounds, uint32_t cap) {
  if (len < 4) return -1;
  uint32_t n;
  std::memcpy(&n, buf, 4);
  if (len < 4ull + 12ull * n || n > cap) return -1;
  const int32_t* p = reinterpret_cast<const int32_t*>(buf + 4);
  for (uint32_t i = 0; i < n; ++i) {
    slots[i] = p[3 * i];
    nodes[i] = p[3 * i + 1];
    rounds[i] = p[3 * i + 2];
  }
  return n;
}

// Two-column variant for SINGLE-acceptor batches (Phase2bVotes): the
// acceptor's identity travels in the message header, so packing a node
// column would ship 4 dead bytes per vote. Slots are i64 like every
// other slot on the wire (Phase2b/Phase2bRange carry '<q' slots); a
// 12-byte packed record, memcpy'd because entries are unaligned.
// Wire layout: [u32 count][count * (i64 slot, i32 round)].
long long fpx_pack_votes2(const int64_t* slots, const int32_t* rounds,
                          uint32_t n, uint8_t* out, uint64_t out_cap) {
  const uint64_t total = 4ull + 12ull * n;
  if (total > out_cap) return -1;
  std::memcpy(out, &n, 4);
  uint8_t* p = out + 4;
  for (uint32_t i = 0; i < n; ++i) {
    std::memcpy(p, &slots[i], 8);
    std::memcpy(p + 8, &rounds[i], 4);
    p += 12;
  }
  return static_cast<long long>(total);
}

long long fpx_unpack_votes2(const uint8_t* buf, uint64_t len,
                            int64_t* slots, int32_t* rounds, uint32_t cap) {
  if (len < 4) return -1;
  uint32_t n;
  std::memcpy(&n, buf, 4);
  if (len < 4ull + 12ull * n || n > cap) return -1;
  const uint8_t* p = buf + 4;
  for (uint32_t i = 0; i < n; ++i) {
    std::memcpy(&slots[i], p, 8);
    std::memcpy(&rounds[i], p + 8, 4);
    p += 12;
  }
  return n;
}

}  // extern "C"
