"""GeoQuorumTracker: per-object-group vote counting over epoch planes.

The paxgeo twin of ``reconfig.tracker.EpochQuorumTracker``. Each
object group's slot space is partitioned by its steal epochs, and each
epoch's Phase2 predicate is a ``ZoneGrid.home_write_spec`` -- a
majority of the home zone's row over the full grid universe. Two
backends, bit-identical (tests/test_geo.py):

  * ``dict`` -- the oracle: per-(slot, ballot) voter sets checked with
    ``QuorumSpec.check`` against the slot's epoch plane.
  * ``tpu`` -- one ``ops.quorum.EpochSegmentedChecker`` scatter per
    event-loop drain; the plane is selected per slot INSIDE the fused
    kernel, so a drain spanning a steal handover stays one dispatch --
    the specs feed the checker UNCHANGED, which is the point: the
    fused TPU quorum machinery already speaks flexible grid quorums.

Both report each (slot, ballot)'s quorum exactly once.
"""

from __future__ import annotations

import numpy as np

from frankenpaxos_tpu.geo.epochs import ObjectEpochStore
from frankenpaxos_tpu.quorums import ZoneGrid


class GeoQuorumTracker:
    def __init__(self, store: ObjectEpochStore, group: int,
                 grid: ZoneGrid, backend: str = "dict",
                 window: int = 4096, mesh=None):
        """``mesh``: optional ``jax.sharding.Mesh`` for the tpu
        backend -- the checker's board shards its slot axis over the
        mesh with the epoch planes replicated (the ZoneGrid steal
        planes ride the same rule as every epoch plane; see
        EpochSegmentedChecker). Ignored by the dict oracle."""
        if backend not in ("dict", "tpu"):
            raise ValueError(f"unknown geo tracker backend {backend!r}")
        self.store = store
        self.group = group
        self.grid = grid
        self.backend = backend
        self.window = window
        self.mesh = mesh
        self._known = store.known(group)
        # dict backend: (slot, ballot) -> set of acceptor ids; None
        # once reported (Done).
        self._states: dict = {}
        self._newly: list = []
        # tpu backend: per-drain vote buffer + the segmented checker.
        self._checker = None
        self._slots: list = []
        self._cols: list = []
        self._ballots: list = []
        self._chunk = 256
        if backend == "tpu":
            self._build_checker()

    def _specs_and_starts(self) -> tuple:
        chain = self.store.known(self.group)
        return ([self.grid.home_write_spec(e.home_zone) for e in chain],
                [e.start_slot for e in chain])

    def _build_checker(self) -> None:
        from frankenpaxos_tpu.ops.quorum import EpochSegmentedChecker

        specs, starts = self._specs_and_starts()
        self._checker = EpochSegmentedChecker(specs, starts,
                                              window=self.window,
                                              mesh=self.mesh)
        # Prewarm the scatter buckets before client traffic.
        self._checker.record_and_check([0], [0], [-1])
        self._checker.release([0])

    def note_epochs(self) -> None:
        """Refresh after the store committed a steal. Pure appends
        extend the checker's plane stack in place (the universe is the
        fixed grid, so columns never move); a ballot-superseded newest
        epoch (a lost steal race) rebuilds it, dropping buffered votes
        -- they voted for the superseded owner's proposals, which
        protocol-level resends re-drive."""
        known = self.store.known(self.group)
        if known == self._known:
            return
        if self._checker is not None:
            if known[:len(self._known)] == self._known:
                for entry in known[len(self._known):]:
                    self._checker.add_epoch(
                        self.grid.home_write_spec(entry.home_zone),
                        entry.start_slot)
            else:
                self._build_checker()
                self._slots, self._cols, self._ballots = [], [], []
        self._known = known

    # --- recording (per message, O(1) Python) -------------------------------
    def record(self, slot: int, ballot: int, acceptor: int) -> None:
        if self.backend == "dict":
            self._record_dict(slot, ballot, acceptor)
            return
        self._slots.append(slot)
        self._cols.append(acceptor)
        self._ballots.append(ballot)

    def _record_dict(self, slot: int, ballot: int, acceptor: int) -> None:
        key = (slot, ballot)
        votes = self._states.get(key)
        if votes is None and key in self._states:
            return  # Done
        if votes is None:
            votes = set()
            self._states[key] = votes
        votes.add(acceptor)
        entry = self.store.epoch_of_slot(self.group, slot)
        spec = self.grid.home_write_spec(entry.home_zone)
        if spec.check(votes):
            self._states[key] = None
            self._newly.append(key)

    # --- drain --------------------------------------------------------------
    def drain(self) -> list:
        """Newly complete ``(slot, ballot)`` quorums since the last
        drain (one fused kernel dispatch per drain on the tpu
        backend)."""
        if self.backend == "dict":
            newly, self._newly = self._newly, []
            return newly
        if not self._slots:
            return []
        slots = np.asarray(self._slots, dtype=np.int64)
        cols = np.asarray(self._cols, dtype=np.int32)
        ballots = np.asarray(self._ballots, dtype=np.int32)
        self._slots, self._cols, self._ballots = [], [], []
        out: list = []
        seen: set = set()
        for at in range(0, slots.size, self._chunk):
            sl = slots[at:at + self._chunk]
            newly = self._checker.record_and_check(
                sl, cols[at:at + self._chunk],
                ballots[at:at + self._chunk])
            for i in np.flatnonzero(newly).tolist():
                key = (int(sl[i]), int(ballots[at + i]))
                if key[0] not in seen:
                    seen.add(key[0])
                    out.append(key)
        return out

    def release(self, slots) -> None:
        """Watermark GC passthrough (ring wrap for the tpu board)."""
        if self._checker is not None and len(slots):
            self._checker.release(np.asarray(slots))
