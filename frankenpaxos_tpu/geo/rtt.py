"""RttEstimator: EWMA + mean-deviation timeout bounds.

The Jacobson/Karels retransmission estimator (SIGCOMM '88, the TCP
RTO): a smoothed RTT plus a smoothed mean deviation, with the timeout
at ``srtt + k * dev``. Fixed protocol timeouts false-positive the
moment links have real latency and jitter (a 5s heartbeat deadline is
fine on localhost and fatal across a degraded WAN link with 10s
brownouts); every geo-aware timer -- heartbeat fail periods, election
no-ping timeouts, client resends -- derives its delay from one of
these instead (docs/GEO.md).
"""

from __future__ import annotations


class RttEstimator:
    def __init__(self, alpha: float = 0.125, beta: float = 0.25,
                 k: float = 4.0, floor_s: float = 1e-4,
                 ceil_s: float = 120.0):
        if not 0 < alpha <= 1 or not 0 < beta <= 1:
            raise ValueError(f"gains outside (0, 1]: {alpha}, {beta}")
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.floor_s = floor_s
        self.ceil_s = ceil_s
        self.srtt: float | None = None
        self.dev: float = 0.0
        self.samples = 0

    def observe(self, rtt_s: float) -> None:
        rtt_s = max(0.0, rtt_s)
        if self.srtt is None:
            # First sample: the classic initialization (dev = rtt/2
            # keeps the first timeout conservative).
            self.srtt = rtt_s
            self.dev = rtt_s / 2
        else:
            err = rtt_s - self.srtt
            self.srtt += self.alpha * err
            self.dev += self.beta * (abs(err) - self.dev)
        self.samples += 1

    def timeout(self, default_s: float) -> float:
        """The adaptive deadline, or ``default_s`` before any sample
        has arrived. Clamped to ``[floor_s, ceil_s]`` so a zero-RTT
        sim link cannot spin a timer and a wedged link cannot push
        the deadline out forever."""
        if self.srtt is None:
            return default_s
        return min(self.ceil_s,
                   max(self.floor_s, self.srtt + self.k * self.dev))
