"""ObjectEpochStore: one paxepoch-flavored epoch chain PER OBJECT GROUP.

The paxgeo twin of ``reconfig.epoch.EpochStore``. There, an epoch is a
membership era of ONE acceptor set; here the acceptor grid is fixed
and an epoch is a LEADERSHIP era of one object group -- which zone's
leader owns the group, at which ballot, from which slot. An object
STEAL is an epoch change: the stealing leader's cross-zone Phase1
doubles as the epoch's commit round (promises are WAL-durable before
the Phase1b ack leaves the acceptor, so a row-majority of old-home
durable acks is the commit point -- the f+1-old-epoch-acks rule of
docs/RECONFIG.md, inherited wholesale), and the new epoch's
``start_slot`` is the watermark-bounded handover: slots below it are
provably chosen and stay with the old era's history; everything at or
above transfers to the new home zone's quorum plane.

Entries are BALLOT-monotone per epoch id exactly as EpochStore entries
are round-monotone: two leaders racing to steal the same group
serialize on ballots, and the loser's unactivated definition is
superseded.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence


@dataclasses.dataclass(frozen=True)
class GeoEpoch:
    """One leadership era of one object group: slots >=
    ``start_slot`` (until the next epoch's start) commit through
    ``home_zone``'s row at ballots owned by that zone's leader."""

    group: int
    epoch: int
    start_slot: int
    home_zone: int
    ballot: int


class ObjectEpochStore:
    """group id -> its epoch chain, with slot -> epoch resolution.

    THE single authority for object-placement reads in paxgeo handler
    code: request routing, steal targets, and per-slot quorum planes
    all resolve through ``current`` / ``epoch_of_slot`` so a committed
    steal reaches every path at once (the PAX110 discipline, applied
    to object leadership)."""

    def __init__(self, num_groups: int, initial_home: Sequence[int]):
        if len(initial_home) != num_groups:
            raise ValueError(
                f"{len(initial_home)} initial homes != {num_groups} groups")
        self.num_groups = num_groups
        # Epoch 0 of every group is config-agreed: home zone z at
        # ballot z (each zone's leader owns ballots == its zone index
        # mod num_zones, so epoch 0 needs no Phase1 -- the multipaxos
        # round-0-implicit-Phase1 convention).
        self._chains: list[list[GeoEpoch]] = [
            [GeoEpoch(group=g, epoch=0, start_slot=0,
                      home_zone=home, ballot=home)]
            for g, home in enumerate(initial_home)]
        #: Bumped on every offer that changes state; trackers compare
        #: it to decide between appending planes and a rebuild.
        self.version = 0

    # --- reads ------------------------------------------------------------
    def current(self, group: int) -> GeoEpoch:
        return self._chains[group][-1]

    def known(self, group: int) -> tuple:
        return tuple(self._chains[group])

    def epoch_of_slot(self, group: int, slot: int) -> GeoEpoch:
        for entry in reversed(self._chains[group]):
            if entry.start_slot <= slot:
                return entry
        return self._chains[group][0]

    def config(self, group: int, epoch: int) -> "GeoEpoch | None":
        chain = self._chains[group]
        i = epoch - chain[0].epoch
        if 0 <= i < len(chain):
            return chain[i]
        return None

    def max_ballot(self, group: int) -> int:
        return max(entry.ballot for entry in self._chains[group])

    # --- writes -----------------------------------------------------------
    def offer(self, entry: GeoEpoch) -> str:
        """Install a steal's epoch entry with ballot-monotone
        supersession (the ``EpochStore.offer`` contract):

          * ``"new"`` -- appended (the next contiguous epoch);
          * ``"replaced"`` -- the newest epoch's definition lost to a
            higher-ballot steal of the same epoch id;
          * ``"dup"`` -- already known at >= this ballot;
          * ``"stale"`` -- lower ballot for a known epoch, or an epoch
            id too far ahead to validate (the resend protocol delivers
            the gap first).
        """
        chain = self._chains[entry.group]
        known = self.config(entry.group, entry.epoch)
        if known is not None:
            i = entry.epoch - chain[0].epoch
            if entry.ballot < known.ballot:
                return "stale"
            if known == entry:
                return "dup"
            if entry.ballot == known.ballot:
                # One ballot belongs to one leader, which defines one
                # entry per epoch: an unequal twin is a stale resend
                # variant, never a fork.
                return "stale"
            if i != len(chain) - 1:
                # Activated definitions (their successor's commit
                # proves activation) are never superseded.
                return "stale"
            chain[i] = self._clamped(entry, chain[i - 1]
                                     if i > 0 else None)
            self.version += 1
            return "replaced"
        newest = chain[-1]
        if entry.epoch != newest.epoch + 1:
            return "stale"
        chain.append(self._clamped(entry, newest))
        self.version += 1
        return "new"

    @staticmethod
    def _clamped(entry: GeoEpoch, predecessor: "GeoEpoch | None"
                 ) -> GeoEpoch:
        """Keep start slots nondecreasing along OUR chain. Two
        stealers racing to define one epoch id serialize on ballots,
        but a store that adopted the loser's definition (larger
        start) can then hear a successor built on the winner's
        (smaller start) -- the chains genuinely diverge in their
        boundary bookkeeping. Clamping is safe: the per-epoch plane
        is each OWNER's local vote-counting rule (strictly stricter
        than the ZoneGrid write predicate), and chosen-uniqueness
        rests on ballots + Phase1 adoption, not on stores agreeing
        where one plane ends (docs/GEO.md)."""
        if predecessor is not None \
                and entry.start_slot < predecessor.start_slot:
            return dataclasses.replace(
                entry, start_slot=predecessor.start_slot)
        return entry
