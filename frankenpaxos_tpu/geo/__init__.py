"""paxgeo: wide-area simulation + per-object multi-leader machinery.

The geo layer has two halves (docs/GEO.md):

  * a SIMULATION substrate -- :class:`GeoTopology` (named zones
    grouped into regions, a per-link latency/jitter matrix sampled
    deterministically per seed, link-level partition/degrade controls)
    and :class:`GeoSimTransport` (a ``SimTransport`` whose deliveries
    are ordered by VIRTUAL ARRIVAL TIME, not FIFO enqueue, with a
    virtual-clock event loop for latency benchmarking); and

  * PROTOCOL machinery for WPaxos-style per-object leadership --
    :class:`ObjectEpochStore` (one paxepoch-flavored epoch chain per
    object group; an object steal is an epoch change),
    :class:`GeoQuorumTracker` (dict oracle / fused
    ``EpochSegmentedChecker`` vote counting over per-epoch
    ``ZoneGrid`` specs), and :class:`RttEstimator` (the EWMA +
    deviation timeout bound heartbeat/election/clients derive their
    timers from once links have real latency).
"""

from frankenpaxos_tpu.geo.epochs import GeoEpoch, ObjectEpochStore
from frankenpaxos_tpu.geo.quorum import GeoQuorumTracker
from frankenpaxos_tpu.geo.rtt import RttEstimator
from frankenpaxos_tpu.geo.topology import GeoTopology, Link
from frankenpaxos_tpu.geo.transport import GeoSimTimer, GeoSimTransport

__all__ = [
    "GeoEpoch",
    "GeoQuorumTracker",
    "GeoSimTimer",
    "GeoSimTransport",
    "GeoTopology",
    "Link",
    "ObjectEpochStore",
    "RttEstimator",
]
