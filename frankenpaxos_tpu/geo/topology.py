"""GeoTopology: named zones/regions + a per-link latency matrix.

The topology is pure POLICY: it owns zone placement, per-link base
latency/jitter parameters, and the chaos controls (partition, degrade,
heal), and it answers "how long does THIS frame take?" via
:meth:`sample_delay`. The mechanism -- buffering frames and delivering
them in virtual-arrival order -- lives in
:class:`~frankenpaxos_tpu.geo.transport.GeoSimTransport`.

DETERMINISM CONTRACT (enforced by paxlint GEO801 and the golden test
in tests/test_geo.py): nothing in the geo simulation layer may read a
wall clock or an unseeded RNG. Per-frame jitter is drawn from a
``random.Random`` seeded with a STRING key ``seed|src|dst|frame_id``
-- CPython hashes string seeds through sha512 (``Random.seed``
version 2), so the same seed produces byte-identical delay sequences
across processes and platforms, unlike ``hash()``-based keys under
PYTHONHASHSEED randomization.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Iterable, Mapping, Optional, Sequence

import numpy as np

from frankenpaxos_tpu.ops.simwave import UNPLACED_ZONE


@dataclasses.dataclass
class Link:
    """One directed zone pair's state. ``base_s`` is the ONE-WAY
    propagation delay; the RTT over the link is ``2 * base_s`` plus
    jitter. ``degrade`` multiplies the base (brownout chaos); ``up``
    False drops frames at delivery time (partition chaos)."""

    base_s: float
    jitter_s: float
    up: bool = True
    degrade: float = 1.0


class GeoTopology:
    """Zones grouped into regions, with a synthesized all-pairs link
    matrix: intra-zone links are near-free, intra-region links cheap,
    and cross-region links pay the WAN delay -- the three-tier model
    every wide-area Paxos evaluation uses (WPaxos section 6)."""

    def __init__(self, regions: Mapping[str, Sequence[str]],
                 intra_zone_s: float = 0.0005,
                 intra_region_s: float = 0.004,
                 cross_region_s: float = 0.040,
                 jitter: float = 0.05,
                 seed: int = 0):
        if not regions:
            raise ValueError("GeoTopology needs at least one region")
        self.region_of: dict[str, str] = {}
        self.zones: tuple[str, ...] = ()
        zones: list[str] = []
        for region in sorted(regions):
            for zone in regions[region]:
                if zone in self.region_of:
                    raise ValueError(f"zone {zone!r} in two regions")
                self.region_of[zone] = region
                zones.append(zone)
        self.zones = tuple(zones)
        self.intra_zone_s = intra_zone_s
        self.intra_region_s = intra_region_s
        self.cross_region_s = cross_region_s
        self.jitter = jitter
        self.seed = seed
        self._placement: dict = {}      # address -> zone name
        self._links: dict[tuple[str, str], Link] = {}
        # (src address, dst address) -> Link | None (None: at least
        # one endpoint unplaced => free, always-up). Link state
        # mutates IN PLACE (partition/degrade flip fields), so cached
        # entries stay live; only (re)placement invalidates.
        self._address_links: dict = {}
        # paxsim: integer zone ids for the vectorized wave masks.
        # ``_zone_ids`` indexes self.zones; ``_addr_zone_ids`` interns
        # per placed address (UNPLACED_ZONE for everything else).
        # ``up_matrix`` caches against ``_up_gen``, bumped by every
        # partition/heal (degrade does not change reachability).
        self._zone_ids: dict[str, int] = {z: i
                                          for i, z in enumerate(self.zones)}
        self._addr_zone_ids: dict = {}
        self._up_gen = 0
        self._up_cache: tuple = (None, -1)
        # One reusable MT instance for per-frame jitter: ``seed(key)``
        # runs the same version-2 string seeding as ``Random(key)``
        # (sha512, PYTHONHASHSEED-proof), so draws are BIT-IDENTICAL
        # to a fresh instance per key -- the goldens prove it -- at
        # about half the cost (no 2.5KB state allocation per frame).
        self._jitter_rng = random.Random(0)

    # --- placement --------------------------------------------------------
    def place(self, address, zone: str) -> None:
        if zone not in self.region_of:
            raise ValueError(f"unknown zone {zone!r}")
        self._placement[address] = zone
        self._addr_zone_ids[address] = self._zone_ids[zone]
        self._address_links.clear()

    def place_all(self, addresses: Iterable, zone: str) -> None:
        for address in addresses:
            self.place(address, zone)

    def zone_of(self, address) -> Optional[str]:
        """The address's zone; None for unplaced addresses (admin /
        chaos senders), which ride zero-latency always-up links."""
        return self._placement.get(address)

    # --- the link matrix --------------------------------------------------
    def link(self, src_zone: str, dst_zone: str) -> Link:
        key = (src_zone, dst_zone)
        state = self._links.get(key)
        if state is None:
            if src_zone == dst_zone:
                base = self.intra_zone_s
            elif self.region_of[src_zone] == self.region_of[dst_zone]:
                base = self.intra_region_s
            else:
                base = self.cross_region_s
            state = Link(base_s=base, jitter_s=base * self.jitter)
            self._links[key] = state
        return state

    def link_for(self, src, dst) -> Optional[Link]:
        """The (cached) link between two ADDRESSES; None when either
        endpoint is unplaced (free, always-up)."""
        key = (src, dst)
        try:
            return self._address_links[key]
        except KeyError:
            pass
        src_zone = self.zone_of(src)
        dst_zone = self.zone_of(dst)
        link = (None if src_zone is None or dst_zone is None
                else self.link(src_zone, dst_zone))
        self._address_links[key] = link
        return link

    def link_up(self, src, dst) -> bool:
        """Whether the link between two ADDRESSES is currently up
        (unplaced endpoints are always reachable)."""
        link = self.link_for(src, dst)
        return link is None or link.up

    def zone_id_of(self, address) -> int:
        """The address's integer zone id for the vectorized wave masks
        (``simwave.UNPLACED_ZONE`` when unplaced)."""
        return self._addr_zone_ids.get(address, UNPLACED_ZONE)

    def up_matrix(self) -> np.ndarray:
        """``[Z+1, Z+1]`` bool reachability by zone id: entry
        ``[s, d]`` is the directed link's ``up``; the last row/column
        (reached by ``UNPLACED_ZONE`` = -1 via numpy wraparound) is the
        always-up sentinel for unplaced endpoints. Cached against the
        partition/heal generation; links never materialized by
        :meth:`link` default to up, matching ``link_up``."""
        cached, gen = self._up_cache
        if cached is not None and gen == self._up_gen:
            return cached
        z = len(self.zones)
        up = np.ones((z + 1, z + 1), dtype=bool)
        zone_ids = self._zone_ids
        for (src, dst), link in self._links.items():
            if not link.up:
                up[zone_ids[src], zone_ids[dst]] = False
        self._up_cache = (up, self._up_gen)
        return up

    def sample_delay(self, src, dst, frame_id: int) -> float:
        """The one-way delay for frame ``frame_id`` from ``src`` to
        ``dst``, deterministic per (topology seed, zone pair, frame).
        Jitter is one-sided (adds to the base): the base delay is the
        physical floor."""
        link = self.link_for(src, dst)
        if link is None:
            return 0.0
        delay = link.base_s * link.degrade
        if link.jitter_s:
            rng = self._jitter_rng
            rng.seed(f"{self.seed}|{self._placement[src]}"
                     f"|{self._placement[dst]}|{frame_id}")
            delay += link.jitter_s * link.degrade * rng.random()
        return delay

    def rtt(self, zone_a: str, zone_b: str) -> float:
        """Base round-trip time between two zones (no jitter)."""
        return self.link(zone_a, zone_b).base_s \
            + self.link(zone_b, zone_a).base_s

    def wan_rtt(self) -> float:
        """The cross-region round trip -- the unit the steal-latency
        gate is expressed in (bench/geo_lt.py)."""
        return 2 * self.cross_region_s

    # --- chaos controls ---------------------------------------------------
    def partition_link(self, zone_a: str, zone_b: str,
                       both_ways: bool = True) -> None:
        self.link(zone_a, zone_b).up = False
        if both_ways:
            self.link(zone_b, zone_a).up = False
        self._up_gen += 1

    def heal_link(self, zone_a: str, zone_b: str,
                  both_ways: bool = True) -> None:
        self.link(zone_a, zone_b).up = True
        if both_ways:
            self.link(zone_b, zone_a).up = True
        self._up_gen += 1

    def degrade_link(self, zone_a: str, zone_b: str,
                     factor: float, both_ways: bool = True) -> None:
        """Multiply the pair's base delay (brownout; 1.0 restores)."""
        self.link(zone_a, zone_b).degrade = factor
        if both_ways:
            self.link(zone_b, zone_a).degrade = factor

    def partition_zone(self, zone: str) -> None:
        """Cut every link between ``zone`` and the rest of the world
        (intra-zone traffic keeps flowing -- the zone is isolated, not
        dead; process death is the transport's ``crash``)."""
        for other in self.zones:
            if other != zone:
                self.partition_link(zone, other)

    def heal_zone(self, zone: str) -> None:
        for other in self.zones:
            if other != zone:
                self.heal_link(zone, other)

    def partition_regions(self, region_a: str, region_b: str) -> None:
        """Cut every link crossing between two regions (the
        cross-region partition arm of the scenario matrix)."""
        for za in self.zones:
            if self.region_of[za] != region_a:
                continue
            for zb in self.zones:
                if self.region_of[zb] == region_b:
                    self.partition_link(za, zb)

    def heal_regions(self, region_a: str, region_b: str) -> None:
        for za in self.zones:
            if self.region_of[za] != region_a:
                continue
            for zb in self.zones:
                if self.region_of[zb] == region_b:
                    self.heal_link(za, zb)

    def heal_all(self) -> None:
        for link in self._links.values():
            link.up = True
            link.degrade = 1.0
        self._up_gen += 1
