"""GeoSimTransport: SimTransport with latency-ordered delivery.

Plain ``SimTransport`` buffers frames and lets the caller deliver them
in any order -- FIFO for integration tests, adversarially for the
randomized sims. The geo transport adds a VIRTUAL CLOCK: every send
samples its link's delay from the :class:`GeoTopology` matrix
(deterministic per seed) and stamps an arrival time, and the event
loop (:meth:`run_for` / :meth:`run_until`) delivers strictly in
arrival order -- so a zone-local ack genuinely overtakes a WAN frame
sent earlier, which is the whole phenomenon the wide-area suite
exists to exercise. Latencies measured against :attr:`now` are exact
virtual durations, which is what makes the bench gates in
``bench/geo_lt.py`` sharp instead of host-noise-bound.

Event scheduling is a pair of LAZY HEAPS (arrival times, timer
deadlines): push on send/start, validate against the authoritative
dicts on pop -- out-of-band removals (adversarial deliveries, link
drops, timer stops) just leave stale heap entries to be skipped, so
every per-event operation is O(log n) instead of a buffer scan.

The adversarial simulator API is unchanged: ``generate_command`` /
``deliver_message`` still deliver ANY buffered frame, so the chaos
sims explore reorderings beyond what latencies would produce, with
link partitions/degrades applied at delivery time. The bounded-inbox
admission path is NOT armed here (geo harnesses attach no admission
controllers); arrival stamping covers synthesized reject replies
anyway because stamps are derived per buffered frame.
"""

from __future__ import annotations

import heapq
from typing import Optional

from frankenpaxos_tpu.geo.topology import GeoTopology
from frankenpaxos_tpu.runtime.logger import Logger
from frankenpaxos_tpu.runtime.sim_transport import (
    SimMessage,
    SimTimer,
    SimTransport,
)
from frankenpaxos_tpu.runtime.transport import Address


class GeoSimTimer(SimTimer):
    """A SimTimer with a virtual deadline: (re)stamped from the
    transport's clock on every start, so the event loop fires it at
    ``now + delay_s`` like a real timer wheel."""

    def start(self) -> None:
        super().start()
        deadline = self._transport.now + self.delay_s
        self._transport._deadlines[self._id] = deadline
        heapq.heappush(self._transport._deadline_heap,
                       (deadline, self._id))

    def stop(self) -> None:
        super().stop()
        self._transport._deadlines.pop(self._id, None)


class GeoSimTransport(SimTransport):
    def __init__(self, topology: GeoTopology,
                 logger: Optional[Logger] = None):
        super().__init__(logger)
        self.topology = topology
        #: The virtual clock, in seconds. Advanced only by the event
        #: loop (never by wall time -- determinism contract, GEO801).
        self.now = 0.0
        #: message id -> virtual arrival time (authoritative; heap
        #: entries are valid only while they match).
        self.arrivals: dict[int, float] = {}
        self._by_id: dict[int, SimMessage] = {}
        self._arrival_heap: list = []
        #: timer id -> virtual deadline (running timers only).
        self._deadlines: dict[int, float] = {}
        self._deadline_heap: list = []

    # --- sending ----------------------------------------------------------
    def send(self, src: Address, dst: Address, data: bytes) -> None:
        before = len(self.messages)
        super().send(src, dst, data)
        # Stamp every frame this send buffered (the frame itself, plus
        # any reject replies a bounded inbox synthesized), each over
        # its OWN link.
        for message in self.messages[before:]:
            arrival = self.now + self.topology.sample_delay(
                message.src, message.dst, message.id)
            self.arrivals[message.id] = arrival
            self._by_id[message.id] = message
            heapq.heappush(self._arrival_heap, (arrival, message.id))

    def timer(self, address: Address, name: str, delay_s: float,
              f) -> GeoSimTimer:
        return GeoSimTimer(self, next(self._ids), address, name,
                           delay_s, f)

    # --- delivery ---------------------------------------------------------
    def _deliver(self, message: SimMessage):
        self.arrivals.pop(message.id, None)
        self._by_id.pop(message.id, None)
        if not self.topology.link_up(message.src, message.dst):
            # Dropped on the partitioned link: consume the frame
            # without running the handler (the sim's per-address
            # ``partitioned`` drop semantics, at link granularity).
            try:
                self.messages.remove(message)
            except ValueError:
                self.logger.warn(
                    f"dropping unbuffered message {message}")
            return None
        return super()._deliver(message)

    # --- the virtual-time event loop --------------------------------------
    @staticmethod
    def _peek(heap: list, live: dict) -> Optional[float]:
        while heap:
            t, key = heap[0]
            if live.get(key) == t:
                return t
            heapq.heappop(heap)
        return None

    def next_event_time(self) -> Optional[float]:
        t_msg = self._peek(self._arrival_heap, self.arrivals)
        t_tmr = self._peek(self._deadline_heap, self._deadlines)
        if t_msg is None:
            return t_tmr
        if t_tmr is None:
            return t_msg
        return min(t_msg, t_tmr)

    def _pop_due_messages(self, t: float) -> list:
        """Every buffered frame with arrival <= ``t``, in (arrival,
        send id) order; their heap/stamp entries are consumed."""
        due = []
        while self._arrival_heap:
            arrival, message_id = self._arrival_heap[0]
            if arrival > t:
                break
            heapq.heappop(self._arrival_heap)
            if self.arrivals.get(message_id) == arrival:
                message = self._by_id.get(message_id)
                if message is not None:
                    due.append(message)
        return due

    def run_until(self, t_end: float, max_steps: int = 1_000_000) -> int:
        """Advance virtual time to ``t_end``, delivering frames in
        arrival order and firing timers at their deadlines. Frames
        sharing one timestamp land as one wave and each touched
        destination drains once -- the event-loop batching semantics
        of the real transport. Returns the number of events run."""
        steps = 0
        while steps < max_steps:
            t = self.next_event_time()
            if t is None or t > t_end:
                break
            self.now = t
            touched: list = []
            seen: set[int] = set()
            for message in self._pop_due_messages(t):
                actor = self._deliver(message)
                steps += 1
                if actor is not None and id(actor) not in seen:
                    seen.add(id(actor))
                    touched.append(actor)
            for actor in touched:
                self._drain(actor)
            # Timers due at (or before) t.
            while self._deadline_heap:
                deadline, timer_id = self._deadline_heap[0]
                if deadline > t:
                    break
                heapq.heappop(self._deadline_heap)
                if self._deadlines.get(timer_id) == deadline:
                    self.trigger_timer(timer_id)
                    steps += 1
        self.now = max(self.now, t_end)
        return steps

    def run_for(self, duration: float,
                max_steps: int = 1_000_000) -> int:
        return self.run_until(self.now + duration, max_steps=max_steps)

    def run_until_quiescent(self, max_steps: int = 1_000_000,
                            horizon_s: float = 3600.0) -> int:
        """Deliver every in-flight frame (following arrival order and
        any sends they trigger) WITHOUT firing timers -- virtual time
        advances past deadlines but the timers stay pending, so a
        settle can never be kept awake by resend churn. Bounded by
        ``horizon_s`` of virtual time. The settle primitive for
        integration tests; timer-driven runs use :meth:`run_for`."""
        steps = 0
        t_end = self.now + horizon_s
        while steps < max_steps:
            t = self._peek(self._arrival_heap, self.arrivals)
            if t is None or t > t_end:
                break
            self.now = max(self.now, t)
            _, message_id = heapq.heappop(self._arrival_heap)
            message = self._by_id.get(message_id)
            if message is None:
                continue
            actor = self._deliver(message)
            steps += 1
            if actor is not None:
                self._drain(actor)
        return steps

    def crash(self, address: Address) -> None:
        super().crash(address)
        self._deadlines = {tid: d for tid, d in self._deadlines.items()
                           if tid in self.timers}


def delivery_schedule(transport: GeoSimTransport) -> list:
    """The in-flight frames as ``(arrival_s, id, src, dst)`` rows in
    delivery order -- the projection the golden determinism test
    snapshots (tests/test_geo.py)."""
    rows = []
    for message in transport.messages:
        arrival = transport.arrivals.get(message.id)
        if arrival is not None:
            heapq.heappush(rows, (round(arrival, 12), message.id,
                                  str(message.src), str(message.dst)))
    return [heapq.heappop(rows) for _ in range(len(rows))]
