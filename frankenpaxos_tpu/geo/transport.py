"""GeoSimTransport: SimTransport with latency-ordered delivery.

Plain ``SimTransport`` buffers frames and lets the caller deliver them
in any order -- FIFO for integration tests, adversarially for the
randomized sims. The geo transport adds a VIRTUAL CLOCK: every send
samples its link's delay from the :class:`GeoTopology` matrix
(deterministic per seed) and stamps an arrival time, and the event
loop (:meth:`run_for` / :meth:`run_until`) delivers strictly in
arrival order -- so a zone-local ack genuinely overtakes a WAN frame
sent earlier, which is the whole phenomenon the wide-area suite
exists to exercise. Latencies measured against :attr:`now` are exact
virtual durations, which is what makes the bench gates in
``bench/geo_lt.py`` sharp instead of host-noise-bound.

Event scheduling is a pair of LAZY HEAPS (arrival times, timer
deadlines): push on send/start, validate against the authoritative
dicts on pop -- out-of-band removals (adversarial deliveries, link
drops, timer stops) just leave stale heap entries to be skipped, so
every per-event operation is O(log n) instead of a buffer scan.

The adversarial simulator API is unchanged: ``generate_command`` /
``deliver_message`` still deliver ANY buffered frame, so the chaos
sims explore reorderings beyond what latencies would produce, with
link partitions/degrades applied at delivery time. The bounded-inbox
admission path is NOT armed here (geo harnesses attach no admission
controllers); arrival stamping covers synthesized reject replies
anyway because stamps are derived per buffered frame.

paxsim: the virtual-clock event loop is a POLICY over the shared wave
engine (sim_transport._run_wave) -- the heap decides WHICH frames
form the next wave (everything due at the next arrival time), then
the same engine evaluates link/partition masks vectorized
(topology.up_matrix x ops/simwave) and delivers with per-wave drains.
Delivered frames tombstone out of the public buffer list
(``_consume_buffered``) instead of paying a ``list.remove`` scan per
message, which is what makes 1000-zone topologies and million-event
schedules linear instead of quadratic (bench/sim_core_ab.py).
"""

from __future__ import annotations

import heapq
from typing import Optional

import numpy as np

from frankenpaxos_tpu.geo.topology import GeoTopology
from frankenpaxos_tpu.ops import simwave
from frankenpaxos_tpu.runtime.logger import Logger
from frankenpaxos_tpu.runtime.sim_transport import (
    SimMessage,
    SimTimer,
    SimTransport,
    WAVE_SAFE_DELIVERS,
)
from frankenpaxos_tpu.runtime.transport import Address


class GeoSimTimer(SimTimer):
    """A SimTimer with a virtual deadline: (re)stamped from the
    transport's clock on every start, so the event loop fires it at
    ``now + delay_s`` like a real timer wheel."""

    def start(self) -> None:
        super().start()
        deadline = self._transport.now + self.delay_s
        self._transport._deadlines[self._id] = deadline
        heapq.heappush(self._transport._deadline_heap,
                       (deadline, self._id))

    def stop(self) -> None:
        super().stop()
        self._transport._deadlines.pop(self._id, None)


class GeoSimTransport(SimTransport):
    def __init__(self, topology: GeoTopology,
                 logger: Optional[Logger] = None):
        super().__init__(logger)
        self.topology = topology
        #: The virtual clock, in seconds. Advanced only by the event
        #: loop (never by wall time -- determinism contract, GEO801).
        self.now = 0.0
        #: message id -> virtual arrival time (authoritative; heap
        #: entries are valid only while they match).
        self.arrivals: dict[int, float] = {}
        self._by_id: dict[int, SimMessage] = {}
        self._arrival_heap: list = []
        #: timer id -> virtual deadline (running timers only).
        self._deadlines: dict[int, float] = {}
        self._deadline_heap: list = []
        self._link_only_check = None
        #: paxworld fault bridge: address -> virtual time its sends
        #: resume departing. A role stalled inside a blocking syscall
        #: (an fsync-stall fault, wal/faults.py) emits its frames
        #: late: arrival stamps base at the stall horizon instead of
        #: ``now``. Empty (one falsy test per send) unless a fault
        #: hook armed it.
        self._stall_until: dict = {}

    # --- sending ----------------------------------------------------------
    def send(self, src: Address, dst: Address, data: bytes) -> None:
        before = len(self.messages)
        super().send(src, dst, data)
        stalls = self._stall_until
        # Stamp every frame this send buffered (the frame itself, plus
        # any reject replies a bounded inbox synthesized), each over
        # its OWN link -- and each from its OWN sender's stall
        # horizon (a synthesized reject originates at dst, which may
        # not share src's stall).
        for message in self.messages[before:]:
            base = self.now
            if stalls:
                until = stalls.get(message.src)
                if until is not None:
                    if until > base:
                        base = until
                    else:
                        del stalls[message.src]  # expired
            arrival = base + self.topology.sample_delay(
                message.src, message.dst, message.id)
            self.arrivals[message.id] = arrival
            self._by_id[message.id] = message
            heapq.heappush(self._arrival_heap, (arrival, message.id))

    def stall_sender(self, address: Address, until_t: float) -> None:
        """Model a role blocked in a syscall until virtual ``until_t``
        (the wal/faults.py fsync-stall bridge): frames it sends before
        then depart AT the stall horizon -- the event-loop pass that
        issued the blocking call finishes late, exactly like a real
        fsync stall holds a drain's group-commit release. Stalls only
        extend (a second fault during one stall pushes the horizon)."""
        if until_t > self._stall_until.get(address, 0.0):
            self._stall_until[address] = until_t

    def timer(self, address: Address, name: str, delay_s: float,
              f) -> GeoSimTimer:
        return GeoSimTimer(self, next(self._ids), address, name,
                           delay_s, f)

    # --- delivery ---------------------------------------------------------
    def _deliver(self, message: SimMessage):
        self.arrivals.pop(message.id, None)
        self._by_id.pop(message.id, None)
        if not self.topology.link_up(message.src, message.dst):
            # Dropped on the partitioned link: consume the frame
            # without running the handler (the sim's per-address
            # ``partitioned`` drop semantics, at link granularity).
            if not self._remove_buffered(message):
                self.logger.warn(
                    f"dropping unbuffered message {message}")
            return None
        return super()._deliver(message)

    # --- paxsim wave-engine policy hooks ----------------------------------
    def _drop_schedule_stamps(self, wave) -> None:
        """FIFO drains consume frames outside the arrival-order loop;
        their stamps and lazy-heap entries must die with them or a
        later ``run_until`` would double-deliver (the legacy core did
        this inside its per-message ``_deliver``)."""
        arrivals = self.arrivals
        by_id = self._by_id
        for message in wave:
            arrivals.pop(message.id, None)
            by_id.pop(message.id, None)

    def _wave_keep_mask(self, wave) -> Optional[np.ndarray]:
        n = len(wave)
        if n < simwave.WAVE_VECTOR_MIN:
            return None
        topo = self.topology
        zid = topo.zone_id_of
        src_z = np.fromiter((zid(m.src) for m in wave), np.int32, n)
        dst_z = np.fromiter((zid(m.dst) for m in wave), np.int32, n)
        keep = simwave.LINK_KEEP_MASK(src_z, dst_z, topo.up_matrix())
        # The wave is already above WAVE_VECTOR_MIN, so the base mask
        # is exactly the partitioned-address mask (None when no
        # addresses are partitioned).
        partitioned = super()._wave_keep_mask(wave)
        if partitioned is not None:
            keep &= partitioned
        return keep

    def _per_message_check(self):
        base = super()._per_message_check()
        if base is None:
            # The common case (no per-address partitions): one cached
            # closure instead of an allocation per (often singleton)
            # wave.
            check = self._link_only_check
            if check is None:
                link_up = self.topology.link_up
                check = self._link_only_check = \
                    lambda m: link_up(m.src, m.dst)
            return check
        link_up = self.topology.link_up
        return lambda m: base(m) and link_up(m.src, m.dst)

    # --- the virtual-time event loop --------------------------------------
    @staticmethod
    def _peek(heap: list, live: dict) -> Optional[float]:
        while heap:
            t, key = heap[0]
            if live.get(key) == t:
                return t
            heapq.heappop(heap)
        return None

    def next_event_time(self) -> Optional[float]:
        t_msg = self._peek(self._arrival_heap, self.arrivals)
        t_tmr = self._peek(self._deadline_heap, self._deadlines)
        if t_msg is None:
            return t_tmr
        if t_tmr is None:
            return t_msg
        return min(t_msg, t_tmr)

    def _pop_due_messages(self, t: float) -> list:
        """Every buffered frame with arrival <= ``t``, in (arrival,
        send id) order; their heap entries are consumed."""
        due = []
        while self._arrival_heap:
            arrival, message_id = self._arrival_heap[0]
            if arrival > t:
                break
            heapq.heappop(self._arrival_heap)
            if self.arrivals.get(message_id) == arrival:
                message = self._by_id.get(message_id)
                if message is not None:
                    due.append(message)
        return due

    def run_until(self, t_end: float, max_steps: int = 1_000_000) -> int:
        """Advance virtual time to ``t_end``, delivering frames in
        arrival order and firing timers at their deadlines. Frames
        sharing one timestamp land as one wave and each touched
        destination drains once -- the event-loop batching semantics
        of the real transport. Returns the number of events run."""
        if not self._wave_fast_path_ok():
            return self._run_until_compat(t_end, max_steps)
        steps = 0
        try:
            while steps < max_steps:
                t = self.next_event_time()
                if t is None or t > t_end:
                    break
                # max(): the clock never REWINDS. A budget-capped call
                # (paxworld: run_until under the overload CPU model)
                # can end with backlog whose arrival stamps are behind
                # the t_end it advanced to; delivering that backlog
                # next tick at its old stamps would move time backward
                # -- and erase exactly the queueing delay the overload
                # SLO clauses exist to measure. In the un-capped case
                # arrivals pop in order, so this is the identity.
                self.now = max(self.now, t)
                # The whole same-timestamp wave delivers even when it
                # overshoots max_steps -- the legacy loop counted steps
                # per message but only checked the cap between waves,
                # and truncating here would let the timers due at t
                # fire BEFORE the wave's tail (a schedule divergence).
                wave = self._pop_due_messages(t)
                if wave:
                    self._drop_schedule_stamps(wave)
                    self._consume_buffered(wave)
                    steps += len(wave)
                    self._run_wave(wave, coalesce=True)
                # Timers due at (or before) t.
                while self._deadline_heap:
                    deadline, timer_id = self._deadline_heap[0]
                    if deadline > t:
                        break
                    heapq.heappop(self._deadline_heap)
                    if self._deadlines.get(timer_id) == deadline:
                        self.trigger_timer(timer_id)
                        steps += 1
        finally:
            self._compact_messages()
        self.now = max(self.now, t_end)
        return steps

    def _run_until_compat(self, t_end: float, max_steps: int) -> int:
        """Per-message fallback for intercepted delivery (identical
        order/drain semantics, every frame through ``_deliver``)."""
        steps = 0
        while steps < max_steps:
            t = self.next_event_time()
            if t is None or t > t_end:
                break
            self.now = max(self.now, t)  # never rewinds (see run_until)
            touched: list = []
            seen: set[int] = set()
            for message in self._pop_due_messages(t):
                actor = self._deliver(message)
                steps += 1
                if actor is not None and id(actor) not in seen:
                    seen.add(id(actor))
                    touched.append(actor)
            for actor in touched:
                self._drain(actor)
            while self._deadline_heap:
                deadline, timer_id = self._deadline_heap[0]
                if deadline > t:
                    break
                heapq.heappop(self._deadline_heap)
                if self._deadlines.get(timer_id) == deadline:
                    self.trigger_timer(timer_id)
                    steps += 1
        self.now = max(self.now, t_end)
        return steps

    def run_for(self, duration: float,
                max_steps: int = 1_000_000) -> int:
        return self.run_until(self.now + duration, max_steps=max_steps)

    def run_until_quiescent(self, max_steps: int = 1_000_000,
                            horizon_s: float = 3600.0) -> int:
        """Deliver every in-flight frame (following arrival order and
        any sends they trigger) WITHOUT firing timers -- virtual time
        advances past deadlines but the timers stay pending, so a
        settle can never be kept awake by resend churn. Bounded by
        ``horizon_s`` of virtual time. The settle primitive for
        integration tests; timer-driven runs use :meth:`run_for`."""
        fast = self._wave_fast_path_ok()
        steps = 0
        t_end = self.now + horizon_s
        try:
            while steps < max_steps:
                t = self._peek(self._arrival_heap, self.arrivals)
                if t is None or t > t_end:
                    break
                self.now = max(self.now, t)
                _, message_id = heapq.heappop(self._arrival_heap)
                message = self._by_id.get(message_id)
                if message is None:
                    continue
                if fast:
                    self.arrivals.pop(message_id, None)
                    self._by_id.pop(message_id, None)
                    self._consume_buffered((message,))
                    steps += 1
                    self._run_wave([message], coalesce=True)
                else:
                    actor = self._deliver(message)
                    steps += 1
                    if actor is not None:
                        self._drain(actor)
        finally:
            self._compact_messages()
        return steps

    def crash(self, address: Address) -> None:
        super().crash(address)
        self._deadlines = {tid: d for tid, d in self._deadlines.items()
                           if tid in self.timers}


# The geo `_deliver` override is wave-aware (its link/partition drops
# are exactly what `_wave_keep_mask`/`_per_message_check` evaluate), so
# the wave engine may bypass it. Subclasses pinning a DIFFERENT
# `_deliver` (sim_legacy) fall back to per-message delivery.
WAVE_SAFE_DELIVERS.add(GeoSimTransport._deliver)


def delivery_schedule(transport: GeoSimTransport) -> list:
    """The in-flight frames as ``(arrival_s, id, src, dst)`` rows in
    delivery order -- the projection the golden determinism test
    snapshots (tests/test_geo.py)."""
    if transport._consumed:
        transport._compact_messages()
    rows = []
    for message in transport.messages:
        arrival = transport.arrivals.get(message.id)
        if arrival is not None:
            heapq.heappush(rows, (round(arrival, 12), message.id,
                                  str(message.src), str(message.dst)))
    return [heapq.heappop(rows) for _ in range(len(rows))]
