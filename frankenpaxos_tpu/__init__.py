"""frankenpaxos_tpu: a TPU-native state machine replication (SMR) framework.

A ground-up rebuild of the capabilities of FrankenPaxos
(https://github.com/zdmwi/frankenpaxos) designed TPU-first:

- Protocol roles are pure, single-threaded event-loop state machines
  (the reference's Actor/Transport contract, Transport.scala:37-40).
- The hot loops -- quorum vote collection, watermark math, dependency-set
  algebra -- are lifted off the per-message path into batched device
  kernels over ``[slots x acceptors]`` matrices (``ops/``), evaluated as
  single fused XLA reductions/matmuls per event-loop drain.
- Multi-core scaling uses ``jax.sharding.Mesh`` + ``shard_map`` over the
  slot axis (Mencius leader stripes, MultiPaxos acceptor groups), not
  point-to-point message translation.
"""

__version__ = "0.1.0"
