"""paxtrace: end-to-end causal tracing + crash flight recorder.

Three pieces, all host-side (never inside ``ops/`` kernels -- paxlint
TPU209 enforces that):

  * ``trace`` -- a trace context (trace_id, span_id, sampling bit)
    propagated at the transport FRAME layer (the wire tag space 1..127
    is fully allocated, so the context rides the frame header, not the
    message codecs) plus the Tracer that emits receive/timer/drain
    spans with drain-stage sub-spans (decode, handler, quorum-kernel,
    wal-fsync, send-release).
  * ``flight`` -- a fixed-size per-role flight recorder ring buffer
    over an mmap'd file: the OS keeps the dirty pages when the process
    is SIGKILL'd, so a crashed role still leaves a record of its last
    actions for the chaos driver's post-mortem.
  * ``perfetto`` -- span records -> Chrome-trace-event JSON (loads in
    Perfetto / chrome://tracing), per-command critical paths, and the
    drain-stage latency-breakdown table.
  * ``telemetry`` -- the paxpulse HOST side: one batched D2H collect
    per reporting interval of the device counters that ride inside the
    jitted pipeline as arrays (ops/telemetry.py -- counters are data,
    not hooks, so TPU209 stays satisfied), publishing
    ``fpx_pipeline_*`` RuntimeMetrics and Perfetto counter tracks.

Docs: docs/OBSERVABILITY.md.
"""

from frankenpaxos_tpu.obs.flight import FlightRecorder
from frankenpaxos_tpu.obs.perfetto import (
    latency_breakdown,
    load_jsonl,
    to_chrome_trace,
    trace_tree,
)
from frankenpaxos_tpu.obs.telemetry import (
    collect,
    TelemetryReporter,
    TelemetrySnapshot,
)
from frankenpaxos_tpu.obs.trace import (
    RuntimeMetrics,
    SpanRecord,
    TraceContext,
    Tracer,
    VirtualClock,
)

__all__ = [
    "FlightRecorder",
    "RuntimeMetrics",
    "SpanRecord",
    "TelemetryReporter",
    "TelemetrySnapshot",
    "TraceContext",
    "Tracer",
    "VirtualClock",
    "collect",
    "latency_breakdown",
    "load_jsonl",
    "to_chrome_trace",
    "trace_tree",
]
