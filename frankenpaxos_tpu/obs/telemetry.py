"""paxpulse host plane: interval collection of the device counters.

The device side (``ops/telemetry.py``) accumulates counters as arrays
inside the pipeline's donated carry; this module is the ONLY place they
cross to the host. :func:`collect` performs exactly one batched
``jax.device_get`` of the whole telemetry subtree -- one D2H sync per
reporting interval, never per drain (DEV1201-clean by construction; the
zero-transfer-between-intervals property is pinned by a
``jax.transfer_guard`` test).

From a snapshot the host derives:

  * ``fpx_pipeline_*`` RuntimeMetrics (obs/trace.py): committed /
    proposed / drains / pad-lane counters, per-shard committed gauges
    and the shard-skew ratio, the quorum-occupancy and watermark-lag
    histograms as labeled counters, and the proposal batch fill.
  * Perfetto COUNTER tracks (``ph: "C"``) merged into the trace export
    next to the span tracks (``obs.perfetto.to_chrome_trace``).

:class:`TelemetryReporter` packages the interval loop: hold the
previous snapshot, publish deltas to RuntimeMetrics, remember timed
samples for the counter tracks, and dump/load ``*.counters.jsonl``
next to the role trace dumps.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterable, Optional, Sequence

import jax
import numpy as np

from frankenpaxos_tpu.ops.telemetry import lag_bucket_bounds


@dataclasses.dataclass(frozen=True)
class TelemetrySnapshot:
    """Host copy of the cumulative device counters (one collect)."""

    drains: int
    proposed: int
    shard_committed: tuple
    occupancy: tuple
    lag_hist: tuple
    pad_lanes: int

    @property
    def committed(self) -> int:
        return int(sum(self.shard_committed))

    def delta(self, prev: Optional["TelemetrySnapshot"]) \
            -> "TelemetrySnapshot":
        """The interval delta against an earlier snapshot (``None``
        means "since the zeroed state": the snapshot itself)."""
        if prev is None:
            return self
        return TelemetrySnapshot(
            drains=self.drains - prev.drains,
            proposed=self.proposed - prev.proposed,
            shard_committed=tuple(
                a - b for a, b in zip(self.shard_committed,
                                      prev.shard_committed)),
            occupancy=tuple(a - b for a, b in zip(self.occupancy,
                                                  prev.occupancy)),
            lag_hist=tuple(a - b for a, b in zip(self.lag_hist,
                                                 prev.lag_hist)),
            pad_lanes=self.pad_lanes - prev.pad_lanes)

    def shard_skew(self) -> float:
        """max/mean of per-shard committed: 1.0 is a perfectly even
        mesh; the gauge the Grafana band alerts on."""
        shards = self.shard_committed
        mean = sum(shards) / max(len(shards), 1)
        return float(max(shards) / mean) if mean else 1.0

    def batch_fill(self, block_size: int) -> float:
        """Valid proposals per drain over the global block: 1.0 means
        every lane carried a command (pad lanes never count)."""
        denom = self.drains * block_size
        return float(self.proposed / denom) if denom else 0.0

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, obj: dict) -> "TelemetrySnapshot":
        return cls(drains=int(obj["drains"]),
                   proposed=int(obj["proposed"]),
                   shard_committed=tuple(obj["shard_committed"]),
                   occupancy=tuple(obj["occupancy"]),
                   lag_hist=tuple(obj["lag_hist"]),
                   pad_lanes=int(obj["pad_lanes"]))


def collect(state) -> Optional[TelemetrySnapshot]:
    """ONE batched D2H fetch of the pipeline's telemetry subtree.

    ``state`` is a ``bench.pipeline.PipelineState`` (or anything with a
    ``.telemetry`` leaf, or a bare ``TelemetryState``). Returns ``None``
    when the plane is off. The single ``jax.device_get`` call transfers
    every leaf in one batch -- the per-interval sync the docs promise."""
    tel = getattr(state, "telemetry", state)
    if tel is None:
        return None
    host = jax.device_get(tel)
    return TelemetrySnapshot(
        drains=int(host.drains),
        proposed=int(host.proposed),
        shard_committed=tuple(
            int(x) for x in np.asarray(host.shard_committed)),
        occupancy=tuple(int(x) for x in np.asarray(host.occupancy)),
        lag_hist=tuple(int(x) for x in np.asarray(host.lag_hist)),
        pad_lanes=int(host.pad_lanes))


def publish(metrics, snap: TelemetrySnapshot,
            prev: Optional[TelemetrySnapshot] = None,
            block_size: Optional[int] = None) -> None:
    """Feed one interval into a RuntimeMetrics: counters get the delta
    against ``prev``, gauges (per-shard committed, skew, fill) the
    cumulative state."""
    d = snap.delta(prev)
    metrics.pipeline_interval(
        drains=d.drains, committed=d.committed, proposed=d.proposed,
        pad_lanes=d.pad_lanes, occupancy=d.occupancy,
        lag_hist=d.lag_hist, shard_committed=snap.shard_committed,
        skew=snap.shard_skew(),
        fill=(snap.batch_fill(block_size)
              if block_size else None))


def counter_events(samples: Sequence[tuple], role: str) -> list:
    """Chrome-trace COUNTER events (``ph: "C"``) from ``(t_seconds,
    snapshot)`` interval samples: per-interval committed/proposed/pad
    deltas plus the cumulative skew ratio, one track set per role.
    Merge them into the span export via ``to_chrome_trace(records,
    counters=...)``."""
    events = []
    prev = None
    for t, snap in samples:
        d = snap.delta(prev)
        prev = snap
        ts = round(float(t) * 1e6, 3)
        events.append({
            "name": f"paxpulse {role} pipeline",
            "ph": "C", "pid": 1, "ts": ts,
            "args": {"committed": d.committed, "proposed": d.proposed,
                     "pad_lanes": d.pad_lanes}})
        events.append({
            "name": f"paxpulse {role} shard skew",
            "ph": "C", "pid": 1, "ts": ts,
            "args": {"max_over_mean": round(snap.shard_skew(), 4)}})
    return events


class TelemetryReporter:
    """The reporting-interval loop for one role/bench: call
    :meth:`collect` once per interval with the live pipeline state and
    the host-side timestamp; deltas go to RuntimeMetrics (when
    attached) and timed samples accumulate for the Perfetto counter
    tracks."""

    def __init__(self, role: str, metrics=None,
                 block_size: Optional[int] = None):
        self.role = role
        self.metrics = metrics
        self.block_size = block_size
        self.samples: list = []
        self._prev: Optional[TelemetrySnapshot] = None

    def collect(self, state, t: float) -> Optional[TelemetrySnapshot]:
        snap = collect(state)
        if snap is None:
            return None
        if self.metrics is not None:
            publish(self.metrics, snap, self._prev, self.block_size)
        self.samples.append((float(t), snap))
        self._prev = snap
        return snap

    @property
    def last(self) -> Optional[TelemetrySnapshot]:
        return self._prev

    def counter_events(self) -> list:
        return counter_events(self.samples, self.role)

    def dump(self, path: str) -> None:
        """``*.counters.jsonl``: one ``{t, snapshot}`` line per
        interval, next to the role's ``*.trace.jsonl``."""
        with open(path, "w") as f:
            for t, snap in self.samples:
                f.write(json.dumps({"t": t, "role": self.role,
                                    "snapshot": snap.to_json()}) + "\n")

    def summary(self) -> dict:
        """The post-mortem / artifact JSON for the last counter state
        (what the chaos driver snapshots beside the flight ring)."""
        snap = self._prev
        if snap is None:
            return {"role": self.role, "collected": False}
        out = {"role": self.role, "collected": True,
               "drains": snap.drains, "proposed": snap.proposed,
               "committed": snap.committed,
               "shard_committed": list(snap.shard_committed),
               "shard_skew": round(snap.shard_skew(), 4),
               "pad_lanes": snap.pad_lanes,
               "occupancy": list(snap.occupancy),
               "lag_hist": list(snap.lag_hist),
               "lag_bucket_lower_bounds":
                   [int(b) for b in lag_bucket_bounds()]}
        if self.block_size:
            out["batch_fill"] = round(snap.batch_fill(self.block_size), 4)
        return out


def load_counters(path: str) -> list:
    """``(t, role, snapshot)`` samples from a ``*.counters.jsonl`` dump
    (tolerates a torn final line, like the span loader)."""
    samples = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
                samples.append((float(obj["t"]), str(obj["role"]),
                                TelemetrySnapshot.from_json(
                                    obj["snapshot"])))
            except (ValueError, KeyError):
                continue
    return samples
