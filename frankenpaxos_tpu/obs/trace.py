"""Trace contexts, spans, and the per-role Tracer.

THE PROPAGATION LAYER: a ``TraceContext`` (trace_id, parent span_id,
sampling bit) rides the transport FRAME header -- ``host:port|<ctx>``
on TCP frames, a ``trace`` field on ``SimMessage`` -- never the
message codecs: the wire tag space 1..127 is fully allocated, and the
frame layer reaches every protocol uniformly without touching a single
codec. Roles that never heard of tracing still propagate it, because
propagation lives in the two transports.

SPAN MODEL (docs/OBSERVABILITY.md): the transports emit one span per
``receive`` (parented by the frame's context, or a fresh sampled root
when the frame carries none -- the client edge), one per timer fire,
and one per ``on_drain``. The drain span adopts the context of the
LAST sampled message delivered in its batch (group commit serves a
batch; the adopted command's critical path runs through its batch's
drain). Inside handlers and drains, ``Actor.trace_stage`` opens
drain-stage sub-spans -- decode, handler, quorum-kernel, wal-fsync,
send-release -- the stages the latency-breakdown table attributes
per-command time to.

DETERMINISM: ids come from a per-role counter (salted with a CRC of
the role name so roles never collide), and the clock is injectable --
``VirtualClock`` advances a fixed tick per reading, so a SimTransport
trace is a pure function of the command sequence and golden-testable.

OVERHEAD: with no tracer attached every hook is one attribute load +
``is None`` test (measured in bench_results/trace_overhead.json).
Unsampled traces propagate their context (so the sampling decision is
made ONCE, at the root) but never read the clock or allocate records.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Callable, Optional
import zlib

_MASK64 = (1 << 64) - 1


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """What propagates: which trace, which parent span, sampled or not."""

    trace_id: int
    span_id: int
    sampled: bool

    def encode(self) -> str:
        """Frame-header form. No ``:`` or ``|`` (both are taken by the
        ``host:port|ctx`` header grammar)."""
        return (f"{self.trace_id:016x}.{self.span_id:016x}."
                f"{1 if self.sampled else 0}")

    @classmethod
    def decode(cls, text: str) -> "Optional[TraceContext]":
        parts = text.split(".")
        if len(parts) != 3:
            return None
        try:
            return cls(trace_id=int(parts[0], 16),
                       span_id=int(parts[1], 16),
                       sampled=parts[2] == "1")
        except ValueError:
            return None


@dataclasses.dataclass
class SpanRecord:
    """One finished span (the unit perfetto.py exports)."""

    name: str       # e.g. "receive:Phase2a", "drain", "stage:wal-fsync"
    cat: str        # receive | timer | drain | stage | event
    role: str       # the tracer's role label ("acceptor_1")
    t0: float       # seconds (shared wall clock; virtual in sim)
    dur: float      # seconds
    trace_id: int
    span_id: int
    parent_id: int  # 0 = root

    def to_json(self) -> dict:
        return {"name": self.name, "cat": self.cat, "role": self.role,
                "t0": round(self.t0, 9), "dur": round(self.dur, 9),
                "trace_id": f"{self.trace_id:016x}",
                "span_id": f"{self.span_id:016x}",
                "parent_id": f"{self.parent_id:016x}"}

    @classmethod
    def from_json(cls, row: dict) -> "SpanRecord":
        return cls(name=row["name"], cat=row["cat"], role=row["role"],
                   t0=row["t0"], dur=row["dur"],
                   trace_id=int(row["trace_id"], 16),
                   span_id=int(row["span_id"], 16),
                   parent_id=int(row["parent_id"], 16))


class VirtualClock:
    """A deterministic clock: every reading advances a fixed tick.
    SimTransport traces under it are pure functions of the command
    sequence (the golden-trace tests rely on this)."""

    def __init__(self, start: float = 0.0, tick_s: float = 1e-6):
        self.now = start
        self.tick_s = tick_s

    def __call__(self) -> float:
        self.now += self.tick_s
        return self.now


class RuntimeMetrics:
    """The drain-granular runtime metrics every role exports when the
    metrics endpoint is on (with or without tracing): drain-stage
    latency histograms, inbound queue depth (messages per drain
    batch), and WAL group-commit fsync latency. These feed the shared
    "runtime" Grafana row and the promdb scrapes."""

    def __init__(self, collectors, role: str):
        self.role = role
        self._stage_hist = collectors.histogram(
            "fpx_runtime_drain_stage_seconds",
            help="Per-drain-stage latency (decode/handler/quorum-kernel/"
                 "wal-fsync/send-release)",
            labels=("role", "stage"))
        self._depth_gauge = collectors.gauge(
            "fpx_runtime_inbound_queue_depth",
            help="Messages delivered in the current drain batch",
            labels=("role",)).labels(role)
        self._fsync_hist = collectors.histogram(
            "fpx_runtime_wal_fsync_seconds",
            help="WAL group-commit fsync latency (one per drain)",
            labels=("role",)).labels(role)
        self._stage_children: dict = {}
        # paxload (serve/): the admission/backpressure families every
        # /metrics role exports -- registered here (not lazily) so the
        # series exist at zero on every role, admission enabled or not
        # (the Grafana "Runtime" row charts them fleet-wide).
        self._adm_admitted = collectors.counter(
            "fpx_runtime_admission_admitted_total",
            help="Client commands admitted by this role's admission "
                 "controller",
            labels=("role",)).labels(role)
        self._adm_rejected = collectors.counter(
            "fpx_runtime_admission_rejected_total",
            help="Client commands rejected (tokens/inflight/queue/"
                 "codel)",
            labels=("role", "reason"))
        self._adm_shed = collectors.counter(
            "fpx_runtime_admission_shed_total",
            help="Client-lane frames shed by a bounded inbox "
                 "(drop-oldest/reject-newest)",
            labels=("role", "policy"))
        self._adm_inflight = collectors.gauge(
            "fpx_runtime_admission_inflight",
            help="Live proposed-minus-chosen in-flight span under the "
                 "slot budget",
            labels=("role",)).labels(role)
        self._adm_queue = collectors.gauge(
            "fpx_runtime_admission_queue_depth",
            help="Client-lane bounded-inbox depth",
            labels=("role",)).labels(role)
        self._retry_counter = collectors.counter(
            "fpx_runtime_client_retries_total",
            help="Client retry-discipline events "
                 "(backoff/failover/giveup)",
            labels=("role", "kind"))
        self._outbuf_hwm = collectors.gauge(
            "fpx_runtime_outbound_buffer_bytes",
            help="Per-role outbound-buffer high-water mark (bytes "
                 "pending to the slowest peer)",
            labels=("role",)).labels(role)
        self._outbuf_stalls = collectors.counter(
            "fpx_runtime_outbound_stalls_total",
            help="Outbound buffer overflows (oldest frames dropped, "
                 "client lane first; protocol resends cover)",
            labels=("role",)).labels(role)
        # paxwire (runtime/paxwire.py + docs/TRANSPORT.md): the batched
        # transport's health triple -- how many wire frames each writev
        # carried, how many Phase2b acks the flush-time coalescers
        # merged away, and how many bytes left through batched flushes.
        self._transport_fpw = collectors.gauge(
            "fpx_runtime_transport_frames_per_writev",
            help="Wire frames carried by the most recent batched "
                 "flush (writev)",
            labels=("role",)).labels(role)
        self._transport_coalesced = collectors.counter(
            "fpx_runtime_transport_coalesced_acks_total",
            help="Phase2b/ack messages merged into run-granular ack "
                 "ranges by the flush-time coalescers",
            labels=("role",)).labels(role)
        # (Named without the counter-conventional _total suffix: the
        # metric name is part of the paxwire metrics contract
        # (docs/TRANSPORT.md) and the generated dashboards chart it
        # verbatim.)
        self._transport_batch_bytes = collectors.counter(
            "fpx_runtime_transport_batch_bytes",
            help="Bytes sent through the batched (paxwire) flush path",
            labels=("role",)).labels(role)
        # paxingest (ingest/, docs/TRANSPORT.md): the ingestion-plane
        # health triple for batchers and leaders -- commands moved as
        # pre-batched run descriptors, descriptor bytes (run metadata
        # + raw value segments forwarded without decode), and the
        # per-run fill (commands per descriptor).
        self._ingest_cmds = collectors.counter(
            "fpx_runtime_ingest_batched_cmds_total",
            help="Client commands shipped/consumed as pre-batched "
                 "ingest run descriptors",
            labels=("role",)).labels(role)
        self._ingest_bytes = collectors.counter(
            "fpx_runtime_ingest_descriptor_bytes",
            help="Run-descriptor bytes handled by the ingest plane "
                 "(value segments forwarded as raw copies)",
            labels=("role",)).labels(role)
        self._ingest_fill = collectors.summary(
            "fpx_runtime_ingest_batch_fill",
            help="Commands per ingest run descriptor (batch fill)",
            labels=("role",)).labels(role)
        # paxfan (ingest/fan.py): per-shard fan-in health for the
        # N-batcher ring -- distinct sessions pinned to this shard
        # (capped gauge), commands routed through it, the descriptor-
        # pipelining window depth, failovers absorbed, and the shard's
        # structural ring skew (arc share x N; 1.0 = perfectly even).
        self._shard_owned = collectors.gauge(
            "fpx_runtime_ingest_shard_owned_keys",
            help="Distinct client sessions (pseudonyms) observed by "
                 "this ingest shard (capped tracking set)",
            labels=("role", "shard"))
        self._shard_routed = collectors.counter(
            "fpx_runtime_ingest_shard_routed_cmds_total",
            help="Client commands shipped onward by this ingest shard",
            labels=("role", "shard"))
        self._shard_depth = collectors.gauge(
            "fpx_runtime_ingest_shard_pipeline_depth",
            help="Un-credited IngestRuns in flight from this shard "
                 "(descriptor-pipelining window occupancy)",
            labels=("role", "shard"))
        self._shard_failovers = collectors.counter(
            "fpx_runtime_ingest_shard_failovers_total",
            help="Leader changes and wedged-window resets absorbed by "
                 "this ingest shard",
            labels=("role", "shard"))
        self._shard_skew = collectors.gauge(
            "fpx_runtime_ingest_shard_ring_skew",
            help="Structural routing skew of this shard's ring arcs "
                 "(hash-space share x num_batchers; 1.0 = even)",
            labels=("role", "shard"))
        self._shard_children: dict = {}
        # paxworld (scenarios/, docs/GLOBAL.md): per-region serving
        # health for the Grafana "Global serving" band -- commands
        # committed and client commands rejected/shed, labeled by the
        # zone/region the exporting role serves.
        self._region_goodput = collectors.counter(
            "fpx_runtime_region_goodput_cmds_total",
            help="Commands committed (chosen) by this role, by "
                 "region/zone",
            labels=("role", "region"))
        self._region_shed = collectors.counter(
            "fpx_runtime_region_shed_total",
            help="Client commands rejected or shed by this role, by "
                 "region/zone",
            labels=("role", "region"))
        # paxpulse (ops/telemetry.py + obs/telemetry.py): the device
        # pipeline counters that ride INSIDE the jitted drain loop as
        # arrays and reach here through one batched collect() per
        # reporting interval. fpx_pipeline_* (not fpx_runtime_*)
        # because the exporter is the pipeline harness, not a role's
        # event loop.
        self._pipe_drains = collectors.counter(
            "fpx_pipeline_drains_total",
            help="Device pipeline drains accumulated (fori_loop "
                 "iterations collected)",
            labels=("role",)).labels(role)
        self._pipe_committed = collectors.counter(
            "fpx_pipeline_committed_total",
            help="Commands newly chosen by the device pipeline "
                 "(mesh-global)",
            labels=("role",)).labels(role)
        self._pipe_proposed = collectors.counter(
            "fpx_pipeline_proposed_total",
            help="Valid (non-pad) commands proposed by the device "
                 "pipeline",
            labels=("role",)).labels(role)
        self._pipe_pads = collectors.counter(
            "fpx_pipeline_pad_lanes_total",
            help="Pad-lane slots masked per drain under a "
                 "non-divisible paxmesh slot split (padding waste)",
            labels=("role",)).labels(role)
        self._pipe_shard = collectors.gauge(
            "fpx_pipeline_shard_committed",
            help="Cumulative committed commands per slot shard (the "
                 "skew source)",
            labels=("role", "shard"))
        self._pipe_skew = collectors.gauge(
            "fpx_pipeline_shard_skew_ratio",
            help="max/mean of per-shard committed (1.0 = perfectly "
                 "even mesh)",
            labels=("role",)).labels(role)
        self._pipe_fill = collectors.gauge(
            "fpx_pipeline_batch_fill",
            help="Valid proposals per drain over the global block "
                 "(1.0 = every lane carried a command)",
            labels=("role",)).labels(role)
        self._pipe_occ = collectors.counter(
            "fpx_pipeline_quorum_occupancy_total",
            help="Slots first chosen with exactly `votes` acceptor "
                 "votes landed (quorum-progress occupancy)",
            labels=("role", "votes"))
        self._pipe_lag = collectors.counter(
            "fpx_pipeline_watermark_lag_total",
            help="End-of-drain watermark lag (proposed-but-unchosen "
                 "slots), log2-bucketed by lower bound",
            labels=("role", "bucket"))
        # paxruns (runs/ + protocols/{epaxos,simplebpaxos,fastpaxos}):
        # the batched dependency-set engine and fast-quorum layer
        # shipped in PR 18 without metrics; these close that gap.
        self._depset_deps = collectors.counter(
            "fpx_runtime_depset_batched_deps_total",
            help="Dependency columns computed through the batched "
                 "depset engine (runs/depruns.py)",
            labels=("role",)).labels(role)
        self._depset_fallbacks = collectors.counter(
            "fpx_runtime_depset_span_fallbacks_total",
            help="Depset unions that fell back to the sparse-span "
                 "path (tail window exceeded / host backend)",
            labels=("role",)).labels(role)
        self._fastquorum_checks = collectors.counter(
            "fpx_runtime_fastquorum_checks_total",
            help="Fast-quorum / spec-checker evaluations (fastpaxos, "
                 "fastmultipaxos, runs/quorums.py)",
            labels=("role",)).labels(role)
        self._adm_rejected_children: dict = {}
        self._adm_shed_children: dict = {}
        self._retry_children: dict = {}
        self._region_children: dict = {}
        self._pipe_children: dict = {}

    def observe_stage(self, stage: str, dur_s: float) -> None:
        child = self._stage_children.get(stage)
        if child is None:
            child = self._stage_hist.labels(self.role, stage)
            self._stage_children[stage] = child
        child.observe(dur_s)
        if stage == "wal-fsync":
            self._fsync_hist.observe(dur_s)

    def observe_batch(self, depth: int) -> None:
        self._depth_gauge.set(depth)

    # --- paxload admission/backpressure (serve/) ------------------------
    def admission_admitted(self, n: int = 1) -> None:
        self._adm_admitted.inc(n)

    def admission_rejected(self, reason: str, n: int = 1) -> None:
        child = self._adm_rejected_children.get(reason)
        if child is None:
            child = self._adm_rejected.labels(self.role, reason)
            self._adm_rejected_children[reason] = child
        child.inc(n)

    def admission_shed(self, policy: str, n: int = 1) -> None:
        child = self._adm_shed_children.get(policy)
        if child is None:
            child = self._adm_shed.labels(self.role, policy)
            self._adm_shed_children[policy] = child
        child.inc(n)

    def admission_inflight(self, value: int) -> None:
        self._adm_inflight.set(value)

    def admission_queue_depth(self, value: int) -> None:
        self._adm_queue.set(value)

    def client_retry(self, kind: str, n: int = 1) -> None:
        child = self._retry_children.get(kind)
        if child is None:
            child = self._retry_counter.labels(self.role, kind)
            self._retry_children[kind] = child
        child.inc(n)

    # --- paxingest ingestion plane (ingest/) ----------------------------
    def ingest_batch(self, cmds: int, nbytes: int) -> None:
        self._ingest_cmds.inc(cmds)
        if nbytes:
            self._ingest_bytes.inc(nbytes)
        self._ingest_fill.observe(cmds)

    # --- paxfan sharded fan-in (ingest/fan.py) --------------------------
    def _shard_family(self, shard: int):
        children = self._shard_children.get(shard)
        if children is None:
            label = str(shard)
            children = (
                self._shard_owned.labels(self.role, label),
                self._shard_routed.labels(self.role, label),
                self._shard_depth.labels(self.role, label),
                self._shard_failovers.labels(self.role, label),
                self._shard_skew.labels(self.role, label),
            )
            self._shard_children[shard] = children
        return children

    def ingest_shard_routed(self, shard: int, cmds: int) -> None:
        self._shard_family(shard)[1].inc(cmds)

    def ingest_shard_state(self, shard: int, *, owned_keys: int,
                           pipeline_depth: int, skew: float) -> None:
        owned, _, depth, _, skew_g = self._shard_family(shard)
        owned.set(owned_keys)
        depth.set(pipeline_depth)
        skew_g.set(skew)

    def ingest_shard_failover(self, shard: int) -> None:
        self._shard_family(shard)[3].inc()

    # --- paxworld global serving (scenarios/) ---------------------------
    def region_goodput(self, region: str, n: int = 1) -> None:
        child = self._region_children.get(("goodput", region))
        if child is None:
            child = self._region_goodput.labels(self.role, region)
            self._region_children[("goodput", region)] = child
        child.inc(n)

    def region_shed(self, region: str, n: int = 1) -> None:
        child = self._region_children.get(("shed", region))
        if child is None:
            child = self._region_shed.labels(self.role, region)
            self._region_children[("shed", region)] = child
        child.inc(n)

    def outbound_buffer_hwm(self, size_bytes: int) -> None:
        if size_bytes > self._outbuf_hwm.get():
            self._outbuf_hwm.set(size_bytes)

    def outbound_stall(self, n: int = 1) -> None:
        self._outbuf_stalls.inc(n)

    # --- paxpulse device pipeline (obs/telemetry.py publishes) ----------
    def pipeline_interval(self, *, drains: int, committed: int,
                          proposed: int, pad_lanes: int,
                          occupancy, lag_hist, shard_committed,
                          skew: float, fill=None) -> None:
        """One reporting interval: deltas for the counters, the
        cumulative per-shard/skew/fill state for the gauges."""
        self._pipe_drains.inc(drains)
        self._pipe_committed.inc(committed)
        self._pipe_proposed.inc(proposed)
        self._pipe_pads.inc(pad_lanes)
        for votes, n in enumerate(occupancy):
            if not n:
                continue
            key = ("occ", votes)
            child = self._pipe_children.get(key)
            if child is None:
                child = self._pipe_occ.labels(self.role, str(votes))
                self._pipe_children[key] = child
            child.inc(n)
        for bucket, n in enumerate(lag_hist):
            if not n:
                continue
            key = ("lag", bucket)
            child = self._pipe_children.get(key)
            if child is None:
                child = self._pipe_lag.labels(self.role, str(bucket))
                self._pipe_children[key] = child
            child.inc(n)
        for shard, total in enumerate(shard_committed):
            key = ("shard", shard)
            child = self._pipe_children.get(key)
            if child is None:
                child = self._pipe_shard.labels(self.role, str(shard))
                self._pipe_children[key] = child
            child.set(total)
        self._pipe_skew.set(skew)
        if fill is not None:
            self._pipe_fill.set(fill)

    # --- paxruns depset / fast-quorum layer (runs/, protocols/) ---------
    def depset_batch(self, ndeps: int) -> None:
        self._depset_deps.inc(ndeps)

    def depset_span_fallback(self, n: int = 1) -> None:
        self._depset_fallbacks.inc(n)

    def fastquorum_check(self, n: int = 1) -> None:
        self._fastquorum_checks.inc(n)

    # --- paxwire batched transport (runtime/paxwire.py) -----------------
    def transport_flush(self, frames: int, nbytes: int) -> None:
        self._transport_fpw.set(frames)
        self._transport_batch_bytes.inc(nbytes)

    def transport_coalesced_acks(self, n: int) -> None:
        self._transport_coalesced.inc(n)


class _Scope:
    """An active span: sets ``tracer.current`` for its dynamic extent
    so sends made inside it propagate its context."""

    __slots__ = ("tracer", "name", "cat", "ctx", "parent_id", "prev",
                 "t0", "m0")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 ctx: TraceContext, parent_id: int):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.ctx = ctx
        self.parent_id = parent_id

    def __enter__(self) -> "_Scope":
        tracer = self.tracer
        self.prev = tracer.current
        tracer.current = self.ctx
        if self.ctx.sampled:
            self.t0 = tracer.clock()
            # Durations come from the MONOTONIC clock (an NTP step
            # between enter and exit would otherwise record a
            # negative duration and corrupt the latency histograms);
            # t0 stays on the shared wall clock so role tracks align.
            self.m0 = (self.t0 if tracer.mono is tracer.clock
                       else tracer.mono())
        return self

    def __exit__(self, *exc) -> bool:
        tracer = self.tracer
        tracer.current = self.prev
        if self.ctx.sampled:
            m1 = (tracer.clock() if tracer.mono is tracer.clock
                  else tracer.mono())
            tracer._record(SpanRecord(
                name=self.name, cat=self.cat, role=tracer.role,
                t0=self.t0, dur=m1 - self.m0,
                trace_id=self.ctx.trace_id, span_id=self.ctx.span_id,
                parent_id=self.parent_id))
            if self.cat == "stage" and tracer.runtime_metrics is not None:
                tracer.runtime_metrics.observe_stage(
                    self.name[len("stage:"):], m1 - self.m0)
        return False


class _MetricStage:
    """Stage timing with metrics only (tracing off but /metrics on):
    feeds the drain-stage histogram without emitting spans."""

    __slots__ = ("metrics", "stage", "t0")

    def __init__(self, metrics: RuntimeMetrics, stage: str):
        self.metrics = metrics
        self.stage = stage

    def __enter__(self) -> "_MetricStage":
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self.metrics.observe_stage(self.stage,
                                   time.perf_counter() - self.t0)
        return False


class _Noop:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SCOPE = _Noop()


def stage_scope(tracer: "Optional[Tracer]",
                metrics: Optional[RuntimeMetrics], name: str):
    """The one stage-timing entry point (Actor.trace_stage): a traced
    sub-span, a metrics-only timer, or a shared no-op."""
    if tracer is not None:
        return tracer.stage(name)
    if metrics is not None:
        return _MetricStage(metrics, name)
    return NOOP_SCOPE


class Tracer:
    """Per-role span emitter. One per process (deployed) or one per
    harness (sim, shared across roles via per-call role labels is NOT
    done -- each simulated role can share one tracer because the role
    label rides each span via the transport's actor address)."""

    def __init__(self, role: str = "",
                 clock: Optional[Callable[[], float]] = None,
                 sample_rate: float = 1.0,
                 flight=None,
                 runtime_metrics: Optional[RuntimeMetrics] = None,
                 sink_path: Optional[str] = None,
                 max_spans: int = 1 << 20,
                 instance: int = 0):
        self.role = role
        self.clock = clock if clock is not None else time.time
        # Durations are measured on a monotonic clock; a CUSTOM clock
        # (VirtualClock) serves both roles so sim traces stay pure
        # functions of the command sequence.
        self.mono: Callable[[], float] = (
            clock if clock is not None else time.perf_counter)
        # Sampling is 1-in-N at trace ROOTS (deterministic, counter
        # based); propagated contexts keep their bit unchanged.
        self.sample_every = (1 if sample_rate >= 1.0
                             else 0 if sample_rate <= 0.0
                             else max(1, round(1.0 / sample_rate)))
        self.flight = flight
        self.runtime_metrics = runtime_metrics
        self.current: Optional[TraceContext] = None
        self.spans: list[SpanRecord] = []
        self.max_spans = max_spans
        # ``instance`` distinguishes INCARNATIONS of one role: a
        # crash-relaunched role restarts its counter at 0, and with
        # the same role salt its ids would collide with the killed
        # life's in the appended trace.jsonl (the CLI passes the pid;
        # sims keep the default 0 so traces stay deterministic).
        self._salt = ((zlib.crc32(role.encode())
                       ^ ((instance * 0x9E3779B1) & 0xFFFFFFFF))
                      & 0xFFFFFFFF) << 32
        self._next = 0
        self._roots = 0
        # Per-actor: colocated actors (supernode, every sim harness)
        # share one tracer, and actor A's drain must never adopt the
        # context of a receive that went to actor B.
        self._drain_parent: dict[str, TraceContext] = {}
        self._sink = open(sink_path, "a") if sink_path else None
        self._sink_pending = 0

    # --- ids / sampling ---------------------------------------------------
    def _new_id(self) -> int:
        self._next += 1
        return (self._salt | (self._next & 0xFFFFFFFF)) & _MASK64

    def _sample_root(self) -> bool:
        if self.sample_every == 0:
            return False
        self._roots += 1
        return (self._roots - 1) % self.sample_every == 0

    # --- span factories (called by the transports) ------------------------
    def receive_span(self, actor: str, msg_type: str,
                     ctx: Optional[TraceContext]) -> _Scope:
        """The per-message receive span. ``ctx`` is the frame's
        context; a missing context makes this receive a trace ROOT
        (the client-facing edge) under the sampling policy."""
        if ctx is None:
            ctx = TraceContext(trace_id=self._new_id(), span_id=0,
                               sampled=self._sample_root())
        child = TraceContext(trace_id=ctx.trace_id,
                             span_id=self._new_id(),
                             sampled=ctx.sampled)
        if ctx.sampled:
            self._drain_parent[actor] = child
        return _Scope(self, f"receive:{msg_type}@{actor}", "receive",
                      child, ctx.span_id)

    def timer_span(self, actor: str, timer_name: str) -> _Scope:
        ctx = TraceContext(trace_id=self._new_id(),
                           span_id=self._new_id(),
                           sampled=self._sample_root())
        if ctx.sampled:
            self._drain_parent[actor] = ctx
        return _Scope(self, f"timer:{timer_name}@{actor}", "timer",
                      ctx, 0)

    def drain_span(self, actor: str) -> _Scope:
        """The on_drain span: adopts THIS actor's last sampled receive
        of the batch (group commit serves the batch; the adopted
        command's critical path runs through its batch's drain)."""
        parent = self._drain_parent.pop(actor, None)
        if parent is None:
            ctx = TraceContext(trace_id=self._new_id(), span_id=0,
                               sampled=False)
            parent_id = 0
        else:
            ctx = TraceContext(trace_id=parent.trace_id,
                               span_id=self._new_id(),
                               sampled=parent.sampled)
            parent_id = parent.span_id
        return _Scope(self, f"drain@{actor}", "drain", ctx, parent_id)

    def stage(self, name: str):
        """A drain-stage sub-span under the current context (decode,
        handler, quorum-kernel, wal-fsync, send-release)."""
        parent = self.current
        if parent is None or not parent.sampled:
            # No span for unsampled work -- but the RUNTIME METRICS
            # must not be starved by the sampling rate (the Grafana
            # row charts every fsync, not 1-in-N), so fall back to the
            # metrics-only timer when one is attached. It leaves
            # ``current`` untouched, which matches the unsampled span
            # behavior exactly: an unsampled stage reuses the parent
            # context anyway.
            if self.runtime_metrics is not None:
                return _MetricStage(self.runtime_metrics, name)
            ctx = parent if parent is not None else TraceContext(
                trace_id=0, span_id=0, sampled=False)
            return _Scope(self, f"stage:{name}", "stage", ctx, 0)
        ctx = TraceContext(trace_id=parent.trace_id,
                           span_id=self._new_id(), sampled=True)
        return _Scope(self, f"stage:{name}", "stage", ctx,
                      parent.span_id)

    def record_stage(self, name: str, m0: float,
                     ctx: Optional[TraceContext]) -> None:
        """A stage span recorded after the fact (ends now; ``m0`` is a
        ``tracer.mono()`` reading from its start): the TCP receive
        path times message decode before any span scope can be open,
        because decode errors must stay inside the transport's
        corrupt-frame guard."""
        if ctx is None or not ctx.sampled:
            return
        if self.mono is self.clock:
            dur = self.clock() - m0
            t0 = m0
        else:
            dur = self.mono() - m0
            t0 = self.clock() - dur
        self._record(SpanRecord(
            name=f"stage:{name}", cat="stage", role=self.role,
            t0=t0, dur=dur, trace_id=ctx.trace_id,
            span_id=self._new_id(), parent_id=ctx.span_id))
        if self.runtime_metrics is not None:
            self.runtime_metrics.observe_stage(name, dur)

    def event(self, text: str) -> None:
        """An instantaneous flight-recorder note (crash post-mortems:
        'recovering 8124 records', 'phase1 restarted @ round 3')."""
        t = self.clock()
        if self.flight is not None:
            self.flight.record(t, f"event {text}")
        self._record(SpanRecord(
            name=f"event:{text}", cat="event", role=self.role,
            t0=t, dur=0.0, trace_id=0, span_id=self._new_id(),
            parent_id=0))

    # --- record sinks -----------------------------------------------------
    def _record(self, record: SpanRecord) -> None:
        # With a jsonl sink the file IS the record of truth; keeping a
        # second in-memory copy would grow a long-running role by
        # hundreds of MB at full sampling for data nothing reads.
        if self._sink is None and len(self.spans) < self.max_spans:
            self.spans.append(record)
        if self.flight is not None and record.cat != "event":
            self.flight.record(
                record.t0 + record.dur,
                f"{record.name} trace={record.trace_id:016x} "
                f"dur_us={record.dur * 1e6:.1f}")
        if self._sink is not None:
            self._sink.write(json.dumps(record.to_json(),
                                        separators=(",", ":")) + "\n")
            self._sink_pending += 1
            if self._sink_pending >= 64:
                self._sink.flush()
                self._sink_pending = 0

    def flush(self) -> None:
        if self._sink is not None:
            self._sink.flush()
            self._sink_pending = 0

    def close(self) -> None:
        if self._sink is not None:
            self._sink.flush()
            self._sink.close()
            self._sink = None
        if self.flight is not None:
            self.flight.close()

    def dump_jsonl(self, path: str) -> None:
        with open(path, "w") as f:
            for record in self.spans:
                f.write(json.dumps(record.to_json(),
                                   separators=(",", ":")) + "\n")
