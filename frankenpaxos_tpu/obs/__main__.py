"""paxtrace CLI: role trace dumps -> one Perfetto file + breakdown.

Usage::

    python -m frankenpaxos_tpu.obs <dir-or-trace.jsonl>... \
        --out trace.json [--breakdown] [--flight <ring.flight>]

Globs ``*.trace.jsonl`` (spans) and ``*.counters.jsonl`` (paxpulse
device-counter samples) under directories, merges every role's spans
and counter tracks into one Chrome-trace-event JSON (load it at
ui.perfetto.dev or chrome://tracing), prints the drain-stage
latency-breakdown table, and renders flight-recorder rings to their
post-mortem JSON.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from frankenpaxos_tpu.obs.flight import FlightRecorder
from frankenpaxos_tpu.obs.perfetto import (
    format_breakdown,
    latency_breakdown,
    load_jsonl,
    to_chrome_trace,
)
from frankenpaxos_tpu.obs.telemetry import counter_events, load_counters


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="frankenpaxos_tpu.obs")
    parser.add_argument("inputs", nargs="*",
                        help="trace.jsonl files or directories of them")
    parser.add_argument("--out", default=None,
                        help="write merged Chrome-trace JSON here")
    parser.add_argument("--breakdown", action="store_true",
                        help="print the per-stage latency table")
    parser.add_argument("--flight", action="append", default=[],
                        help="flight-recorder ring file to render "
                             "(repeatable); writes <file>.json")
    args = parser.parse_args(argv)

    paths = []
    counter_paths = []
    for item in args.inputs:
        if os.path.isdir(item):
            paths.extend(sorted(glob.glob(
                os.path.join(item, "*.trace.jsonl"))))
            counter_paths.extend(sorted(glob.glob(
                os.path.join(item, "*.counters.jsonl"))))
        elif item.endswith(".counters.jsonl"):
            counter_paths.append(item)
        else:
            paths.append(item)
    records = []
    for path in paths:
        records.extend(load_jsonl(path))
    records.sort(key=lambda r: (r.t0, r.role, r.span_id))
    counters = []
    for path in counter_paths:
        by_role: dict = {}
        for t, role, snap in load_counters(path):
            by_role.setdefault(role, []).append((t, snap))
        for role, samples in sorted(by_role.items()):
            counters.extend(counter_events(samples, role))

    if args.out:
        with open(args.out, "w") as f:
            json.dump(to_chrome_trace(records, counters), f)
        print(f"wrote {args.out} ({len(records)} spans from "
              f"{len(paths)} role dumps, {len(counters)} counter "
              f"events from {len(counter_paths)} paxpulse dumps)")
    if args.breakdown:
        print(format_breakdown(latency_breakdown(records)))
    for ring in args.flight:
        out = ring + ".json"
        dump = FlightRecorder.dump_file(ring, out)
        print(f"wrote {out} ({len(dump['records'])} records)")
    if not (args.out or args.breakdown or args.flight):
        parser.print_usage()
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
