"""The crash flight recorder: a fixed-size ring of recent events that
survives ``kill -9``.

A SIGKILL'd role gets no chance to dump anything -- no signal handler,
no atexit, no buffered-file flush. So the recorder writes every record
straight into an ``mmap``'d file: the kernel owns the dirty pages, and
when the process dies they are still there for whoever reads the file
next (the chaos driver's post-mortem, ``bench/chaos.py``). Records are
fixed-size slots written round-robin with a monotone sequence number,
so the reader reconstructs the last-N-events order without any footer
or index that a crash could tear.

LAYOUT (little-endian)::

    header:  8s magic "FPXFLT1\\n" | u32 slot_count | u32 slot_size
    slot:    u64 seq (0 = never written) | f64 t | u16 len | text bytes

Torn slots are possible only for the single record being written at
the instant of death; the reader drops any slot whose text length
exceeds its slot and keeps everything else.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
from typing import Optional

MAGIC = b"FPXFLT1\n"
_HEADER = struct.Struct("<8sII")
_SLOT = struct.Struct("<QdH")


class FlightRecorder:
    """Fixed-size per-role event ring; ``path=None`` keeps it in memory
    (the sim's variant -- SimTransport crashes are object deaths, so a
    plain buffer owned by the harness survives them)."""

    def __init__(self, path: Optional[str] = None, slots: int = 1024,
                 slot_size: int = 192):
        self.path = path
        self.slots = slots
        self.slot_size = slot_size
        self._seq = 0
        size = _HEADER.size + slots * slot_size
        if path is None:
            self._buf = bytearray(size)
            self._mm = None
        else:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            # O_RDWR + ftruncate (not "wb") so a restarted role REUSES
            # the ring, seeding its sequence past the crash's records
            # instead of clobbering them.
            fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
            try:
                if os.fstat(fd).st_size != size:
                    os.ftruncate(fd, size)
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
            self._buf = self._mm
            header = bytes(self._buf[:_HEADER.size])
            if header[:8] == MAGIC:
                magic, old_slots, old_size = _HEADER.unpack(header)
                if (old_slots, old_size) == (slots, slot_size):
                    self._seq = max(
                        (seq for seq, _, _ in _iter_slots(
                            self._buf, slots, slot_size)), default=0)
        _HEADER.pack_into(self._buf, 0, MAGIC, slots, slot_size)

    def record(self, t: float, text: str) -> None:
        """Write one record into the next slot. Cheap enough for the
        hot path: one pack_into + one memcpy into the mapping."""
        self._seq += 1
        offset = _HEADER.size + (
            (self._seq - 1) % self.slots) * self.slot_size
        data = text.encode("utf-8", "replace")[
            :self.slot_size - _SLOT.size]
        _SLOT.pack_into(self._buf, offset, self._seq, t, len(data))
        start = offset + _SLOT.size
        self._buf[start:start + len(data)] = data
        # Zero the slot's tail so a shorter record never leaves a
        # previous record's bytes visible past its length.
        end = offset + self.slot_size
        self._buf[start + len(data):end] = bytes(
            end - start - len(data))

    def records(self) -> list:
        """All live records, oldest first: [(seq, t, text)]."""
        return sorted(_iter_slots(self._buf, self.slots, self.slot_size))

    def dump(self) -> dict:
        return {"slots": self.slots, "slot_size": self.slot_size,
                "records": [{"seq": seq, "t": round(t, 9), "text": text}
                            for seq, t, text in self.records()]}

    def close(self) -> None:
        if self._mm is not None:
            self._mm.flush()
            self._mm.close()
            self._mm = None
            self._buf = bytearray(0)

    # --- post-mortem readers ----------------------------------------------
    @classmethod
    def read(cls, path: str) -> list:
        """Records from a (possibly crashed) role's ring file, oldest
        first -- the post-mortem entry point; never needs the writing
        process to have exited cleanly."""
        with open(path, "rb") as f:
            data = f.read()
        if len(data) < _HEADER.size:
            raise ValueError(f"{path}: truncated flight-recorder file")
        magic, slots, slot_size = _HEADER.unpack_from(data, 0)
        if magic != MAGIC:
            raise ValueError(f"{path}: bad flight-recorder magic")
        if _HEADER.size + slots * slot_size > len(data):
            raise ValueError(f"{path}: flight-recorder file shorter "
                             f"than its declared ring")
        return sorted(_iter_slots(data, slots, slot_size))

    @classmethod
    def dump_file(cls, path: str, out_path: str) -> dict:
        """Read ``path`` and write the post-mortem JSON to
        ``out_path``; returns the dump dict."""
        dump = {"source": path,
                "records": [{"seq": seq, "t": round(t, 9), "text": text}
                            for seq, t, text in cls.read(path)]}
        with open(out_path, "w") as f:
            json.dump(dump, f, indent=2)
        return dump


def _iter_slots(buf, slots: int, slot_size: int):
    for i in range(slots):
        offset = _HEADER.size + i * slot_size
        seq, t, length = _SLOT.unpack_from(buf, offset)
        if seq == 0 or length > slot_size - _SLOT.size:
            continue  # empty, or torn by the crash mid-write
        start = offset + _SLOT.size
        text = bytes(buf[start:start + length]).decode("utf-8",
                                                       "replace")
        yield seq, t, text
