"""Span records -> Chrome-trace-event JSON, critical paths, breakdowns.

``to_chrome_trace`` emits the Trace Event Format's complete events
(``ph: "X"``) that both Perfetto (ui.perfetto.dev) and
chrome://tracing load directly: one track (pid/tid) per role, span
nesting from start/duration, trace and span ids in ``args`` so a
command's hops can be followed across role tracks.

``latency_breakdown`` is the per-stage table the overhead/alignment
analysis prints: where a command's latency goes -- queueing vs decode
vs handler vs quorum kernel vs WAL fsync vs send -- the attribution
"The Performance of Paxos in the Cloud" shows cloud deployments lose
their budget without.
"""

from __future__ import annotations

import json
from typing import Iterable

from frankenpaxos_tpu.obs.trace import SpanRecord


def load_jsonl(path: str) -> list:
    """SpanRecords from one role's ``*.trace.jsonl`` dump (tolerates a
    torn final line -- roles die mid-write in chaos runs)."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(SpanRecord.from_json(json.loads(line)))
            except (ValueError, KeyError):
                continue
    return records


def to_chrome_trace(records: Iterable[SpanRecord],
                    counters: Iterable[dict] = ()) -> dict:
    """The Trace Event Format dict (``json.dump`` it; Perfetto and
    chrome://tracing both load it).

    ``counters`` are ready-made COUNTER events (``ph: "C"``, e.g. from
    ``obs.telemetry.counter_events``): Perfetto renders them as value
    tracks alongside the span tracks, so the paxpulse device counters
    line up under the host spans on one timeline."""
    events = []
    roles = {}
    for record in records:
        tid = roles.setdefault(record.role or "role", len(roles) + 1)
        event = {
            "name": record.name,
            "cat": record.cat,
            "ph": "X" if record.cat != "event" else "i",
            "ts": round(record.t0 * 1e6, 3),   # microseconds
            "pid": 1,
            "tid": tid,
            "args": {"trace_id": f"{record.trace_id:016x}",
                     "span_id": f"{record.span_id:016x}",
                     "parent_id": f"{record.parent_id:016x}"},
        }
        if record.cat != "event":
            event["dur"] = round(record.dur * 1e6, 3)
        else:
            event["s"] = "t"
        events.append(event)
    for role, tid in roles.items():
        events.append({"name": "thread_name", "ph": "M", "pid": 1,
                       "tid": tid, "args": {"name": role}})
    events.extend(counters)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def trace_tree(records: Iterable[SpanRecord], trace_id: int) -> dict:
    """One command's causal tree: every span of ``trace_id`` keyed by
    span_id with its children resolved -- the critical-path walk's
    input. Returns {"spans": {span_id: record}, "children":
    {span_id: [span_id]}, "roots": [span_id], "critical_path":
    [record]} where the critical path follows, from the
    latest-finishing root, the child whose SUBTREE finishes last (the
    chain that determined the command's end-to-end latency -- a hop's
    consequences can outlive the hop's own span, e.g. a handler stage
    whose send triggers the reply's receive on another role)."""
    spans = {r.span_id: r for r in records if r.trace_id == trace_id}
    children: dict = {}
    roots = []
    for sid, record in spans.items():
        if record.parent_id in spans:
            children.setdefault(record.parent_id, []).append(sid)
        else:
            roots.append(sid)

    subtree_end: dict = {}

    def end_of(sid: int) -> float:
        cached = subtree_end.get(sid)
        if cached is None:
            cached = max([spans[sid].t0 + spans[sid].dur]
                         + [end_of(kid)
                            for kid in children.get(sid, ())])
            subtree_end[sid] = cached
        return cached

    path = []
    if roots:
        at = max(roots, key=end_of)
        while True:
            path.append(spans[at])
            kids = children.get(at)
            if not kids:
                break
            at = max(kids, key=end_of)
    return {"spans": spans, "children": children, "roots": roots,
            "critical_path": path}


def latency_breakdown(records: Iterable[SpanRecord]) -> dict:
    """Per-stage totals: {stage/category name: {count, total_us,
    mean_us, p50_us, p99_us, max_us}}. Stage sub-spans are keyed by
    their stage name; receive/timer/drain spans by category."""
    buckets: dict = {}
    for record in records:
        if record.cat == "stage":
            key = record.name[len("stage:"):]
        elif record.cat == "event":
            continue
        else:
            key = record.cat
        buckets.setdefault(key, []).append(record.dur)
    table = {}
    for key, durs in sorted(buckets.items()):
        durs.sort()
        n = len(durs)
        table[key] = {
            "count": n,
            "total_us": round(sum(durs) * 1e6, 1),
            "mean_us": round(sum(durs) / n * 1e6, 2),
            "p50_us": round(durs[n // 2] * 1e6, 2),
            "p99_us": round(durs[min(n - 1, (99 * n) // 100)] * 1e6, 2),
            "max_us": round(durs[-1] * 1e6, 2),
        }
    return table


def format_breakdown(table: dict) -> str:
    """The human latency-breakdown table (docs/OBSERVABILITY.md)."""
    header = (f"{'stage':<16} {'count':>8} {'total_us':>12} "
              f"{'mean_us':>10} {'p50_us':>10} {'p99_us':>10} "
              f"{'max_us':>10}")
    lines = [header, "-" * len(header)]
    for key, row in table.items():
        lines.append(
            f"{key:<16} {row['count']:>8} {row['total_us']:>12.1f} "
            f"{row['mean_us']:>10.2f} {row['p50_us']:>10.2f} "
            f"{row['p99_us']:>10.2f} {row['max_us']:>10.2f}")
    return "\n".join(lines)
