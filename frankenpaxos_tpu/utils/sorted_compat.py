"""Pure-Python fallbacks for ``sortedcontainers``.

``sortedcontainers`` is a runtime dependency (pyproject.toml), but some
execution environments (stripped CI images, the growth container) lack
it. The framework only leans on a tiny slice of its API -- TopK's
``SortedSet`` (add/pop/update/iterate) and the acceptors' ``SortedDict``
(mapping + ``irange(minimum=...)``) -- so these bisect-backed stand-ins
keep every protocol importable with identical semantics at somewhat
worse asymptotics. Import sites prefer the real library when present::

    try:
        from sortedcontainers import SortedDict
    except ImportError:
        from frankenpaxos_tpu.utils.sorted_compat import SortedDict
"""

from __future__ import annotations

import bisect
from typing import Iterable, Iterator


class SortedSet:
    """Ordered unique values: the subset of
    ``sortedcontainers.SortedSet`` used by ``utils.topk.TopK``."""

    def __init__(self, iterable: Iterable = ()):
        self._items: list = sorted(set(iterable))

    def add(self, value) -> None:
        i = bisect.bisect_left(self._items, value)
        if i == len(self._items) or self._items[i] != value:
            self._items.insert(i, value)

    def update(self, iterable: Iterable) -> None:
        for value in iterable:
            self.add(value)

    def pop(self, index: int = -1):
        return self._items.pop(index)

    def discard(self, value) -> None:
        i = bisect.bisect_left(self._items, value)
        if i < len(self._items) and self._items[i] == value:
            self._items.pop(i)

    def __contains__(self, value) -> bool:
        i = bisect.bisect_left(self._items, value)
        return i < len(self._items) and self._items[i] == value

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self) -> Iterator:
        return iter(self._items)

    def __repr__(self) -> str:
        return f"SortedSet({self._items!r})"


class SortedDict(dict):
    """A dict iterated in key order, plus ``irange``: the subset of
    ``sortedcontainers.SortedDict`` the acceptors use.

    Keys are re-sorted lazily: inserts are O(1) and each ordered read
    (``irange``/``items``/``keys``/iteration) sorts once if anything
    changed since the last read -- the acceptor access pattern is long
    insert runs punctuated by occasional Phase1b scans, where this is
    near-optimal.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._sorted: list | None = None

    def __setitem__(self, key, value) -> None:
        if key not in self:
            self._sorted = None
        super().__setitem__(key, value)

    def __delitem__(self, key) -> None:
        super().__delitem__(key)
        self._sorted = None

    def pop(self, *args):
        self._sorted = None
        return super().pop(*args)

    def popitem(self):
        self._sorted = None
        return super().popitem()

    def clear(self) -> None:
        super().clear()
        self._sorted = None

    def setdefault(self, key, default=None):
        if key not in self:
            self._sorted = None
        return super().setdefault(key, default)

    def update(self, *args, **kwargs) -> None:
        super().update(*args, **kwargs)
        self._sorted = None

    def _keys(self) -> list:
        if self._sorted is None:
            self._sorted = sorted(super().keys())
        return self._sorted

    def irange(self, minimum=None, maximum=None) -> Iterator:
        keys = self._keys()
        lo = 0 if minimum is None else bisect.bisect_left(keys, minimum)
        hi = len(keys) if maximum is None else bisect.bisect_right(
            keys, maximum)
        return iter(keys[lo:hi])

    def __iter__(self) -> Iterator:
        return iter(self._keys())

    def keys(self):
        return self._keys()

    def values(self):
        return [self[k] for k in self._keys()]

    def items(self):
        return [(k, self[k]) for k in self._keys()]

    def peekitem(self, index: int = -1):
        key = self._keys()[index]
        return key, self[key]
