"""TopOne / TopK: per-leader maxima of seen vertex ids.

Reference behavior: util/TopOne.scala:6+, util/TopK.scala:6+,
util/VertexIdLike.scala:9+. Used by BPaxos-family dependency tracking:
a TopOne over vertex ids is a per-leader watermark vector (``max id + 1``
seen per leader column); TopK keeps the k largest ids per leader.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Generic, TypeVar

import numpy as np

try:
    from sortedcontainers import SortedSet  # type: ignore[import-untyped]
except ImportError:  # stripped environments: pure-Python fallback
    from frankenpaxos_tpu.utils.sorted_compat import SortedSet

V = TypeVar("V")


@dataclasses.dataclass(frozen=True)
class VertexIdLike(Generic[V]):
    """How to view V as a (leader_index, id) vertex id
    (util/VertexIdLike.scala:9)."""

    leader_index: Callable[[V], int]
    id: Callable[[V], int]


# The standard view for tuple-shaped vertex ids ((leader_index, id)
# tuples or NamedTuples like EPaxos Instance / BPaxos VertexId).
TUPLE_VERTEX_LIKE: "VertexIdLike" = VertexIdLike(
    leader_index=lambda v: v[0], id=lambda v: v[1])


class TopOne(Generic[V]):
    """Per-leader ``max(id) + 1`` over everything put (TopOne.scala:6+)."""

    def __init__(self, num_leaders: int, like: VertexIdLike[V]):
        self.like = like
        self.top_ones = np.zeros(num_leaders, dtype=np.int64)

    def put(self, x: V) -> None:
        i = self.like.leader_index(x)
        self.top_ones[i] = max(self.top_ones[i], self.like.id(x) + 1)

    def get(self) -> list[int]:
        return self.top_ones.tolist()

    def merge_equals(self, other: "TopOne[V]") -> None:
        np.maximum(self.top_ones, other.top_ones, out=self.top_ones)


class TopK(Generic[V]):
    """The k largest ids seen per leader (TopK.scala:6+)."""

    def __init__(self, k: int, num_leaders: int, like: VertexIdLike[V]):
        self.k = k
        self.like = like
        self.top: list[SortedSet] = [SortedSet() for _ in range(num_leaders)]

    def put(self, x: V) -> None:
        ids = self.top[self.like.leader_index(x)]
        ids.add(self.like.id(x))
        if len(ids) > self.k:
            ids.pop(0)

    def get(self) -> list[list[int]]:
        return [list(ids) for ids in self.top]

    def merge_equals(self, other: "TopK[V]") -> None:
        for ids, other_ids in zip(self.top, other.top):
            ids.update(other_ids)
            while len(ids) > self.k:
                ids.pop(0)
