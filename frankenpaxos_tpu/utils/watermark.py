"""Quorum watermarks: "largest k such that >= quorum_size watermarks >= k".

Reference behavior: util/QuorumWatermark.scala:31-50 and
util/QuorumWatermarkVector.scala:20+. Watermarks only increase. Sorted
descending, the answer is the quorum_size'th entry -- itself a batched
reduction, so the vector form has a device twin in ops/watermark.py.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


class QuorumWatermark:
    """n monotonically-increasing integer watermarks with quorum queries."""

    def __init__(self, num_watermarks: int):
        self._watermarks = np.zeros(num_watermarks, dtype=np.int64)

    def __repr__(self):
        return f"QuorumWatermark({self._watermarks.tolist()})"

    @property
    def num_watermarks(self) -> int:
        return self._watermarks.shape[0]

    def update(self, index: int, watermark: int) -> None:
        self._watermarks[index] = max(self._watermarks[index], watermark)

    def watermark(self, quorum_size: int) -> int:
        if not 1 <= quorum_size <= self.num_watermarks:
            raise ValueError(
                f"quorum_size {quorum_size} out of [1, {self.num_watermarks}]")
        return int(np.sort(self._watermarks)[self.num_watermarks - quorum_size])


class QuorumWatermarkVector:
    """n vector-valued watermarks; every depth column is an independent
    QuorumWatermark (QuorumWatermarkVector.scala:20+)."""

    def __init__(self, n: int, depth: int):
        self._watermarks = np.zeros((n, depth), dtype=np.int64)

    def __repr__(self):
        return f"QuorumWatermarkVector({self._watermarks.tolist()})"

    def update(self, index: int, watermark: Sequence[int]) -> None:
        w = np.asarray(watermark, dtype=np.int64)
        self._watermarks[index, :w.shape[0]] = np.maximum(
            self._watermarks[index, :w.shape[0]], w)

    def watermark(self, quorum_size: int,
                  backend: str = "host") -> list[int]:
        """``backend="tpu"`` evaluates the reduction through the device
        twin (ops/watermark.py); ``"host"`` is the numpy oracle."""
        n = self._watermarks.shape[0]
        if not 1 <= quorum_size <= n:
            raise ValueError(f"quorum_size {quorum_size} out of [1, {n}]")
        if backend == "tpu":
            from frankenpaxos_tpu.ops.watermark import (
                quorum_watermark_vector,
            )

            return quorum_watermark_vector(
                self._watermarks, quorum_size=quorum_size).tolist()
        return np.sort(self._watermarks, axis=0)[n - quorum_size].tolist()
