"""Host-side log-storage and watermark utilities (reference: util/)."""

from frankenpaxos_tpu.utils.buffer_map import BufferMap
from frankenpaxos_tpu.utils.topk import TopK, TopOne, VertexIdLike
from frankenpaxos_tpu.utils.watermark import (
    QuorumWatermark,
    QuorumWatermarkVector,
)

__all__ = [
    "BufferMap",
    "QuorumWatermark",
    "QuorumWatermarkVector",
    "TopOne",
    "TopK",
    "VertexIdLike",
]
