"""BufferMap: a dense int-keyed log with a garbage-collection watermark.

Reference behavior: util/BufferMap.scala:8-66. Semantics:

- ``get``/``put``/``contains`` over integer keys;
- keys below the GC ``watermark`` read as absent and writes to them are
  silently dropped (they were already executed/collected);
- ``garbage_collect(w)`` discards everything below ``w``; the watermark
  only increases.

This is the host twin of the device window layout (ops/quorum.py's
VoteBoard ring): dense storage + watermark is the memory model for the
unbounded replicated log across the framework.
"""

from __future__ import annotations

from typing import Generic, Iterator, Optional, TypeVar

V = TypeVar("V")


class BufferMap(Generic[V]):
    def __init__(self, grow_size: int = 5000):
        self.grow_size = grow_size
        self._buffer: list[Optional[V]] = [None] * grow_size
        self._watermark = 0
        self._largest_key = -1

    def __repr__(self):
        return f"BufferMap(watermark={self._watermark}, {self.to_dict()!r})"

    @property
    def watermark(self) -> int:
        return self._watermark

    @property
    def largest_key(self) -> int:
        return self._largest_key

    def get(self, key: int) -> Optional[V]:
        i = key - self._watermark
        if i < 0 or i >= len(self._buffer):
            return None
        return self._buffer[i]

    def put(self, key: int, value: V) -> None:
        self._largest_key = max(self._largest_key, key)
        i = key - self._watermark
        if i < 0:
            return
        if i >= len(self._buffer):
            self._buffer.extend([None] * (i + 1 + self.grow_size
                                          - len(self._buffer)))
        self._buffer[i] = value

    def contains(self, key: int) -> bool:
        return self.get(key) is not None

    def garbage_collect(self, watermark: int) -> None:
        if watermark <= self._watermark:
            return
        drop = min(watermark - self._watermark, len(self._buffer))
        del self._buffer[:drop]
        self._watermark = watermark

    def items(self, start: int = 0) -> Iterator[tuple[int, V]]:
        """Present (key, value) pairs from ``max(start, watermark)`` up to
        the largest key ever put."""
        for key in range(max(start, self._watermark), self._largest_key + 1):
            value = self.get(key)
            if value is not None:
                yield key, value

    def to_dict(self) -> dict[int, V]:
        return dict(self.items())
