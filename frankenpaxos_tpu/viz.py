"""Execution trace visualization.

The reference ships 23 Scala.js in-browser protocol visualizations
(js/src/main/...; SURVEY.md section 1 L5): every protocol wired over a
JsTransport, stepped interactively, with live actor state rendered by
Vue. The TPU-native replacement covers the same ground without a
browser runtime dependency:

  * :class:`TraceRecorder` -- post-hoc: snapshot a SimTransport's
    delivery/timer history as viewer JSON.
  * :class:`LiveTraceRecorder` -- attached: wraps the transport's
    ``deliver_message``/``trigger_timer`` so every step also captures
    the receiving actor's state (shallow field summary), giving the
    viewer per-step state panels like the reference's ``@JSExportAll``
    state rendering.
  * :func:`record_scenario` -- wire ANY registry protocol over a
    SimTransport (the deployment registry supplies config + roles +
    client + drive), run a seeded random interleaving of commands and
    deliveries, and record it. One command visualizes any of the 20
    protocols -- the analog of the reference's per-protocol pages.
  * :func:`dump_html` -- emit a SELF-CONTAINED interactive HTML page
    (viewer + inlined trace): actor lanes, step slider, in-flight
    messages, per-actor state at the selected step.

Usage::

    python -m frankenpaxos_tpu.viz --protocol multipaxos --steps 120 \
        --out multipaxos_trace.html
"""

from __future__ import annotations

import json
import os
import random
from typing import Optional

from frankenpaxos_tpu.runtime.sim_transport import (
    DeliverMessage,
    SimTransport,
    TriggerTimer,
)

_SKIP_FIELDS = ("transport", "logger", "serializer", "rng", "config",
                "state_machine", "heartbeat", "election", "checker",
                "tracker", "collectors")
_MAX_REPR = 160


def _fmt(value) -> str:
    try:
        text = repr(value)
    except Exception:  # noqa: BLE001 - reprs of live state may fail
        text = f"<{type(value).__name__}>"
    if len(text) > _MAX_REPR:
        text = text[:_MAX_REPR - 1] + "…"
    return text


def snapshot_actor(actor) -> dict:
    """A shallow, repr-truncated view of an actor's protocol state (the
    reference renders actor fields the same way, via @JSExportAll)."""
    out = {}
    for key, value in vars(actor).items():
        if key.startswith("_") or key in _SKIP_FIELDS:
            continue
        if callable(value):
            continue
        out[key] = _fmt(value)
    return out


class TraceRecorder:
    """Snapshots a SimTransport's history into viewer JSON (post-hoc:
    events only, no per-step state)."""

    def __init__(self, transport: SimTransport):
        self.transport = transport

    def events(self) -> list[dict]:
        events = []
        for i, command in enumerate(self.transport.history):
            if isinstance(command, DeliverMessage):
                message = command.message
                events.append({
                    "step": i,
                    "kind": "deliver",
                    "src": str(message.src),
                    "dst": str(message.dst),
                    "bytes": len(message.data),
                    "label": _message_label(self.transport, message),
                })
            elif isinstance(command, TriggerTimer):
                events.append({
                    "step": i,
                    "kind": "timer",
                    "src": str(command.address),
                    "dst": str(command.address),
                    "label": command.name,
                })
        return events

    def to_dict(self) -> dict:
        return {
            "actors": [str(a) for a in self.transport.actors],
            "events": self.events(),
        }

    def dump(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2)
        return path


class LiveTraceRecorder:
    """Wraps a SimTransport so each delivery/timer step records the
    event AND the receiving actor's post-step state snapshot.

    ``labels`` maps raw transport addresses to human-readable names
    (role_index); unmapped addresses stringify as-is.
    """

    def __init__(self, transport: SimTransport,
                 protocol: Optional[str] = None,
                 labels: Optional[dict] = None):
        self.transport = transport
        self.protocol = protocol
        self.labels = labels or {}
        self.events: list[dict] = []
        self._attached = False

    def _name(self, address) -> str:
        return self.labels.get(address, str(address))

    def attach(self) -> "LiveTraceRecorder":
        if self._attached:
            return self
        self._attached = True
        transport = self.transport
        deliver, trigger = (transport.deliver_message,
                            transport.trigger_timer)

        def recording_deliver(message):
            event = {
                "step": len(self.events),
                "kind": "deliver",
                "src": self._name(message.src),
                "dst": self._name(message.dst),
                "bytes": len(message.data),
                "label": _message_label(transport, message),
            }
            before = len(transport.history)
            deliver(message)
            # Dropped deliveries (partitioned/unknown destination) never
            # reach history and must not appear in the trace either
            # (sim_transport.py:135-137; mirrors the post-hoc recorder).
            if len(transport.history) > before:
                self._finish(event, message.dst)

        def recording_trigger(timer_id):
            timer = transport.timers.get(timer_id)
            event = {
                "step": len(self.events),
                "kind": "timer",
                "src": self._name(timer.address) if timer else "?",
                "dst": self._name(timer.address) if timer else "?",
                "label": timer.name if timer else "?",
            }
            before = len(transport.history)
            trigger(timer_id)
            if len(transport.history) > before:
                self._finish(event,
                             timer.address if timer is not None else None)

        transport.deliver_message = recording_deliver
        transport.trigger_timer = recording_trigger
        return self

    def _finish(self, event: dict, dst) -> None:
        actor = self.transport.actors.get(dst)
        if actor is not None:
            event["state"] = snapshot_actor(actor)
        event["inflight"] = len(self.transport.messages)
        self.events.append(event)

    def mark(self, label: str) -> None:
        """Insert an annotation event (e.g. 'client issues write 3')."""
        self.events.append({"step": len(self.events), "kind": "mark",
                            "src": "", "dst": "", "label": label})

    def to_dict(self) -> dict:
        return {
            "protocol": self.protocol,
            "actors": [self._name(a) for a in self.transport.actors],
            "events": self.events,
        }


def _message_label(transport: SimTransport, message) -> str:
    actor = transport.actors.get(message.dst)
    if actor is None:
        return "?"
    try:
        decoded = actor.serializer.from_bytes(message.data)
        return type(decoded).__name__
    except Exception:
        return "?"


def record_scenario(protocol_name: str, *, steps: int = 120,
                    num_commands: int = 5, f: int = 1,
                    seed: int = 0) -> dict:
    """Wire ``protocol_name`` over a SimTransport via the deployment
    registry, run a seeded interleaving of client commands and message
    deliveries/timers, and return the recorded trace dict."""
    from frankenpaxos_tpu.deploy import DeployCtx, get_protocol
    from frankenpaxos_tpu.runtime import FakeLogger, LogLevel

    protocol = get_protocol(protocol_name)
    # Fake "ports": the registry's cluster generator just needs unique
    # addresses; SimTransport treats them as opaque keys.
    counter = {"next": 0}

    def fake_port():
        counter["next"] += 1
        return ["sim", counter["next"]]

    raw = protocol.cluster(f, fake_port)
    config = protocol.load_config(raw)

    # Human-readable lane names: role_index from the cluster layout
    # (covers embedded sub-actors like elections/heartbeats too).
    labels: dict = {}
    counts: dict = {}

    def walk(key, node):
        if (isinstance(node, list) and len(node) == 2
                and not isinstance(node[0], list)):
            prefix = key.rstrip("s")
            index = counts.get(prefix, 0)
            counts[prefix] = index + 1
            labels[(node[0], int(node[1]))] = f"{prefix}_{index}"
        elif isinstance(node, list):
            for item in node:
                walk(key, item)

    for key, node in raw.items():
        if isinstance(node, list):
            walk(key, node)

    logger = FakeLogger(LogLevel.FATAL)
    transport = SimTransport(logger)
    recorder = LiveTraceRecorder(transport, protocol=protocol_name,
                                 labels=labels)
    recorder.attach()
    ctx = DeployCtx(config=config, transport=transport, logger=logger,
                    overrides={}, seed=seed, state_machine="AppendLog")
    for role_name, role in protocol.roles.items():
        for index, address in enumerate(role.addresses(config)):
            ctx.seed = seed + index
            role.make(ctx, address, index)
    client_ctx = DeployCtx(config=config, transport=transport,
                           logger=logger, overrides={}, seed=seed + 100)
    client_address = ("sim", "client-0")
    labels[client_address] = "client_0"
    client = protocol.make_client(client_ctx, client_address)

    rng = random.Random(seed)
    issued = completed = 0
    replies = []
    for _ in range(steps):
        # One outstanding command: drive() reuses pseudonym 0, and a
        # client allows one pending op per pseudonym.
        can_issue = issued < num_commands and issued == len(replies)
        command = transport.generate_command(rng)
        if can_issue and (command is None or rng.random() < 0.2):
            recorder.mark(f"client issues command {issued}")
            protocol.drive(client, issued,
                           lambda *_: replies.append(True))
            issued += 1
        elif command is not None:
            transport.run_command(command)
        else:
            break
        completed = len(replies)
    # Settle: drain residual messages (and resend timers, which recover
    # anything the random phase left stranded) so the trace ends with
    # completed commands.
    for _ in range(8):
        transport.deliver_all()
        if len(replies) >= issued:
            break
        for timer in list(transport.running_timers()):
            if timer.name.startswith(("resend", "repropose")):
                transport.trigger_timer(timer.id)
    completed = len(replies)
    recorder.mark(f"{completed}/{issued} commands completed")
    return recorder.to_dict()


def viewer_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "viz_viewer.html")


def dump_html(trace: dict, path: str) -> str:
    """Write a self-contained interactive page: the viewer with the
    trace JSON inlined (no fetch/CORS, opens anywhere)."""
    with open(viewer_path()) as f:
        html = f.read()
    payload = json.dumps(trace).replace("</", "<\\/")
    html = html.replace("/*__TRACE_JSON__*/null", payload)
    with open(path, "w") as f:
        f.write(html)
    return path


def main(argv=None) -> None:
    import argparse

    from frankenpaxos_tpu.deploy import PROTOCOL_NAMES

    parser = argparse.ArgumentParser()
    parser.add_argument("--protocol", required=True,
                        choices=PROTOCOL_NAMES)
    parser.add_argument("--steps", type=int, default=120)
    parser.add_argument("--num_commands", type=int, default=5)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--out", default=None,
                        help=".html (self-contained) or .json")
    args = parser.parse_args(argv)

    trace = record_scenario(args.protocol, steps=args.steps,
                            num_commands=args.num_commands,
                            seed=args.seed)
    out = args.out or f"{args.protocol}_trace.html"
    if out.endswith(".json"):
        with open(out, "w") as f:
            json.dump(trace, f, indent=2)
    else:
        dump_html(trace, out)
    print(f"wrote {out} ({len(trace['events'])} events, "
          f"{len(trace['actors'])} actors)")


if __name__ == "__main__":
    main()
