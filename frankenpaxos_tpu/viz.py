"""Execution trace visualization.

The reference ships 23 Scala.js in-browser protocol visualizations
(js/src/main/...; SURVEY.md section 1 L5). The TPU-native replacement:
record a SimTransport execution's delivery/timer history plus per-step
actor annotations, dump it as JSON, and render it as an interactive
sequence diagram in a dependency-free HTML viewer
(``frankenpaxos_tpu/viz_viewer.html``).

Usage::

    recorder = TraceRecorder(transport)
    ... run the protocol ...
    recorder.dump("trace.json")
    # open viz_viewer.html and load trace.json
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

from frankenpaxos_tpu.runtime.sim_transport import (
    DeliverMessage,
    SimTransport,
    TriggerTimer,
)


class TraceRecorder:
    """Snapshots a SimTransport's history into viewer JSON."""

    def __init__(self, transport: SimTransport):
        self.transport = transport

    def events(self) -> list[dict]:
        events = []
        for i, command in enumerate(self.transport.history):
            if isinstance(command, DeliverMessage):
                message = command.message
                events.append({
                    "step": i,
                    "kind": "deliver",
                    "src": str(message.src),
                    "dst": str(message.dst),
                    "bytes": len(message.data),
                    "label": _message_label(self.transport, message),
                })
            elif isinstance(command, TriggerTimer):
                events.append({
                    "step": i,
                    "kind": "timer",
                    "src": str(command.address),
                    "dst": str(command.address),
                    "label": command.name,
                })
        return events

    def to_dict(self) -> dict:
        return {
            "actors": [str(a) for a in self.transport.actors],
            "events": self.events(),
        }

    def dump(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2)
        return path


def _message_label(transport: SimTransport, message) -> str:
    actor = transport.actors.get(message.dst)
    if actor is None:
        return "?"
    try:
        decoded = actor.serializer.from_bytes(message.data)
        return type(decoded).__name__
    except Exception:
        return "?"


def viewer_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "viz_viewer.html")
