"""paxepoch wire messages, shared by every reconfig-wired protocol.

The config-change command flow (leader-driven, docs/RECONFIG.md):

  admin --Reconfigure--> leader
  leader --EpochCommit--> old members + new members + proxy leaders
                          + peer leaders        (resent until acked)
  acceptor: WAL the epoch, THEN --EpochAck--> leader (group commit)
  leader: write quorum of OLD-epoch acks => epoch ACTIVE; buffered
          proposals open the new epoch's slots as EpochPhase2aRun

Only the proposal direction carries an epoch tag: acks are
slot-addressed and epochs partition slot space, so a vote's epoch is
derivable; but a proposal must not be fanned out by a proxy whose
store has not seen the epoch yet -- the tag lets the proxy stash the
run until the (resent) EpochCommit arrives instead of mis-routing it.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Reconfigure:
    """Admin request: replace the acceptor set with ``members``
    (2f+1 addresses; any overlap with the current set is fine --
    single-member swaps are the repair path)."""

    members: tuple  # tuple[Address, ...]


@dataclasses.dataclass(frozen=True)
class EpochCommit:
    """The epoch map entry, broadcast by the proposing leader until
    acked: slots >= ``start_slot`` are governed by ``members``.

    ``round`` is the committing leader's Paxos round: epoch entries are
    ROUND-MONOTONE per epoch id (a higher-round commit for the same
    epoch supersedes a lower-round one), which serializes concurrent
    leaders racing to define epoch e+1 exactly as Phase2a rounds
    serialize value proposals -- an ACTIVATED definition (f+1 old-epoch
    durable acks) is visible to any later leader's Phase1 read quorum,
    so it is adopted rather than replaced (docs/RECONFIG.md)."""

    epoch: int
    start_slot: int
    f: int
    round: int
    members: tuple  # tuple[Address, ...]


@dataclasses.dataclass(frozen=True)
class EpochAck:
    """Durability receipt for one EpochCommit. From acceptors it is
    released only after the WalEpoch record's group-commit fsync
    (DurableRole), which is what makes an old-epoch write quorum of
    acks a matchmaker-grade commit. Echoes the commit's round so a
    preempted leader's stale acks are not mistaken for the new
    round's."""

    epoch: int
    round: int


@dataclasses.dataclass(frozen=True)
class EpochPhase2aRun:
    """A Phase2aRun whose slots belong to epoch ``epoch``: the proxy
    leader fans it to that epoch's members (f+1 thrifty sample) and
    counts the acks under that epoch's spec. A proxy that does not
    know the epoch yet stashes the run until the EpochCommit resend
    lands -- never mis-routes it to the old set."""

    epoch: int
    start_slot: int
    round: int
    values: tuple  # tuple[CommandBatchOrNoop, ...], one per slot
