"""paxepoch: live reconfiguration with matchmaker-backed epochs.

The BASELINE north star's "Matchmaker reconfiguration (quorum-matrix
reshape)" capability, grown into a subsystem the workhorse protocol
families share (docs/RECONFIG.md):

  * ``reconfig.epoch`` -- ``EpochConfig`` / ``EpochStore``: epoch id ->
    acceptor set + QuorumSpec, watermark-partitioned over slot space,
    persisted through ``wal.records.WalEpoch`` in the closed WAL tag
    space.
  * ``reconfig.messages`` / ``reconfig.wire`` -- the config-change
    command flow (Reconfigure -> EpochCommit -> EpochAck, epoch-tagged
    EpochPhase2aRun proposals), fixed-layout codecs on the wire's
    extended tag page (128-131), corrupt-frame-fuzz gated.
  * ``reconfig.tracker`` -- ``EpochQuorumTracker``: address-keyed,
    epoch-segmented vote counting (dict oracle or the TPU
    ``EpochSegmentedChecker`` whose fused kernels span the handover
    boundary; ``ops.quorum`` owns the reshape gather).

MultiPaxos wires the full leader-driven flow (propose epoch e+1,
Phase1-with-both-configs over the Flexible-Paxos intersection
condition, watermark-bounded handover); Mencius reuses the store,
messages, and tracker per leader group.
"""

from frankenpaxos_tpu.reconfig.epoch import EpochConfig, EpochStore  # noqa: F401
from frankenpaxos_tpu.reconfig.messages import (  # noqa: F401
    EpochAck,
    EpochCommit,
    EpochPhase2aRun,
    Reconfigure,
)
from frankenpaxos_tpu.reconfig.tracker import EpochQuorumTracker  # noqa: F401
# Importing the wire module registers the extended-page codecs.
from frankenpaxos_tpu.reconfig.wire import (  # noqa: F401
    decode_epoch_config,
    encode_epoch_config,
)
