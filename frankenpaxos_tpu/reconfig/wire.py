"""Fixed-layout codecs for the paxepoch messages (extended tag page).

The primary wire tag space 1..127 filled up by PR 4, so these are the
first tenants of the EXTENDED PAGE (0x00-escape + one tag byte, tags
128..131 -- runtime/serializer.py). Layouts follow the repo's codec
conventions: little-endian fixed-width structs, length-prefixed
address/value segments, hostile-length validation inside decode so the
registry-wide corrupt-frame fuzz (tests/test_wire_codecs.py) can hold
them to the ValueError containment contract.

``encode_epoch_config``/``decode_epoch_config`` double as the WAL
payload codec for ``wal.records.WalEpoch`` -- one layout for the wire
and the log, so a recovered epoch is bit-identical to a broadcast one.
"""

from __future__ import annotations

import struct

from frankenpaxos_tpu.reconfig.messages import (
    EpochAck,
    EpochCommit,
    EpochPhase2aRun,
    Reconfigure,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I64I64 = struct.Struct("<qq")
_I32 = struct.Struct("<i")
_QQQ = struct.Struct("<qqq")

#: Per-frame member-count sanity bound: a hostile count field must not
#: size an allocation (no real acceptor set comes close).
_MAX_MEMBERS = 4096


def _mp_wire():
    """The multipaxos wire helpers (address + SoA value-array
    layouts), bound at CALL time: importing them at module load would
    close an import cycle (protocols.multipaxos's roles import
    reconfig, whose package init loads this module). Registration
    below needs no helper; the first encode/decode resolves this to an
    already-initialized module either way."""
    from frankenpaxos_tpu.protocols.multipaxos import wire

    return wire


def _put_members(out: bytearray, members) -> None:
    w = _mp_wire()
    out += _I32.pack(len(members))
    for address in members:
        w._put_address(out, address)


def _take_members(buf: bytes, at: int) -> tuple:
    w = _mp_wire()
    (n,) = _I32.unpack_from(buf, at)
    at += 4
    if not 0 <= n <= _MAX_MEMBERS:
        raise ValueError(f"malformed member list: count {n}")
    members = []
    for _ in range(n):
        address, at = w._take_address(buf, at)
        members.append(address)
    return tuple(members), at


_QQIQ = struct.Struct("<qqiq")  # epoch, start_slot, f, round


def encode_epoch_config(epoch: int, start_slot: int, f: int,
                        round: int, members) -> bytes:
    """The (epoch, start_slot, f, round, members) body shared by the
    EpochCommit codec and the WalEpoch record payload."""
    out = bytearray()
    out += _QQIQ.pack(epoch, start_slot, f, round)
    _put_members(out, members)
    return bytes(out)


def decode_epoch_config(data: bytes) -> tuple:
    """-> (epoch, start_slot, f, round, members)."""
    try:
        epoch, start_slot, f, round = _QQIQ.unpack_from(data, 0)
        members, _ = _take_members(data, _QQIQ.size)
    except (struct.error, IndexError, UnicodeDecodeError) as e:
        raise ValueError(f"corrupt epoch config: {e!r}") from e
    return epoch, start_slot, f, round, members


class ReconfigureCodec(MessageCodec):
    message_type = Reconfigure
    tag = 128

    def encode(self, out, message):
        _put_members(out, message.members)

    def decode(self, buf, at):
        members, at = _take_members(buf, at)
        return Reconfigure(members=members), at


class EpochCommitCodec(MessageCodec):
    message_type = EpochCommit
    tag = 129

    def encode(self, out, message):
        out += _QQIQ.pack(message.epoch, message.start_slot, message.f,
                          message.round)
        _put_members(out, message.members)

    def decode(self, buf, at):
        epoch, start_slot, f, round = _QQIQ.unpack_from(buf, at)
        members, at = _take_members(buf, at + _QQIQ.size)
        return EpochCommit(epoch=epoch, start_slot=start_slot, f=f,
                           round=round, members=members), at


class EpochAckCodec(MessageCodec):
    message_type = EpochAck
    tag = 130

    def encode(self, out, message):
        out += _I64I64.pack(message.epoch, message.round)

    def decode(self, buf, at):
        epoch, round = _I64I64.unpack_from(buf, at)
        return EpochAck(epoch=epoch, round=round), at + 16


class EpochPhase2aRunCodec(MessageCodec):
    """The run-pipeline proposal with an epoch tag: the SoA value
    array rides the multipaxos lazy layout, so forwarding one of these
    (proxy leader -> acceptors, re-wrapped as a plain Phase2aRun) is a
    raw bytes copy of the segment."""

    message_type = EpochPhase2aRun
    tag = 131

    def encode(self, out, message):
        out += _QQQ.pack(message.epoch, message.start_slot,
                         message.round)
        _mp_wire()._put_value_array(out, message.values)

    def decode(self, buf, at):
        epoch, start, round = _QQQ.unpack_from(buf, at)
        values, at = _mp_wire()._take_value_array(buf, at + 24)
        return EpochPhase2aRun(epoch=epoch, start_slot=start,
                               round=round, values=values), at


for _codec in (ReconfigureCodec(), EpochCommitCodec(), EpochAckCodec(),
               EpochPhase2aRunCodec()):
    register_codec(_codec)
