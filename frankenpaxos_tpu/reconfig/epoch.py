"""The matchmaker-backed epoch store: epoch id -> acceptor set + spec.

An *epoch* is one membership era of an acceptor set. Epochs partition
slot space at ACTIVATION WATERMARKS: epoch ``e`` governs every slot in
``[start_slot_e, start_slot_{e+1})`` -- which acceptors are proposed
to, whose votes count, and under which QuorumSpec the quorum predicate
runs. That watermark bound is the whole handover story: in-flight runs
below the boundary drain in the old epoch while new slots open in the
new one, and one TPU drain spanning the boundary stays a single fused
kernel call (``ops.quorum.EpochSegmentedChecker``).

Matchmaker pedigree (vldb20, Reconfigurer.scala:98-155): the paper
keeps round -> configuration in a dedicated 2f+1 matchmaker service.
Here the *old epoch's acceptors* ARE the matchmakers: an epoch commit
is durable once a write quorum of them has WAL'd the ``WalEpoch``
record, and any future leader's Phase1 read quorum of the old epoch
intersects that write quorum -- so at least one Phase1b carries the
new epoch and the leader extends Phase1 to cover it (the
Flexible-Paxos intersection condition, arxiv 1608.06696, reduced to
set intersection over the epoch map).

Universe ids are store-local but DETERMINISTIC: members get integer
ids in (epoch, member-order) first-seen order, so every role that saw
the same EpochCommit sequence derives identical column layouts for the
TPU kernels.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Sequence

import numpy as np

from frankenpaxos_tpu.quorums import SimpleMajority
from frankenpaxos_tpu.quorums.spec import QuorumSpec


@dataclasses.dataclass(frozen=True)
class EpochConfig:
    """One membership era: ``members`` is the full acceptor set (a
    single 2f+1 majority group), ``start_slot`` its activation
    watermark (first slot it governs)."""

    epoch: int
    start_slot: int
    f: int
    members: tuple  # tuple[Address, ...]

    def __post_init__(self):
        object.__setattr__(self, "members", tuple(self.members))
        if len(self.members) != 2 * self.f + 1:
            raise ValueError(
                f"epoch {self.epoch}: {len(self.members)} members != "
                f"2f+1 = {2 * self.f + 1}")
        if len(set(self.members)) != len(self.members):
            raise ValueError(f"epoch {self.epoch}: duplicate members")

    @property
    def quorum_size(self) -> int:
        return self.f + 1

    def has_write_quorum(self, present: Iterable) -> bool:
        """f+1 of this epoch's members (majority: read and write
        quorums coincide, quorums/SimpleMajority.scala:19-56)."""
        members = set(self.members)
        return len(members.intersection(present)) >= self.f + 1

    has_read_quorum = has_write_quorum


class EpochStore:
    """epoch id -> EpochConfig, with slot -> epoch resolution.

    THE single authority for acceptor-set reads in reconfig-aware
    protocol handlers (paxlint PAX110 forbids bypassing it): fan-out
    targets, vote-counting specs, and Phase1 coverage all resolve
    through ``epoch_of_slot`` / ``config`` so a committed epoch change
    reaches every path at once.
    """

    def __init__(self, initial: EpochConfig):
        if initial.epoch != 0 or initial.start_slot != 0:
            raise ValueError("the initial epoch must be (epoch=0, "
                             f"start_slot=0), got {initial}")
        self._configs: list[EpochConfig] = [initial]
        # Commit round per epoch (round-monotone supersession of an
        # unactivated newest epoch by a higher-round leader).
        self._rounds: list[int] = [-1]
        # Stable universe ids, (epoch, member-order) first-seen.
        self._ids: dict = {a: i for i, a in enumerate(initial.members)}
        #: Bumped on every add/replace; trackers compare it to decide
        #: between appending planes and a full rebuild.
        self.version = 0

    @classmethod
    def from_members(cls, members: Sequence, f: int) -> "EpochStore":
        return cls(EpochConfig(epoch=0, start_slot=0, f=f,
                               members=tuple(members)))

    # --- reads ------------------------------------------------------------
    def current(self) -> EpochConfig:
        return self._configs[-1]

    @property
    def multi_epoch(self) -> bool:
        return len(self._configs) > 1

    def config(self, epoch: int) -> "EpochConfig | None":
        i = epoch - self._configs[0].epoch
        if 0 <= i < len(self._configs):
            return self._configs[i]
        return None

    def epoch_of_slot(self, slot: int) -> EpochConfig:
        """The config governing ``slot`` (last epoch whose activation
        watermark is <= slot)."""
        for config in reversed(self._configs):
            if config.start_slot <= slot:
                return config
        return self._configs[0]

    def epochs_covering(self, min_slot: int) -> list:
        """Every epoch with governed slots >= ``min_slot`` -- the set a
        Phase1 recovering ``[min_slot, inf)`` must hold a read quorum
        in (Phase1-with-both-configs across a handover)."""
        out = []
        for i, config in enumerate(self._configs):
            end = (self._configs[i + 1].start_slot
                   if i + 1 < len(self._configs) else None)
            if end is None or end > min_slot:
                out.append(config)
        return out

    def known(self) -> tuple:
        return tuple(self._configs)

    def round_of(self, epoch: int) -> int:
        i = epoch - self._configs[0].epoch
        return self._rounds[i] if 0 <= i < len(self._rounds) else -1

    def all_members(self) -> tuple:
        """Union of every known epoch's members, universe-id order."""
        return tuple(self._ids)

    def column_of(self, address) -> "int | None":
        """The address's stable universe id (None: never a member)."""
        return self._ids.get(address)

    # --- writes -----------------------------------------------------------
    def offer(self, config: EpochConfig, round: int) -> str:
        """Install a committed epoch entry with round-monotone
        supersession. Returns:

          * ``"new"`` -- appended (the next contiguous epoch);
          * ``"replaced"`` -- the NEWEST epoch's definition was
            superseded by a higher-round commit (a preempted leader's
            unactivated definition losing to its successor's);
          * ``"dup"`` -- already known at >= this round (re-ack it);
          * ``"stale"`` -- a lower-round commit for a known epoch, or
            an epoch too far ahead to validate (non-contiguous: the
            resend protocol will deliver the gap first).
        """
        known = self.config(config.epoch)
        if known is not None:
            i = config.epoch - self._configs[0].epoch
            if round <= self._rounds[i]:
                return "dup" if known == config else "stale"
            if known == config:
                self._rounds[i] = round
                return "dup"
            if i != len(self._configs) - 1:
                # Only the newest epoch can still be in flux: older
                # ones were activated (their successor's commit quorum
                # proves it), and an activated definition is never
                # superseded (docs/RECONFIG.md).
                return "stale"
            self._configs[i] = config
            self._rounds[i] = round
            self._rebuild_ids()
            self.version += 1
            return "replaced"
        newest = self._configs[-1]
        if config.epoch != newest.epoch + 1:
            return "stale"
        if config.start_slot < newest.start_slot:
            raise ValueError(
                f"epoch {config.epoch} start {config.start_slot} below "
                f"epoch {newest.epoch} start {newest.start_slot}")
        self._configs.append(config)
        self._rounds.append(round)
        for a in config.members:
            self._ids.setdefault(a, len(self._ids))
        self.version += 1
        return "new"

    def add(self, config: EpochConfig, round: int = 0) -> bool:
        """offer() narrowed to the append case (tests, WAL replay in
        epoch order): True when newly installed."""
        return self.offer(config, round) in ("new", "replaced")

    def _rebuild_ids(self) -> None:
        ids: dict = {}
        for config in self._configs:
            for a in config.members:
                ids.setdefault(a, len(ids))
        self._ids = ids

    # --- kernel-facing views ----------------------------------------------
    def universe(self) -> tuple:
        """Integer universe (0..n_members_ever-1) for the TPU kernels."""
        return tuple(range(len(self._ids)))

    def spec(self, config: EpochConfig) -> QuorumSpec:
        """``config``'s write/read QuorumSpec over the store's union
        universe (majority of the epoch's member columns)."""
        return SimpleMajority(
            [self._ids[a] for a in config.members]
        ).write_spec().reindexed(self.universe())

    def specs_and_boundaries(self) -> tuple:
        """``([QuorumSpec, ...], [start_slot, ...])`` for
        ``ops.quorum.EpochSegmentedChecker``."""
        return ([self.spec(c) for c in self._configs],
                [c.start_slot for c in self._configs])

    def boundaries(self) -> np.ndarray:
        return np.asarray([c.start_slot for c in self._configs[1:]],
                          dtype=np.int64)
