"""Epoch-segmented write-quorum tracking for reconfig-wired proxies.

The reconfig twin of ``protocols.multipaxos.quorum_tracker``: votes are
recorded by VOTER ADDRESS (the transport's ``src`` -- carried indices
can collide across epochs when a replacement reuses a dead member's
config slot, addresses cannot), and each slot's quorum predicate is its
EPOCH's spec, resolved through the ``EpochStore``. Two backends:

  * ``dict`` -- the oracle: per-(slot, round) voter sets checked with
    ``EpochConfig.has_write_quorum`` (set intersection, the reference
    semantics). Counts only the slot's epoch's members.
  * ``tpu`` -- one ``ops.quorum.EpochSegmentedChecker`` scatter per
    event-loop drain over the store's union universe; the epoch plane
    is selected per slot INSIDE the fused kernel, so a drain spanning
    the handover boundary stays one dispatch. Non-member votes land in
    columns the epoch's mask zeroes -- they can never complete a
    quorum they do not belong to.

Both report each (slot, round)'s quorum exactly once (the dict's Done
sentinel; the board's chosen bitmap).
"""

from __future__ import annotations

import numpy as np

from frankenpaxos_tpu.reconfig.epoch import EpochStore


class EpochQuorumTracker:
    def __init__(self, store: EpochStore, backend: str = "dict",
                 window: int = 4096):
        if backend not in ("dict", "tpu"):
            raise ValueError(f"unknown epoch tracker backend {backend!r}")
        self.store = store
        self.backend = backend
        self._known = store.known()
        # dict backend: (slot, round) -> set of voter addresses; None
        # once reported (Done).
        self._states: dict = {}
        self._newly: list = []
        # tpu backend: per-drain vote buffer + the segmented checker.
        self._checker = None
        self._slots: list = []
        self._cols: list = []
        self._rounds: list = []
        self._chunk = 256
        if backend == "tpu":
            from frankenpaxos_tpu.ops.quorum import EpochSegmentedChecker

            specs, starts = store.specs_and_boundaries()
            self._checker = EpochSegmentedChecker(specs, starts,
                                                  window=window)
            # Prewarm the scatter buckets before client traffic.
            self._checker.record_and_check([0], [0], [-1])
            self._checker.release([0])

    def note_epochs(self) -> None:
        """Refresh after the store committed new epochs. Pure appends
        extend the TPU checker's plane stack in place (the epoch
        reshape gather keeps mid-flight votes); a round-superseded
        newest epoch (rare: a preempted leader's unactivated
        definition) rebuilds the checker -- in-flight quorums for that
        never-activated epoch are resolved by protocol-level resends."""
        known = self.store.known()
        if known == self._known:
            return
        if self._checker is not None:
            if known[:len(self._known)] == self._known:
                for config in known[len(self._known):]:
                    self._checker.add_epoch(self.store.spec(config),
                                            config.start_slot)
            else:
                from frankenpaxos_tpu.ops.quorum import (
                    EpochSegmentedChecker,
                )

                specs, starts = self.store.specs_and_boundaries()
                self._checker = EpochSegmentedChecker(
                    specs, starts, window=self._checker.window)
                # A replacement REBUILDS the universe ids: buffered
                # votes' column ids were computed under the old
                # mapping and would credit the wrong acceptor on the
                # new board (a quorum one real vote short). Drop them
                # -- they voted for the superseded definition's
                # proposals, which protocol-level resends re-drive.
                self._slots, self._cols, self._rounds = [], [], []
        self._known = known

    # --- recording (per message, O(1) Python) ------------------------------
    def record(self, slot: int, round: int, voter) -> None:
        if self.backend == "dict":
            self._record_dict(slot, round, voter)
            return
        col = self.store.column_of(voter)
        if col is None:
            return  # never a member of any epoch: nothing to count
        self._slots.append(slot)
        self._cols.append(col)
        self._rounds.append(round)

    def record_range(self, slot_start: int, slot_end: int, round: int,
                     voter) -> None:
        if self.backend == "dict":
            for slot in range(slot_start, slot_end):
                self._record_dict(slot, round, voter)
            return
        col = self.store.column_of(voter)
        if col is None or slot_end <= slot_start:
            return
        width = slot_end - slot_start
        self._slots.extend(range(slot_start, slot_end))
        self._cols.extend([col] * width)
        self._rounds.extend([round] * width)

    def record_votes(self, slots, rounds, voter) -> None:
        """One voter's votes for an arbitrary slot array (a packed
        Phase2bVotes)."""
        if self.backend == "dict":
            for slot, round in zip(np.asarray(slots).tolist(),
                                   np.asarray(rounds).tolist()):
                self._record_dict(int(slot), int(round), voter)
            return
        col = self.store.column_of(voter)
        if col is None:
            return
        slots = np.asarray(slots)
        self._slots.extend(slots.tolist())
        self._cols.extend([col] * slots.size)
        self._rounds.extend(np.asarray(rounds).tolist())

    def _record_dict(self, slot: int, round: int, voter) -> None:
        key = (slot, round)
        votes = self._states.get(key)
        if votes is None and key in self._states:
            return  # Done
        if votes is None:
            votes = set()
            self._states[key] = votes
        votes.add(voter)
        config = self.store.epoch_of_slot(slot)
        if voter not in config.members:
            return  # not this epoch's vote; kept only for debugging
        if config.has_write_quorum(votes):
            self._states[key] = None
            self._newly.append(key)

    # --- drain -------------------------------------------------------------
    def drain(self) -> list:
        if self.backend == "dict":
            newly, self._newly = self._newly, []
            return newly
        if not self._slots:
            return []
        slots = np.asarray(self._slots, dtype=np.int64)
        cols = np.asarray(self._cols, dtype=np.int32)
        rounds = np.asarray(self._rounds, dtype=np.int32)
        self._slots, self._cols, self._rounds = [], [], []
        out: list = []
        seen: set = set()
        for at in range(0, slots.size, self._chunk):
            sl = slots[at:at + self._chunk]
            newly = self._checker.record_and_check(
                sl, cols[at:at + self._chunk],
                rounds[at:at + self._chunk])
            for i in np.flatnonzero(newly).tolist():
                key = (int(sl[i]), int(rounds[at + i]))
                # The board reports every same-batch duplicate of a
                # newly-chosen slot; exactly-once within the drain is
                # host-side (cross-drain is the chosen bitmap's job).
                if key[0] not in seen:
                    seen.add(key[0])
                    out.append(key)
        return out

    def release(self, slots) -> None:
        """Watermark GC passthrough (ring wrap for the tpu board)."""
        if self._checker is not None and len(slots):
            self._checker.release(np.asarray(slots))
