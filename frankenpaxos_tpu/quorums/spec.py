"""Matrix form of a quorum predicate.

Every quorum system in the reference (quorums/SimpleMajority.scala:19-56,
quorums/Grid.scala:5-57, quorums/UnanimousWrites.scala:17-57) answers
``isReadQuorum(nodes)`` / ``isWriteQuorum(nodes)`` with set operations over
small integer sets. All of them are instances of one algebraic shape:

    counts[g]    = |nodes intersect group[g]|          (a matvec)
    satisfied[g] = counts[g] >= threshold[g]
    result       = ANY(satisfied)  or  ALL(satisfied)

- SimpleMajority read/write: one group (the members), threshold f+1, ANY.
- Grid read  ("some full row present"):   groups = rows, threshold = row
  size, ANY.
- Grid write ("one node from every row"): groups = rows, threshold = 1, ALL.
- UnanimousWrites write: one group, threshold = n, ANY; read: threshold 1.

Batched over a window of slots, ``counts = votes @ masks.T`` is a single
MXU matmul over the whole ``[window x acceptors]`` vote matrix -- this is
the kernel the north star asks for.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

ANY = "any"
ALL = "all"


@dataclasses.dataclass(frozen=True)
class QuorumSpec:
    """A quorum predicate in matrix form over a fixed node universe.

    Attributes:
      masks: ``[G, N]`` uint8 membership matrix; ``masks[g, i] == 1`` iff
        universe node ``i`` belongs to group ``g``.
      thresholds: ``[G]`` int32; group ``g`` is satisfied when at least
        ``thresholds[g]`` of its members responded.
      combine: ``"any"`` or ``"all"`` over satisfied groups.
      universe: the node ids, in column order, that the masks index.
    """

    masks: np.ndarray
    thresholds: np.ndarray
    combine: str
    universe: tuple[int, ...]

    def __post_init__(self):
        masks = np.asarray(self.masks, dtype=np.uint8)
        thresholds = np.asarray(self.thresholds, dtype=np.int32)
        if masks.ndim != 2:
            raise ValueError(f"masks must be [G, N], got shape {masks.shape}")
        if thresholds.shape != (masks.shape[0],):
            raise ValueError(
                f"thresholds shape {thresholds.shape} != ({masks.shape[0]},)")
        if masks.shape[1] != len(self.universe):
            raise ValueError(
                f"masks have {masks.shape[1]} columns but universe has "
                f"{len(self.universe)} nodes")
        if self.combine not in (ANY, ALL):
            raise ValueError(f"combine must be 'any' or 'all': {self.combine}")
        object.__setattr__(self, "masks", masks)
        object.__setattr__(self, "thresholds", thresholds)
        object.__setattr__(self, "universe", tuple(self.universe))

    @property
    def num_groups(self) -> int:
        return self.masks.shape[0]

    @property
    def num_nodes(self) -> int:
        return self.masks.shape[1]

    def as_arrays(self) -> "tuple[np.ndarray, np.ndarray, bool]":
        """``(masks [G, N] int32, thresholds [G] int32, combine_any)``
        -- the factored predicate form every device kernel consumes
        (ops/quorum, bench/pipeline); one conversion point instead of
        hand-rolled triples at each call site."""
        return (np.asarray(self.masks, dtype=np.int32),
                np.asarray(self.thresholds, dtype=np.int32),
                self.combine == ANY)

    def column_of(self, node_id: int) -> int:
        return self.universe.index(node_id)

    def present_vector(self, nodes: Sequence[int]) -> np.ndarray:
        """``[N]`` uint8 indicator of which universe nodes are in ``nodes``."""
        present = np.zeros(self.num_nodes, dtype=np.uint8)
        node_set = set(nodes)
        for i, node_id in enumerate(self.universe):
            if node_id in node_set:
                present[i] = 1
        return present

    def evaluate(self, present: np.ndarray) -> np.ndarray:
        """Host/NumPy evaluation; the oracle the device kernel is tested against.

        Args:
          present: ``[..., N]`` bool/uint8 responder indicator(s).

        Returns:
          ``[...]`` bool: whether each responder set satisfies the predicate.
        """
        present = np.asarray(present)
        counts = present.astype(np.int32) @ self.masks.T.astype(np.int32)
        satisfied = counts >= self.thresholds
        if self.combine == ANY:
            return satisfied.any(axis=-1)
        return satisfied.all(axis=-1)

    def check(self, nodes: Sequence[int]) -> bool:
        return bool(self.evaluate(self.present_vector(nodes)))

    def reindexed(self, universe: Sequence[int]) -> "QuorumSpec":
        """The same predicate over a larger/reordered node universe.

        Nodes of the new universe not mentioned by this spec get all-zero
        mask columns (their votes never count). Every node of the current
        universe must appear in the new one. Used to pad per-group or
        per-configuration quorum systems into one fixed-width matrix
        (Matchmaker reconfiguration; MultiPaxos acceptor groups).
        """
        universe = tuple(universe)
        col = {node_id: i for i, node_id in enumerate(universe)}
        masks = np.zeros((self.num_groups, len(universe)), dtype=np.uint8)
        for g in range(self.num_groups):
            for i, node_id in enumerate(self.universe):
                if self.masks[g, i]:
                    masks[g, col[node_id]] = 1
        return QuorumSpec(masks=masks, thresholds=self.thresholds,
                          combine=self.combine, universe=universe)


def pad_specs(specs: Sequence[QuorumSpec]) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad several same-universe specs to a common group count.

    Returns ``(masks [K, Gmax, N], thresholds [K, Gmax], combine_any [K])``
    where padding groups are always-satisfied under ALL (threshold 0) and
    never-satisfied under ANY (threshold N+1). This is the ragged-quorum
    plan of SURVEY.md section 7: reshaped configurations become one padded
    tensor plus validity handled through thresholds.
    """
    if not specs:
        raise ValueError("need at least one spec")
    n = specs[0].num_nodes
    for s in specs:
        if s.universe != specs[0].universe:
            raise ValueError("all specs must share a universe; reindex first")
    gmax = max(s.num_groups for s in specs)
    masks = np.zeros((len(specs), gmax, n), dtype=np.uint8)
    thresholds = np.zeros((len(specs), gmax), dtype=np.int32)
    combine_any = np.zeros(len(specs), dtype=bool)
    for k, s in enumerate(specs):
        g = s.num_groups
        masks[k, :g] = s.masks
        thresholds[k, :g] = s.thresholds
        combine_any[k] = s.combine == ANY
        if s.combine == ANY:
            thresholds[k, g:] = n + 1  # unsatisfiable padding
        else:
            thresholds[k, g:] = 0  # always-satisfied padding
    return masks, thresholds, combine_any
