"""Read/write quorum systems (Flexible-Paxos style).

Reference behavior: shared/src/main/scala/frankenpaxos/quorums/
(QuorumSystem.scala:16-24, SimpleMajority.scala:19-56, Grid.scala:5-57,
UnanimousWrites.scala:17-57).

The TPU-first design factors every quorum system into a :class:`QuorumSpec`
-- a ``[groups x nodes]`` membership-mask matrix plus per-group thresholds
and an any/all combiner -- so that "is this set of responders a quorum?"
becomes one matmul + compare + reduction, batched over a whole window of
slots on the MXU (see ops/quorum.py).
"""

from frankenpaxos_tpu.quorums.spec import QuorumSpec
from frankenpaxos_tpu.quorums.systems import (
    Grid,
    quorum_system_from_dict,
    quorum_system_to_dict,
    QuorumSystem,
    SimpleMajority,
    UnanimousWrites,
    ZoneGrid,
)

__all__ = [
    "QuorumSpec",
    "QuorumSystem",
    "SimpleMajority",
    "Grid",
    "ZoneGrid",
    "UnanimousWrites",
    "quorum_system_from_dict",
    "quorum_system_to_dict",
]
