"""Read-write quorum systems.

A quorum system is a node universe plus sets of read quorums R and write
quorums W such that every r in R intersects every w in W (Flexible Paxos).
Reference behavior: quorums/QuorumSystem.scala:16-24 (trait: nodes,
randomReadQuorum, randomWriteQuorum, isReadQuorum, isWriteQuorum,
isSuperSetOfReadQuorum, isSuperSetOfWriteQuorum) and the three
implementations SimpleMajority.scala:19-56, Grid.scala:5-57,
UnanimousWrites.scala:17-57; wire ser/de QuorumSystem.scala:26-61.

Each system also exposes ``read_spec()`` / ``write_spec()`` -- its
:class:`~frankenpaxos_tpu.quorums.spec.QuorumSpec` matrix form -- which is
what the device kernels consume.
"""

from __future__ import annotations

import abc
import random
from typing import Iterable, Sequence

import numpy as np

from frankenpaxos_tpu.quorums.spec import ALL, ANY, QuorumSpec


class QuorumSystem(abc.ABC):
    """Abstract read-write quorum system over integer node ids."""

    @abc.abstractmethod
    def nodes(self) -> frozenset[int]:
        ...

    @abc.abstractmethod
    def random_read_quorum(self, rng: random.Random) -> set[int]:
        ...

    @abc.abstractmethod
    def random_write_quorum(self, rng: random.Random) -> set[int]:
        ...

    def is_read_quorum(self, xs: Iterable[int]) -> bool:
        xs = set(xs)
        if not xs <= self.nodes():
            raise ValueError(f"{xs} is not a subset of {set(self.nodes())}")
        return self.is_superset_of_read_quorum(xs)

    def is_write_quorum(self, xs: Iterable[int]) -> bool:
        xs = set(xs)
        if not xs <= self.nodes():
            raise ValueError(f"{xs} is not a subset of {set(self.nodes())}")
        return self.is_superset_of_write_quorum(xs)

    def is_superset_of_read_quorum(self, xs: Iterable[int]) -> bool:
        return bool(self.read_spec().check(set(xs)))

    def is_superset_of_write_quorum(self, xs: Iterable[int]) -> bool:
        return bool(self.write_spec().check(set(xs)))

    @abc.abstractmethod
    def read_spec(self) -> QuorumSpec:
        """Matrix form of the read-quorum predicate."""

    @abc.abstractmethod
    def write_spec(self) -> QuorumSpec:
        """Matrix form of the write-quorum predicate."""


class SimpleMajority(QuorumSystem):
    """Every majority is both a read and a write quorum.

    Reference: quorums/SimpleMajority.scala:19-56.
    """

    def __init__(self, members: Iterable[int]):
        self.members = frozenset(members)
        if not self.members:
            raise ValueError("SimpleMajority needs at least one member")
        self.quorum_size = len(self.members) // 2 + 1
        self._universe = tuple(sorted(self.members))

    def __repr__(self):
        return f"SimpleMajority(members={sorted(self.members)})"

    def nodes(self) -> frozenset[int]:
        return self.members

    def random_read_quorum(self, rng: random.Random) -> set[int]:
        return set(rng.sample(self._universe, self.quorum_size))

    def random_write_quorum(self, rng: random.Random) -> set[int]:
        return self.random_read_quorum(rng)

    def read_spec(self) -> QuorumSpec:
        return QuorumSpec(
            masks=np.ones((1, len(self._universe)), dtype=np.uint8),
            thresholds=np.array([self.quorum_size], dtype=np.int32),
            combine=ANY,
            universe=self._universe,
        )

    def write_spec(self) -> QuorumSpec:
        return self.read_spec()


class Grid(QuorumSystem):
    """Nodes arranged in a grid: every row is a read quorum; one node from
    every row is a write quorum.

    Reference: quorums/Grid.scala:5-57. Matrix form (SURVEY.md section 2.3):
    read = ANY row fully present; write = ALL rows touched.
    """

    def __init__(self, grid: Sequence[Sequence[int]]):
        if not grid:
            raise ValueError("Grid needs at least one row")
        if any(len(row) != len(grid[0]) for row in grid):
            raise ValueError("Grid rows must be equal-sized")
        self.grid = tuple(tuple(row) for row in grid)
        self._rows = [frozenset(row) for row in self.grid]
        self._nodes = frozenset().union(*self._rows)
        self._universe = tuple(sorted(self._nodes))

    def __repr__(self):
        return f"Grid(grid={self.grid})"

    def nodes(self) -> frozenset[int]:
        return self._nodes

    def random_read_quorum(self, rng: random.Random) -> set[int]:
        return set(self.grid[rng.randrange(len(self.grid))])

    def random_write_quorum(self, rng: random.Random) -> set[int]:
        i = rng.randrange(len(self.grid[0]))
        return {row[i] for row in self.grid}

    def is_superset_of_read_quorum(self, xs: Iterable[int]) -> bool:
        xs = set(xs)
        return any(row <= xs for row in self._rows)

    def is_superset_of_write_quorum(self, xs: Iterable[int]) -> bool:
        xs = set(xs)
        return all(row & xs for row in self._rows)

    def _row_masks(self) -> np.ndarray:
        masks = np.zeros((len(self._rows), len(self._universe)), dtype=np.uint8)
        col = {node: i for i, node in enumerate(self._universe)}
        for g, row in enumerate(self._rows):
            for node in row:
                masks[g, col[node]] = 1
        return masks

    def read_spec(self) -> QuorumSpec:
        masks = self._row_masks()
        return QuorumSpec(
            masks=masks,
            thresholds=masks.sum(axis=1).astype(np.int32),
            combine=ANY,
            universe=self._universe,
        )

    def write_spec(self) -> QuorumSpec:
        masks = self._row_masks()
        return QuorumSpec(
            masks=masks,
            thresholds=np.ones(len(self._rows), dtype=np.int32),
            combine=ALL,
            universe=self._universe,
        )


class ZoneGrid(QuorumSystem):
    """WPaxos-flavored asymmetric grid: rows are availability ZONES.

    The transpose of :class:`Grid`'s asymmetry, tuned for wide-area
    deployments (paxgeo, docs/GEO.md): a WRITE (Phase2) quorum is a
    majority of ANY single row -- in steady state the leader uses its
    home zone's row, so commits never cross a zone boundary -- while a
    READ (Phase1) quorum takes a majority of EVERY row, the cross-zone
    column sweep an object steal pays exactly once. Intersection
    (Flexible Paxos, arxiv 1608.06696): a read quorum contains a
    majority of whichever row a write quorum majority came from, and
    two majorities of one row always intersect. This is the f_z = 0
    WPaxos deployment (arxiv 1703.08905): zone-local commits, with a
    full-zone outage stalling steals of that zone's objects until f+1
    of its members recover from their WALs.
    """

    def __init__(self, grid: Sequence[Sequence[int]]):
        if not grid:
            raise ValueError("ZoneGrid needs at least one row")
        if any(len(row) != len(grid[0]) for row in grid):
            raise ValueError("ZoneGrid rows must be equal-sized")
        self.grid = tuple(tuple(row) for row in grid)
        self._rows = [frozenset(row) for row in self.grid]
        self._nodes = frozenset().union(*self._rows)
        if len(self._nodes) != sum(len(r) for r in self._rows):
            raise ValueError("ZoneGrid rows must be disjoint")
        self._universe = tuple(sorted(self._nodes))
        self.row_majority = len(self.grid[0]) // 2 + 1

    def __repr__(self):
        return f"ZoneGrid(grid={self.grid})"

    def nodes(self) -> frozenset[int]:
        return self._nodes

    def random_read_quorum(self, rng: random.Random) -> set[int]:
        out: set[int] = set()
        for row in self.grid:
            out.update(rng.sample(row, self.row_majority))
        return out

    def random_write_quorum(self, rng: random.Random) -> set[int]:
        row = self.grid[rng.randrange(len(self.grid))]
        return set(rng.sample(row, self.row_majority))

    def is_superset_of_read_quorum(self, xs: Iterable[int]) -> bool:
        xs = set(xs)
        return all(len(row & xs) >= self.row_majority
                   for row in self._rows)

    def is_superset_of_write_quorum(self, xs: Iterable[int]) -> bool:
        xs = set(xs)
        return any(len(row & xs) >= self.row_majority
                   for row in self._rows)

    def _row_masks(self) -> np.ndarray:
        masks = np.zeros((len(self._rows), len(self._universe)),
                         dtype=np.uint8)
        col = {node: i for i, node in enumerate(self._universe)}
        for g, row in enumerate(self._rows):
            for node in row:
                masks[g, col[node]] = 1
        return masks

    def read_spec(self) -> QuorumSpec:
        return QuorumSpec(
            masks=self._row_masks(),
            thresholds=np.full(len(self._rows), self.row_majority,
                               dtype=np.int32),
            combine=ALL,
            universe=self._universe,
        )

    def write_spec(self) -> QuorumSpec:
        return QuorumSpec(
            masks=self._row_masks(),
            thresholds=np.full(len(self._rows), self.row_majority,
                               dtype=np.int32),
            combine=ANY,
            universe=self._universe,
        )

    def home_write_spec(self, row_index: int) -> QuorumSpec:
        """The write predicate ANCHORED at one row: a majority of row
        ``row_index`` over the FULL grid universe (other rows' columns
        are zero-masked, so their votes never count). This is the
        per-epoch Phase2 spec paxgeo feeds the fused checkers -- each
        object-steal epoch selects its home zone's plane."""
        if not 0 <= row_index < len(self.grid):
            raise ValueError(f"row {row_index} outside 0.."
                             f"{len(self.grid) - 1}")
        masks = np.zeros((1, len(self._universe)), dtype=np.uint8)
        col = {node: i for i, node in enumerate(self._universe)}
        for node in self.grid[row_index]:
            masks[0, col[node]] = 1
        return QuorumSpec(
            masks=masks,
            thresholds=np.array([self.row_majority], dtype=np.int32),
            combine=ANY,
            universe=self._universe,
        )


class UnanimousWrites(QuorumSystem):
    """One write quorum (all members); every non-empty subset reads.

    Reference: quorums/UnanimousWrites.scala:17-57. Used by fast-path
    protocols (UnanimousBPaxos).
    """

    def __init__(self, members: Iterable[int]):
        self.members = frozenset(members)
        if not self.members:
            raise ValueError("UnanimousWrites needs at least one member")
        self._universe = tuple(sorted(self.members))

    def __repr__(self):
        return f"UnanimousWrites(members={sorted(self.members)})"

    def nodes(self) -> frozenset[int]:
        return self.members

    def random_read_quorum(self, rng: random.Random) -> set[int]:
        return {rng.choice(self._universe)}

    def random_write_quorum(self, rng: random.Random) -> set[int]:
        return set(self.members)

    def read_spec(self) -> QuorumSpec:
        return QuorumSpec(
            masks=np.ones((1, len(self._universe)), dtype=np.uint8),
            thresholds=np.array([1], dtype=np.int32),
            combine=ANY,
            universe=self._universe,
        )

    def write_spec(self) -> QuorumSpec:
        return QuorumSpec(
            masks=np.ones((1, len(self._universe)), dtype=np.uint8),
            thresholds=np.array([len(self._universe)], dtype=np.int32),
            combine=ANY,
            universe=self._universe,
        )


def quorum_system_to_dict(qs: QuorumSystem) -> dict:
    """Wire form (the analog of QuorumSystemProto, QuorumSystem.scala:26-44)."""
    if isinstance(qs, SimpleMajority):
        return {"kind": "simple_majority", "members": sorted(qs.members)}
    if isinstance(qs, UnanimousWrites):
        return {"kind": "unanimous_writes", "members": sorted(qs.members)}
    if isinstance(qs, ZoneGrid):
        return {"kind": "zone_grid", "grid": [list(row) for row in qs.grid]}
    if isinstance(qs, Grid):
        return {"kind": "grid", "grid": [list(row) for row in qs.grid]}
    raise TypeError(f"unserializable quorum system {qs!r}")


def quorum_system_from_dict(d: dict) -> QuorumSystem:
    """Inverse of :func:`quorum_system_to_dict` (QuorumSystem.scala:45-61)."""
    kind = d["kind"]
    if kind == "simple_majority":
        return SimpleMajority(d["members"])
    if kind == "unanimous_writes":
        return UnanimousWrites(d["members"])
    if kind == "grid":
        return Grid(d["grid"])
    if kind == "zone_grid":
        return ZoneGrid(d["grid"])
    raise ValueError(f"unknown quorum system kind {kind!r}")
