"""Deterministic WAL storage-fault injection (paxworld).

"The Performance of Paxos in the Cloud" (PAPERS.md) attributes the
worst deployed tail latencies to STORAGE, not the network: a single
fsync stalling for tens of milliseconds holds the whole group commit,
and every ack behind it, amplifying p999 by orders of magnitude. The
scenario matrix (scenarios/, bench/global_lt.py) reproduces that
pathology on virtual time with this module.

:class:`FsyncStallStorage` wraps any WAL storage (MemStorage in sims,
FileStorage on disk) and injects a stall after every ``stall_every``-th
``sync``. Stall durations are drawn from a STRING-SEEDED
``random.Random`` keyed ``(seed, label, sync index)`` -- sha512
seeding, PYTHONHASHSEED-proof -- so a scenario's fault schedule is
byte-reproducible per seed (the same determinism contract the geo
layer enforces via paxlint GEO801). The wrapper reports each stall to
``on_stall``; the scenario harness bridges that to
``GeoSimTransport.stall_sender`` so the stalled role's drain releases
its held acks late in VIRTUAL time (wal/role.py holds acks until the
fsync returns -- the stall therefore lands exactly where a real one
would: between the fsync and the send-release stage).

OFF BY DEFAULT, ZERO HOT-PATH COST: fault injection is a wrapping
storage object that only exists when a scenario arms it. The unwrapped
Wal/FileStorage/MemStorage path is not touched by this module at all
-- no flag test, no attribute, no import.
"""

from __future__ import annotations

import random
from typing import Callable, Optional


class FsyncStallStorage:
    """A WAL storage decorator injecting deterministic fsync stalls.

    Two fault shapes (paxchaos):

    * COUNT cadence -- ``stall_every=k`` stalls every k-th sync;
      ``stall_s`` is the mean stall with one-sided uniform jitter of
      +-``jitter`` fraction, drawn from the string-seeded RNG.
    * PERIODIC WINDOWS -- ``stall_period_s``/``stall_window_s``: the
      device is slow for the first ``window`` seconds of every
      ``period`` (the background-flush shape from "Paxos in the
      Cloud"); a sync landing inside a window stalls to the window's
      end. Windows are anchored at ``clock()`` ZERO, so two wrapped
      storages sharing a clock (the sim's virtual clock; the host
      wall clock across deployed role processes) have ALIGNED
      windows -- which is what makes overlap faults reproducible in
      the deployed world, where count cadences drift apart the
      moment one stall compresses the stalled role's backlog into a
      single drain.

    Neither armed (the default): the wrapper only counts syncs."""

    def __init__(self, inner, *, seed: int = 0, label: str = "",
                 stall_every: int = 0, stall_s: float = 0.05,
                 jitter: float = 0.5,
                 stall_period_s: float = 0.0,
                 stall_window_s: float = 0.0,
                 clock: Optional[Callable[[], float]] = None,
                 on_stall: Optional[Callable[[float], None]] = None,
                 blocking: bool = False):
        self.inner = inner
        self.seed = seed
        self.label = label
        self.stall_every = stall_every
        self.stall_s = stall_s
        self.jitter = jitter
        self.stall_period_s = stall_period_s
        self.stall_window_s = stall_window_s
        if clock is None and stall_period_s:
            import time

            clock = time.time  # shared across a host's processes
        self.clock = clock
        self.on_stall = on_stall
        #: paxchaos deployed mode: actually SLEEP through the stall
        #: inside sync() -- the role's single event-loop thread blocks
        #: exactly like it would inside a real slow fsync, holding the
        #: group commit and every ack behind it wall-clock. Sim arms
        #: ``on_stall`` + the transport bridge instead (virtual time).
        self.blocking = blocking
        self.syncs = 0
        #: Every injected stall duration, in order (the scenario
        #: records the schedule next to the SLO row).
        self.stalls: list[float] = []
        self._rng = random.Random(0)

    def _emit(self, stall: float) -> None:
        self.stalls.append(stall)
        if self.on_stall is not None:
            self.on_stall(stall)
        if self.blocking:
            import time

            time.sleep(stall)

    # --- the fault site ----------------------------------------------------
    def sync(self, name: str) -> None:
        self.inner.sync(name)
        self.syncs += 1
        if self.stall_period_s:
            phase = self.clock() % self.stall_period_s
            if phase < self.stall_window_s:
                self._emit(self.stall_window_s - phase)
            return
        if not self.stall_every or self.syncs % self.stall_every:
            return
        rng = self._rng
        rng.seed(f"fsync-stall|{self.seed}|{self.label}|{self.syncs}")
        lo = 1.0 - self.jitter
        self._emit(self.stall_s * (lo + 2 * self.jitter * rng.random()))

    # --- transparent delegation --------------------------------------------
    def segments(self) -> list:
        return self.inner.segments()

    def read(self, name: str) -> bytes:
        return self.inner.read(name)

    def append(self, name: str, data: bytes) -> None:
        self.inner.append(name, data)

    def delete(self, name: str) -> None:
        self.inner.delete(name)

    def truncate(self, name: str, size: int) -> None:
        self.inner.truncate(name, size)

    def size(self, name: str) -> int:
        return self.inner.size(name)

    def close(self) -> None:
        self.inner.close()
