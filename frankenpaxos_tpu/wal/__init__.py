"""paxlog: drain-granular durability for protocol roles.

An append-only, CRC-framed, segment-rotating write-ahead log with GROUP
COMMIT at the actor runtime's ``on_drain`` boundary: every record
appended while a drain's messages are being handled is made durable by
ONE ``fsync`` when the drain ends, so the per-message durability cost
amortizes across the drain exactly like the run pipeline's device
dispatches ("Paxos in the Cloud" finds durable logging dominates Paxos
latency unless writes are batched -- PAPERS.md).

The reference keeps no persistence layer at all (VERDICT.md section 5);
this package is the production-scale answer: acceptors recover
promises/votes/run records and replicas recover an SM snapshot + the
executed watermark after ``kill -9``, then rejoin the cluster.

  * ``wal.records`` -- the typed record set + fixed-layout codecs
    (wire tags 84-89, registered with the runtime codec registry so
    the corrupt-frame containment fuzz covers them).
  * ``wal.log`` -- ``Wal`` (framing, group commit, segment rotation,
    snapshot/compaction, torn-tail recovery) over ``FileStorage``
    (real files + fsync) or ``MemStorage`` (the sim's crash-surviving
    stand-in: synced bytes survive ``crash_restart``, the unsynced
    group-commit buffer dies with the actor).
  * ``wal.faults`` -- deterministic fsync-stall fault injection for
    the paxworld scenario matrix (a wrapping storage: off by default,
    zero cost on the unwrapped hot path).
"""

from frankenpaxos_tpu.wal.faults import FsyncStallStorage  # noqa: F401
from frankenpaxos_tpu.wal.log import FileStorage, MemStorage, Wal, WalMetrics  # noqa: F401
from frankenpaxos_tpu.wal.records import (  # noqa: F401
    WalChosenRun,
    WalEpoch,
    WalGeoEpoch,
    WalGeoPromise,
    WalGeoVote,
    WalNoopRange,
    WalPromise,
    WalSnapshot,
    WalVote,
    WalVoteRun,
)
from frankenpaxos_tpu.wal.role import DurableRole  # noqa: F401
