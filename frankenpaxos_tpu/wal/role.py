"""DurableRole: the ONE implementation of the group-commit ordering.

Every durable actor (MultiPaxos/Mencius acceptors and replicas) shares
the same release discipline -- records staged during a drain are
fsynced ONCE, and only then do the acks that depend on them leave the
actor. That ordering is the WAL's entire safety argument (a crash can
never lose acked state), so it lives here exactly once instead of
drifting across four role classes; only ``_wal_compact`` (what live
state a compaction re-logs) and recovery genuinely differ per role.
"""

from __future__ import annotations


class DurableRole:
    """Mixin over Actor: wal staging, deferred sends, and the drain's
    sync -> compact -> release sequence."""

    def _wal_init(self, wal) -> None:
        self.wal = wal
        self._wal_sends: list = []

    def _wal_send(self, dst, message) -> None:
        """Send, or -- when durable -- hold until the drain's group
        commit (the group-commit rule, wal/log.py): an ack that
        depends on a staged record must never precede its fsync."""
        if self.wal is None:
            self.send(dst, message)
        else:
            self._wal_sends.append((dst, message))

    def _wal_drain(self) -> None:
        """The on_drain tail for durable roles: ONE fsync covers every
        record this drain appended, compaction runs on the same
        boundary, and only then do the held acks go out. The two
        paxtrace drain stages here -- wal-fsync and send-release --
        are exactly the latency a command spends waiting on the group
        commit (the dominant cloud-Paxos cost PAPERS.md's experience
        report attributes poorly without tracing)."""
        if self.wal is None:
            return
        with self.trace_stage("wal-fsync"):
            self.wal.sync()
        if self.wal.wants_compaction():
            self._wal_compact()
        if self._wal_sends:
            sends, self._wal_sends = self._wal_sends, []
            with self.trace_stage("send-release"):
                for dst, message in sends:
                    self.send(dst, message)

    def _wal_compact(self) -> None:  # pragma: no cover - roles override
        raise NotImplementedError
