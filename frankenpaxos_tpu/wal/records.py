"""WAL record types and their fixed-layout codecs (record tags 1-7).

Records are protocol-NEUTRAL: value payloads are opaque byte segments
already encoded by the owning role's wire helpers
(``multipaxos.wire.encode_value`` / ``encode_value_array``, which
Mencius shares), so one record set serves every protocol family and a
run record's payload is a raw copy of the LazyValueArray segment that
arrived on the wire -- logging a drain's Phase2aRun never re-encodes
its values.

Records live in their OWN tag space (``WAL_SERIALIZER``), not the wire
registry: they never cross the network, the wire's 1..127 space is
fully allocated, and a closed record set lets recovery refuse unknown
tags outright -- there is NO pickle fallback here, so replaying a log
never executes code. The codec classes still follow the MessageCodec
shape (message_type + tag + encode/decode), which keeps them under the
COD3xx paxlint symmetry rules and the corrupt-frame containment fuzz
(a malformed record must raise ValueError, never an uncontrolled
exception type). WAL frames additionally carry a CRC (wal/log.py), so
a corrupt record on disk is normally caught before decode.
"""

from __future__ import annotations

import dataclasses
import struct

from frankenpaxos_tpu.runtime.serializer import MessageCodec

_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")
_QQQ = struct.Struct("<qqq")
_I32 = struct.Struct("<i")


@dataclasses.dataclass(frozen=True)
class WalPromise:
    """The acceptor promised (or voted in) ``round``."""

    round: int


@dataclasses.dataclass(frozen=True)
class WalVote:
    """A single-slot vote: ``value`` is one wire-encoded
    CommandBatchOrNoop (``wire.encode_value``)."""

    slot: int
    round: int
    value: bytes


@dataclasses.dataclass(frozen=True)
class WalVoteRun:
    """A whole voted run in one record: ``values`` is the wire's
    value-array segment (``wire.encode_value_array`` -- a raw copy of
    the inbound Phase2aRun's lazy payload). ``stride`` is 1 for
    MultiPaxos runs and the owner's slot stride for Mencius."""

    start_slot: int
    stride: int
    round: int
    values: bytes


@dataclasses.dataclass(frozen=True)
class WalNoopRange:
    """A noop-range vote (Mencius skip machinery): the acceptor voted
    Noop for every slot it owns in [start, end)."""

    slot_start_inclusive: int
    slot_end_exclusive: int
    round: int


@dataclasses.dataclass(frozen=True)
class WalChosenRun:
    """Chosen log entries at a replica: slots start, start + stride,
    ...; ``values`` is a value-array segment."""

    start_slot: int
    stride: int
    values: bytes


@dataclasses.dataclass(frozen=True)
class WalEpoch:
    """A committed reconfiguration epoch (reconfig/): ``payload`` is
    the role-encoded EpochConfig (``reconfig.wire.encode_epoch_config``
    -- epoch id, activation start slot, f, member addresses). Durable
    BEFORE the EpochAck leaves the acceptor: a crashed acceptor can
    never have acked an epoch it will not recover, which is what makes
    the old-epoch write quorum of acks a real matchmaker commit."""

    payload: bytes


@dataclasses.dataclass(frozen=True)
class WalGeoPromise:
    """paxgeo (protocols/wpaxos): the acceptor promised ``ballot`` for
    object group ``group``. Durable BEFORE the Phase1b ack leaves the
    acceptor -- a row-majority of these durable acks from the old home
    zone is an object steal's commit point (docs/GEO.md)."""

    group: int
    ballot: int


@dataclasses.dataclass(frozen=True)
class WalGeoVote:
    """paxgeo: a per-(group, slot) vote; ``value`` is one wire-encoded
    CommandBatchOrNoop (``wire.encode_value``, shared with
    multipaxos)."""

    group: int
    slot: int
    ballot: int
    value: bytes


@dataclasses.dataclass(frozen=True)
class WalGeoEpoch:
    """paxgeo: a committed object-steal epoch entry; ``payload`` is
    the role-encoded GeoEpoch (``wpaxos.wire.encode_geo_epoch`` --
    group, epoch, activation start slot, home zone, ballot). One
    layout for the wire and the log, like WalEpoch."""

    payload: bytes


@dataclasses.dataclass(frozen=True)
class WalSnapshot:
    """A compaction base: everything before this record is superseded.

    For replicas ``payload`` carries the SM snapshot + executed
    watermark + client table (role-encoded); for acceptors it is empty
    (their compaction re-logs live state as ordinary records after the
    marker)."""

    payload: bytes


def _take_bytes(buf: bytes, at: int) -> tuple[bytes, int]:
    """Length-prefixed bytes with HOSTILE-LENGTH validation: a negative
    or overrunning count raises ValueError inside decode (the
    transport corrupt-frame guard / recovery CRC both treat that as a
    clean drop)."""
    (n,) = _I32.unpack_from(buf, at)
    at += 4
    if n < 0 or at + n > len(buf):
        raise ValueError(
            f"malformed WAL byte segment: length {n} exceeds payload "
            f"({len(buf) - at} bytes left)")
    return buf[at:at + n], at + n


class WalPromiseCodec(MessageCodec):
    message_type = WalPromise
    tag = 1

    def encode(self, out, message):
        out += _I64.pack(message.round)

    def decode(self, buf, at):
        (round,) = _I64.unpack_from(buf, at)
        return WalPromise(round=round), at + 8


class WalVoteCodec(MessageCodec):
    message_type = WalVote
    tag = 2

    def encode(self, out, message):
        out += _I64I64.pack(message.slot, message.round)
        out += _I32.pack(len(message.value))
        out += message.value

    def decode(self, buf, at):
        slot, round = _I64I64.unpack_from(buf, at)
        value, at = _take_bytes(buf, at + 16)
        return WalVote(slot=slot, round=round, value=value), at


class WalVoteRunCodec(MessageCodec):
    message_type = WalVoteRun
    tag = 3

    def encode(self, out, message):
        out += _QQQ.pack(message.start_slot, message.stride,
                         message.round)
        out += _I32.pack(len(message.values))
        out += message.values

    def decode(self, buf, at):
        start, stride, round = _QQQ.unpack_from(buf, at)
        values, at = _take_bytes(buf, at + 24)
        return WalVoteRun(start_slot=start, stride=stride, round=round,
                          values=values), at


class WalNoopRangeCodec(MessageCodec):
    message_type = WalNoopRange
    tag = 4

    def encode(self, out, message):
        out += _QQQ.pack(message.slot_start_inclusive,
                         message.slot_end_exclusive, message.round)

    def decode(self, buf, at):
        start, end, round = _QQQ.unpack_from(buf, at)
        return WalNoopRange(slot_start_inclusive=start,
                            slot_end_exclusive=end, round=round), at + 24


class WalChosenRunCodec(MessageCodec):
    message_type = WalChosenRun
    tag = 5

    def encode(self, out, message):
        out += _I64I64.pack(message.start_slot, message.stride)
        out += _I32.pack(len(message.values))
        out += message.values

    def decode(self, buf, at):
        start, stride = _I64I64.unpack_from(buf, at)
        values, at = _take_bytes(buf, at + 16)
        return WalChosenRun(start_slot=start, stride=stride,
                            values=values), at


class WalEpochCodec(MessageCodec):
    message_type = WalEpoch
    tag = 7

    def encode(self, out, message):
        out += _I32.pack(len(message.payload))
        out += message.payload

    def decode(self, buf, at):
        payload, at = _take_bytes(buf, at)
        return WalEpoch(payload=payload), at


class WalGeoPromiseCodec(MessageCodec):
    message_type = WalGeoPromise
    tag = 8

    def encode(self, out, message):
        out += _I64I64.pack(message.group, message.ballot)

    def decode(self, buf, at):
        group, ballot = _I64I64.unpack_from(buf, at)
        return WalGeoPromise(group=group, ballot=ballot), at + 16


class WalGeoVoteCodec(MessageCodec):
    message_type = WalGeoVote
    tag = 9

    def encode(self, out, message):
        out += _QQQ.pack(message.group, message.slot, message.ballot)
        out += _I32.pack(len(message.value))
        out += message.value

    def decode(self, buf, at):
        group, slot, ballot = _QQQ.unpack_from(buf, at)
        value, at = _take_bytes(buf, at + 24)
        return WalGeoVote(group=group, slot=slot, ballot=ballot,
                          value=value), at


class WalGeoEpochCodec(MessageCodec):
    message_type = WalGeoEpoch
    tag = 10

    def encode(self, out, message):
        out += _I32.pack(len(message.payload))
        out += message.payload

    def decode(self, buf, at):
        payload, at = _take_bytes(buf, at)
        return WalGeoEpoch(payload=payload), at


class WalSnapshotCodec(MessageCodec):
    message_type = WalSnapshot
    tag = 6

    def encode(self, out, message):
        out += _I32.pack(len(message.payload))
        out += message.payload

    def decode(self, buf, at):
        payload, at = _take_bytes(buf, at)
        return WalSnapshot(payload=payload), at


_RECORD_CODECS_BY_TYPE: dict[type, MessageCodec] = {}
_RECORD_CODECS_BY_TAG: dict[int, MessageCodec] = {}


class WalRecordSerializer:
    """The record-space twin of HybridSerializer, WITHOUT the pickle
    fallback: the record set is closed, so an unknown tag in a
    CRC-valid frame is corruption (or a future format) and raises
    ValueError instead of ever evaluating bytes."""

    def to_bytes(self, record) -> bytes:
        codec = _RECORD_CODECS_BY_TYPE.get(type(record))
        if codec is None:
            raise ValueError(
                f"no WAL record codec for {type(record).__name__}")
        out = bytearray((codec.tag,))
        codec.encode(out, record)
        return bytes(out)

    def from_bytes(self, data: bytes):
        if not data:
            # A zero-length frame passes the CRC check (crc32(b"") is
            # 0), so a zero-filled torn tail reaches here: refuse with
            # the ValueError the recovery loop treats as a torn frame.
            raise ValueError("empty WAL record payload")
        codec = _RECORD_CODECS_BY_TAG.get(data[0])
        if codec is None:
            raise ValueError(f"unknown WAL record tag {data[0]}")
        try:
            record, _ = codec.decode(data, 1)
        except (struct.error, IndexError) as e:
            raise ValueError(f"corrupt WAL record: {e}") from e
        return record


WAL_SERIALIZER = WalRecordSerializer()

for _codec in (WalPromiseCodec(), WalVoteCodec(), WalVoteRunCodec(),
               WalNoopRangeCodec(), WalChosenRunCodec(),
               WalSnapshotCodec(), WalEpochCodec(),
               WalGeoPromiseCodec(), WalGeoVoteCodec(),
               WalGeoEpochCodec()):
    _RECORD_CODECS_BY_TYPE[_codec.message_type] = _codec
    _RECORD_CODECS_BY_TAG[_codec.tag] = _codec
