"""The write-ahead log: CRC framing, group commit, segments, recovery.

THE GROUP-COMMIT RULE: ``append`` only stages a record in an in-memory
buffer; nothing is durable -- and no acknowledgement depending on it
may leave the actor -- until ``sync()`` runs. Roles call ``sync()``
once per ``on_drain`` (the event-loop drain boundary), so a drain of k
messages costs ONE buffered file write + ONE fsync, and every ack the
drain produced is released only after that fsync returns. A crash
between append and sync loses exactly the staged records -- and, by
the rule, no peer ever saw an ack for them.

FRAME FORMAT (docs/DURABILITY.md): each record is
``<u32 len><u32 crc32(payload)><payload>`` little-endian, where payload
is a WAL-record frame (record tag byte + fixed-layout body, in the
record-private tag space of wal/records.py). Recovery walks segments in order and stops at the
first torn or CRC-failing frame: a partial group commit at the tail is
truncated away, which is exactly the crash contract (those records were
never acknowledged).

SEGMENTS & COMPACTION: records append to ``seg-<n>.wal``; when the live
segment exceeds ``segment_bytes`` the next sync rotates to a fresh one.
``compact(records)`` writes a WalSnapshot marker + the re-logged live
state as the first records of a NEW segment (one fsync), then deletes
every older segment -- roles trigger it from the same watermark GC
that bounds their in-memory state, so the log on disk stays O(live
state), not O(history).
"""

from __future__ import annotations

import dataclasses
import os
import struct
from typing import Iterable
import zlib

from frankenpaxos_tpu.wal.records import WAL_SERIALIZER, WalSnapshot

_FRAME = struct.Struct("<II")  # record length, crc32(payload)

#: Refuse absurd frame lengths during recovery (a corrupt length field
#: must not size an allocation): no drain's record comes close.
MAX_RECORD = 64 * 1024 * 1024


class FileStorage:
    """Real files under a directory; ``sync`` is flush + ``os.fsync``.

    One WAL per role process, so handles are plain (no locking): the
    single-threaded event-loop contract covers the WAL exactly as it
    covers role state.
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._handles: dict[str, object] = {}

    def segments(self) -> list[str]:
        return sorted(n for n in os.listdir(self.root)
                      if n.startswith("seg-") and n.endswith(".wal"))

    def read(self, name: str) -> bytes:
        with open(os.path.join(self.root, name), "rb") as f:
            return f.read()

    def append(self, name: str, data: bytes) -> None:
        handle = self._handles.get(name)
        if handle is None:
            handle = open(os.path.join(self.root, name), "ab")
            self._handles[name] = handle
        handle.write(data)

    def sync(self, name: str) -> None:
        handle = self._handles.get(name)
        if handle is not None:
            handle.flush()
            os.fsync(handle.fileno())

    def delete(self, name: str) -> None:
        handle = self._handles.pop(name, None)
        if handle is not None:
            handle.close()
        try:
            os.unlink(os.path.join(self.root, name))
        except FileNotFoundError:
            pass

    def truncate(self, name: str, size: int) -> None:
        path = os.path.join(self.root, name)
        with open(path, "r+b") as f:
            f.truncate(size)
            f.flush()
            os.fsync(f.fileno())

    def size(self, name: str) -> int:
        try:
            return os.path.getsize(os.path.join(self.root, name))
        except FileNotFoundError:
            return 0

    def close(self) -> None:
        for handle in self._handles.values():
            handle.close()
        self._handles.clear()


class MemStorage:
    """The sim's crash-surviving stand-in: a dict of byte arrays OWNED
    BY THE HARNESS, not the actor. ``crash_restart`` discards the Wal
    object (and with it the unsynced group-commit buffer) but keeps
    this storage -- precisely the durability boundary a real crash
    draws, with byte-identical framing to FileStorage."""

    def __init__(self):
        self.files: dict[str, bytearray] = {}
        self.fsyncs = 0

    def segments(self) -> list[str]:
        return sorted(self.files)

    def read(self, name: str) -> bytes:
        return bytes(self.files[name])

    def append(self, name: str, data: bytes) -> None:
        self.files.setdefault(name, bytearray()).extend(data)

    def sync(self, name: str) -> None:
        self.fsyncs += 1

    def delete(self, name: str) -> None:
        self.files.pop(name, None)

    def truncate(self, name: str, size: int) -> None:
        if name in self.files:
            del self.files[name][size:]

    def size(self, name: str) -> int:
        return len(self.files.get(name, b""))

    def close(self) -> None:
        pass


@dataclasses.dataclass
class WalMetrics:
    """Group-commit accounting (the wal_lt bench records these)."""

    records_appended: int = 0
    syncs: int = 0  # sync() calls that flushed something (= fsyncs)
    bytes_synced: int = 0
    records_synced: int = 0
    compactions: int = 0
    segments_deleted: int = 0
    recovered_records: int = 0
    truncated_tail_bytes: int = 0

    def bytes_per_sync(self) -> float:
        return self.bytes_synced / self.syncs if self.syncs else 0.0


class Wal:
    def __init__(self, storage, segment_bytes: int = 1 << 20,
                 compact_every_bytes: int = 4 << 20):
        self.storage = storage
        self.segment_bytes = segment_bytes
        self.compact_every_bytes = compact_every_bytes
        self.metrics = WalMetrics()
        self._buf = bytearray()
        self._buf_records = 0
        self._bytes_since_compact = 0
        segments = storage.segments()
        if segments:
            self._seg_index = int(segments[-1][4:-4])
        else:
            self._seg_index = 0
        self._segment = f"seg-{self._seg_index:08d}.wal"

    # --- write path -------------------------------------------------------
    def append(self, record) -> None:
        """Stage one record for the drain's group commit. NOT durable
        until sync(); callers must hold back any ack that depends on
        it (the group-commit rule)."""
        payload = WAL_SERIALIZER.to_bytes(record)
        self._buf += _FRAME.pack(len(payload), zlib.crc32(payload))
        self._buf += payload
        self._buf_records += 1
        self.metrics.records_appended += 1

    def sync(self) -> None:
        """Group commit: write + fsync everything staged since the last
        sync (one fsync per drain, amortized over the drain's records).
        No-op when nothing is staged."""
        if not self._buf:
            return
        buf, self._buf = bytes(self._buf), bytearray()
        records, self._buf_records = self._buf_records, 0
        self.storage.append(self._segment, buf)
        self.storage.sync(self._segment)
        self.metrics.syncs += 1
        self.metrics.bytes_synced += len(buf)
        self.metrics.records_synced += records
        self._bytes_since_compact += len(buf)
        if self.storage.size(self._segment) >= self.segment_bytes:
            self._rotate()

    def _rotate(self) -> None:
        self._seg_index += 1
        self._segment = f"seg-{self._seg_index:08d}.wal"

    def wants_compaction(self) -> bool:
        return self._bytes_since_compact >= self.compact_every_bytes

    def compact(self, snapshot: WalSnapshot, records: Iterable) -> None:
        """Snapshot + reclaim: write ``snapshot`` followed by the
        re-logged live state as the first records of a fresh segment
        (one fsync), then delete every older segment. The caller
        passes exactly the state a restart must rebuild -- everything
        behind its watermark is gone from disk after this returns."""
        self.sync()  # staged records belong to the OLD log order
        old = self.storage.segments()
        self._rotate()
        self.append(snapshot)
        for record in records:
            self.append(record)
        buf, self._buf = bytes(self._buf), bytearray()
        records_n, self._buf_records = self._buf_records, 0
        self.storage.append(self._segment, buf)
        self.storage.sync(self._segment)
        self.metrics.syncs += 1
        self.metrics.bytes_synced += len(buf)
        self.metrics.records_synced += records_n
        for name in old:
            self.storage.delete(name)
            self.metrics.segments_deleted += 1
        self.metrics.compactions += 1
        self._bytes_since_compact = 0

    # --- recovery ---------------------------------------------------------
    def recover(self, logger=None) -> list:
        """All durable records in log order, stopping cleanly at the
        first torn/corrupt frame (an interrupted group commit at the
        tail -- records that, by the group-commit rule, were never
        acknowledged). Subsequent appends go to a FRESH segment so new
        records never land after truncated garbage."""
        records: list = []
        truncated = False
        for name in self.storage.segments():
            if truncated:
                # A torn frame in a NON-last segment cannot happen
                # through the append path (rotation only follows a
                # successful fsync); if it somehow does, everything
                # after it is unordered history -- drop it rather than
                # replaying out-of-order state.
                self.storage.delete(name)
                self.metrics.segments_deleted += 1
                continue
            data = self.storage.read(name)
            at = 0
            while at + _FRAME.size <= len(data):
                length, crc = _FRAME.unpack_from(data, at)
                start = at + _FRAME.size
                if length > MAX_RECORD or start + length > len(data):
                    break
                payload = data[start:start + length]
                if zlib.crc32(payload) != crc:
                    break
                try:
                    records.append(WAL_SERIALIZER.from_bytes(payload))
                except ValueError:
                    break
                at = start + length
            if at < len(data):
                # Torn tail (an interrupted group commit): physically
                # truncate it so recovery is IDEMPOTENT -- a later
                # restart must not re-find the garbage and mistake
                # segments written since for post-tear history.
                truncated = True
                self.metrics.truncated_tail_bytes += len(data) - at
                if logger is not None:
                    logger.warn(
                        f"wal: truncating torn tail of {name} "
                        f"({len(data) - at} bytes after offset {at})")
                self.storage.truncate(name, at)
        if records or truncated:
            self._rotate()
        self.metrics.recovered_records = len(records)
        return records

    def close(self) -> None:
        self.storage.close()
