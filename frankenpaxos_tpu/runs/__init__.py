"""The protocol-neutral run layer.

What MultiPaxos and Mencius grew separately -- run records + lazy value
arrays, watermark GC, WAL run records, serve/admission + retry
discipline, IngestBatcher routing -- extracted so any protocol can join
the drain-granular run pipeline without re-duplicating it. See
docs/RUN_PIPELINE.md ("The protocol-neutral layer") for the contract a
protocol implements to join.

Modules:

  * :mod:`.client` -- the client-side retry/admission discipline
    (retry budgets, Rejected backoff, staged-write coalescing);
  * :mod:`.routing` -- ClientRequest/array destination selection
    (ingest batchers > batchers > protocol leader fallback);
  * :mod:`.records` -- chosen-run log/WAL record helpers shared by
    replica roles;
  * :mod:`.depruns` -- drain-coalesced dependency columns for the
    EPaxos/BPaxos family (batched ops/depset reductions);
  * :mod:`.quorums` -- Fast Flexible Paxos quorum-spec construction
    for the fast-path protocols;
  * :mod:`.wire` -- fixed-layout codecs + paxwire coalescers for the
    run messages.

Import from the submodules directly -- this ``__init__`` deliberately
re-exports nothing, so a change to one runs/ module keeps a narrow
reverse-import closure (the diff-aware paxlint <10s budget,
docs/ANALYSIS.md).
"""
