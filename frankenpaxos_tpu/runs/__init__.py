"""The protocol-neutral run layer.

What MultiPaxos and Mencius grew separately -- run records + lazy value
arrays, watermark GC, WAL run records, serve/admission + retry
discipline, IngestBatcher routing -- extracted so any protocol can join
the drain-granular run pipeline without re-duplicating it. See
docs/RUN_PIPELINE.md ("The protocol-neutral layer") for the contract a
protocol implements to join.

Modules:

  * :mod:`.client` -- the client-side retry/admission discipline
    (retry budgets, Rejected backoff, staged-write coalescing);
  * :mod:`.routing` -- ClientRequest/array destination selection
    (ingest batchers > batchers > protocol leader fallback);
  * :mod:`.records` -- chosen-run log/WAL record helpers shared by
    replica roles;
  * :mod:`.depruns` -- drain-coalesced dependency columns for the
    EPaxos/BPaxos family (batched ops/depset reductions);
  * :mod:`.quorums` -- Fast Flexible Paxos quorum-spec construction
    for the fast-path protocols;
  * :mod:`.wire` -- fixed-layout codecs + paxwire coalescers for the
    run messages.
"""

from frankenpaxos_tpu.runs.client import RetryAdmissionMixin, StagedWriteMixin  # noqa: F401
from frankenpaxos_tpu.runs.records import log_chosen_values, wal_log_chosen_run  # noqa: F401
from frankenpaxos_tpu.runs.routing import (  # noqa: F401
    pick_array_destination,
    pick_request_destination,
)
