"""DepRun wire messages: drain-coalesced dependency-reply runs.

The dependency-carrying acks of the graph protocols (EPaxos
PreAcceptOk, tag 14's reply tag 15; SimpleBPaxos DependencyReply, tag
23) dominate their hot paths the way Phase2b dominates multipaxos --
and like Phase2b they arrive in same-peer runs at every transport
flush. The paxwire coalescers here fold such a run into ONE fixed-
layout extended-page message whose dependency sets travel as flat
columns::

    [i32 B][i32 L]
    B x entry header            (protocol-specific fixed struct)
    B*L x i64 watermarks        (row-major [entry][leader])
    B*L x i32 counts            (sparse-tail lengths)
    sum(counts) x i64 values    (concatenated sparse ids)

The column blocks decode with ``np.frombuffer`` and scatter straight
into a ``[B, L, W]`` DepSetBatch (``runs/depruns.py``), so a receiver
can union or compare the whole drain in one vmapped reduction instead
of B host-set walks. Receivers that want the original messages get
them via ``__wire_expand__`` -- like Phase2bAckBatch, coalescing
changes the frame and decode cost, never the delivered semantics, and
the protocol role x message topology is untouched (these codecs are
``transport_layer``; no role ever sends one).

Tags 208 and 209 (next free extended tags after 207).
"""

from __future__ import annotations

import dataclasses
import struct

import numpy as np

from frankenpaxos_tpu.runs import depruns
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I32I32 = struct.Struct("<ii")
# instance (replica i32, number i64) + ballot (i64, i32) + sender
# replica i32 + sequence number i64 (the PreAcceptOk fixed prefix).
_EPAXOS_ENTRY = struct.Struct("<iqqiiq")
# vertex (leader i32, id i64) + dep service node i32.
_BPAXOS_ENTRY = struct.Struct("<iqi")


def _put_columns(out: bytearray, watermarks, counts, values) -> None:
    out += np.asarray(watermarks, dtype="<i8").tobytes()
    out += np.asarray(counts, dtype="<i4").tobytes()
    out += np.asarray(values, dtype="<i8").tobytes()


def _take_columns(buf, at: int, num_columns: int):
    """Decode the three column blocks; ValueError on a hostile or torn
    count table (the transport's corrupt-frame containment channel)."""
    end = at + 12 * num_columns
    if end > len(buf):
        raise ValueError(
            f"malformed dep run: {num_columns} columns exceed payload")
    watermarks = np.frombuffer(buf, dtype="<i8", count=num_columns,
                               offset=at)
    counts = np.frombuffer(buf, dtype="<i4", count=num_columns,
                           offset=at + 8 * num_columns)
    if counts.size and int(counts.min()) < 0:
        raise ValueError("malformed dep run: negative tail count")
    total = int(counts.sum())
    if end + 8 * total > len(buf):
        raise ValueError(
            f"malformed dep run: {total} values exceed payload")
    values = np.frombuffer(buf, dtype="<i8", count=total, offset=end)
    return (tuple(int(w) for w in watermarks),
            tuple(int(c) for c in counts),
            tuple(int(v) for v in values), end + 8 * total)


def _expand_deps(run):
    """Per-entry InstancePrefixSets from a run's flat columns."""
    from frankenpaxos_tpu.compact import IntPrefixSet
    from frankenpaxos_tpu.protocols.epaxos.instance_prefix_set import (
        InstancePrefixSet,
    )

    for watermarks, counts, values in depruns.split_columns(
            run.num_leaders, run.watermarks, run.counts, run.values):
        columns = []
        offset = 0
        for watermark, count in zip(watermarks, counts):
            columns.append(IntPrefixSet(
                watermark, set(values[offset:offset + count])))
            offset += count
        yield InstancePrefixSet(run.num_leaders, columns)


@dataclasses.dataclass(frozen=True)
class PreAcceptOkRun:
    """A drain's EPaxos PreAcceptOks in column form, send order
    preserved. ``headers[b]`` is ``(instance_replica, instance_number,
    ballot_ordering, ballot_replica, replica_index, sequence_number)``.
    """

    num_leaders: int
    headers: tuple
    watermarks: tuple
    counts: tuple
    values: tuple

    def __wire_expand__(self, serializer):
        from frankenpaxos_tpu.protocols.epaxos.instance_prefix_set import (
            Instance,
        )
        from frankenpaxos_tpu.protocols.epaxos.messages import PreAcceptOk

        for header, deps in zip(self.headers, _expand_deps(self)):
            inst_replica, inst_number, b0, b1, replica, seq = header
            yield PreAcceptOk(instance=Instance(inst_replica, inst_number),
                              ballot=(b0, b1), replica_index=replica,
                              sequence_number=seq, dependencies=deps)


@dataclasses.dataclass(frozen=True)
class DepReplyRun:
    """A drain's BPaxos DependencyReplies in column form. ``headers[b]``
    is ``(vertex_leader_index, vertex_instance_number, node_index)``."""

    num_leaders: int
    headers: tuple
    watermarks: tuple
    counts: tuple
    values: tuple

    def __wire_expand__(self, serializer):
        from frankenpaxos_tpu.protocols.simplebpaxos.messages import (
            DependencyReply,
            VertexId,
        )

        for header, deps in zip(self.headers, _expand_deps(self)):
            leader, number, node = header
            yield DependencyReply(vertex_id=VertexId(leader, number),
                                  dep_service_node_index=node,
                                  dependencies=deps)


class _DepRunCodec(MessageCodec):
    """Shared run layout; subclasses fix the entry-header struct."""

    entry_struct: struct.Struct

    def encode(self, out, message):
        out += _I32I32.pack(len(message.headers), message.num_leaders)
        for header in message.headers:
            out += self.entry_struct.pack(*header)
        _put_columns(out, message.watermarks, message.counts,
                     message.values)

    def decode(self, buf, at):
        num_entries, num_leaders = _I32I32.unpack_from(buf, at)
        at += 8
        entry_size = self.entry_struct.size
        if (num_entries < 0 or num_leaders <= 0
                or at + num_entries * entry_size > len(buf)):
            raise ValueError(
                f"malformed dep run: {num_entries} entries x "
                f"{num_leaders} leaders exceeds payload")
        headers = []
        for _ in range(num_entries):
            headers.append(self.entry_struct.unpack_from(buf, at))
            at += entry_size
        watermarks, counts, values, at = _take_columns(
            buf, at, num_entries * num_leaders)
        return self.message_type(
            num_leaders=num_leaders, headers=tuple(headers),
            watermarks=watermarks, counts=counts, values=values), at


class PreAcceptOkRunCodec(_DepRunCodec):
    message_type = PreAcceptOkRun
    tag = 208
    entry_struct = _EPAXOS_ENTRY
    # Encoded by the transport's flush-time coalescer, decoded and
    # expanded by the transport -- no role send site (paxflow FLOW403
    # skips transport_layer codecs; the marker must sit in the
    # registered class's own body for the AST scan).
    transport_layer = True


class DepReplyRunCodec(_DepRunCodec):
    message_type = DepReplyRun
    tag = 209
    entry_struct = _BPAXOS_ENTRY
    transport_layer = True


def _encode_run(codec: _DepRunCodec, run) -> bytes:
    out = bytearray((0, codec.tag - 128))
    codec.encode(out, run)
    return bytes(out)


def _coalesce_pre_accept_ok(payloads: list):
    """paxwire coalescer for runs of tag-15 (PreAcceptOk) payloads.
    Declines (None -> generic batch frame) on any unexpected layout."""
    from frankenpaxos_tpu.protocols.epaxos.wire import PreAcceptOkCodec

    codec = PreAcceptOkCodec()
    messages = []
    for payload in payloads:
        if not payload or payload[0] != PreAcceptOkCodec.tag:
            return None
        message, end = codec.decode(payload, 1)
        if end != len(payload):
            return None
        messages.append(message)
    columns = depruns.sets_to_columns([m.dependencies for m in messages])
    if columns is None:
        return None
    num_leaders, watermarks, counts, values = columns
    return _encode_run(PreAcceptOkRunCodec(), PreAcceptOkRun(
        num_leaders=num_leaders,
        headers=tuple((m.instance.replica_index,
                       m.instance.instance_number, m.ballot[0],
                       m.ballot[1], m.replica_index, m.sequence_number)
                      for m in messages),
        watermarks=watermarks, counts=counts, values=values))


def _coalesce_dependency_reply(payloads: list):
    """paxwire coalescer for runs of tag-23 (DependencyReply) payloads."""
    from frankenpaxos_tpu.protocols.simplebpaxos.wire import (
        DependencyReplyCodec,
    )

    codec = DependencyReplyCodec()
    messages = []
    for payload in payloads:
        if not payload or payload[0] != DependencyReplyCodec.tag:
            return None
        message, end = codec.decode(payload, 1)
        if end != len(payload):
            return None
        messages.append(message)
    columns = depruns.sets_to_columns([m.dependencies for m in messages])
    if columns is None:
        return None
    num_leaders, watermarks, counts, values = columns
    return _encode_run(DepReplyRunCodec(), DepReplyRun(
        num_leaders=num_leaders,
        headers=tuple((m.vertex_id.replica_index,
                       m.vertex_id.instance_number,
                       m.dep_service_node_index) for m in messages),
        watermarks=watermarks, counts=counts, values=values))


def _register() -> None:
    from frankenpaxos_tpu.runtime import paxwire

    register_codec(PreAcceptOkRunCodec())
    register_codec(DepReplyRunCodec())
    # The protocol ack tags these runs coalesce (epaxos/wire.py
    # PreAcceptOkCodec, simplebpaxos/wire.py DependencyReplyCodec) --
    # literal here so this module never imports a protocol at load.
    paxwire.register_coalescer(15, _coalesce_pre_accept_ok)
    paxwire.register_coalescer(23, _coalesce_dependency_reply)


_register()
