"""Batched dependency-column engine for DepRun wire messages.

A drain's dependency-carrying replies (EPaxos PreAcceptOk, BPaxos
DependencyReply) coalesce on the wire into ONE run message whose
dependency sets travel as flat columns (``runs/wire.py``):

  * ``watermarks``: ``B*L`` int64, row-major ``[entry][leader]``;
  * ``counts``:     ``B*L`` int32, sparse-tail lengths per column;
  * ``values``:     ``sum(counts)`` int64 sparse ids, concatenated in
    column order.

This module turns those columns into the ``[B, L, W]`` ``DepSetBatch``
of ``ops/depset.py`` with vectorized NumPy scatters -- no per-entry
``InstancePrefixSet`` objects on the decode path -- so a receiver can
union or compare a whole drain in one vmapped device reduction
(``drain_union``). The inverse (``sets_to_columns``) feeds the
coalescer. Layout-only; protocol message types never appear here.

Sets whose sparse ids span more than ``MAX_TAIL_WINDOW`` fall back to
host algebra (mirroring ``protocols/epaxos/device_deps.py`` -- tails
hug the per-column watermarks in steady state, so the dense window is
the common case).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from frankenpaxos_tpu.ops import depset

MAX_TAIL_WINDOW = 2048


def sets_to_columns(dep_sets) -> Optional[tuple[int, tuple, tuple, tuple]]:
    """Flatten InstancePrefixSet-shaped objects (anything with
    ``columns`` of ``(watermark, values)``) into flat column tuples.

    Returns ``(num_leaders, watermarks, counts, values)`` with values
    per column in ascending order, or None when the sets disagree on
    column count (a malformed mix -- callers decline to coalesce).
    """
    if not dep_sets:
        return None
    num_leaders = len(dep_sets[0].columns)
    watermarks: list[int] = []
    counts: list[int] = []
    values: list[int] = []
    for dep_set in dep_sets:
        if len(dep_set.columns) != num_leaders:
            return None
        for column in dep_set.columns:
            ordered = sorted(column.values)
            watermarks.append(column.watermark)
            counts.append(len(ordered))
            values.extend(ordered)
    return num_leaders, tuple(watermarks), tuple(counts), tuple(values)


def split_columns(num_leaders: int, watermarks, counts, values):
    """Per-entry views of flat columns: yields ``(watermarks [L],
    counts [L], values tuple)`` for each of the B entries."""
    if num_leaders <= 0:
        raise ValueError(f"num_leaders must be positive: {num_leaders}")
    if len(watermarks) % num_leaders or len(watermarks) != len(counts):
        raise ValueError(
            f"ragged columns: {len(watermarks)} watermarks, "
            f"{len(counts)} counts, L={num_leaders}")
    if sum(counts) != len(values):
        raise ValueError(
            f"ragged columns: counts sum to {sum(counts)} but "
            f"{len(values)} values present")
    offset = 0
    for entry in range(len(watermarks) // num_leaders):
        lo, hi = entry * num_leaders, (entry + 1) * num_leaders
        taken = sum(counts[lo:hi])
        yield (watermarks[lo:hi], counts[lo:hi],
               values[offset:offset + taken])
        offset += taken


def columns_to_batch(num_leaders: int, watermarks, counts,
                     values) -> Optional[depset.DepSetBatch]:
    """Flat columns -> one ``[B, L, W]`` DepSetBatch, scattered without
    per-entry Python objects. None when the sparse ids span a window
    wider than ``MAX_TAIL_WINDOW`` (callers fall back to host sets).
    """
    import jax.numpy as jnp

    if num_leaders <= 0 or len(watermarks) % num_leaders:
        return None
    num_entries = len(watermarks) // num_leaders
    vals = np.asarray(values, dtype=np.int64)
    counts_arr = np.asarray(counts, dtype=np.int64)
    if counts_arr.sum() != vals.shape[0]:
        return None
    base = int(vals.min()) if vals.size else 0
    spread = (int(vals.max()) - base + 1) if vals.size else 1
    width = 8
    while width < spread:
        width *= 2
    if width > MAX_TAIL_WINDOW:
        return None
    wm = np.asarray(watermarks, dtype=np.int32).reshape(num_entries,
                                                        num_leaders)
    tails = np.zeros((num_entries * num_leaders, width), dtype=np.uint8)
    rows = np.repeat(np.arange(num_entries * num_leaders), counts_arr)
    tails[rows, vals - base] = 1
    return depset.DepSetBatch(
        jnp.asarray(wm),
        jnp.asarray(tails.reshape(num_entries, num_leaders, width)),
        jnp.int32(base))


def drain_union(batch: depset.DepSetBatch) -> tuple[np.ndarray,
                                                    np.ndarray, int]:
    """Union every dependency set of a decoded drain in one vmapped
    reduction: ``(watermarks [L], tails [L, W], tail_base)`` on host.
    """
    reduced = depset.union_reduce(batch)
    return (np.asarray(reduced.watermarks)[0],
            np.asarray(reduced.tails)[0], int(reduced.tail_base))
