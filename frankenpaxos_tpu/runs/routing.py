"""ClientRequest routing for the run pipeline.

The paxingest destination ladder the multipaxos and mencius clients
each hard-coded: ingest disseminators absorb client fan-in when the
config deploys them (a resend re-rolls the pick, so a dead batcher
costs a retry, not a wedge), plain batchers come next, and the
protocol's own leader-selection rule is the fallback. Protocols that
route differently (per-group leaders, rounds) pass that rule in as
``leader_fallback`` -- the ladder itself is protocol-neutral.
"""

from __future__ import annotations

import random
from typing import Callable


def pick_request_destination(config, rng: random.Random,
                             leader_fallback: Callable):
    """Destination for a single ClientRequest:
    ingest batchers > batchers > ``leader_fallback()``."""
    if getattr(config, "num_ingest_batchers", 0) > 0:
        return config.ingest_batcher_addresses[
            rng.randrange(config.num_ingest_batchers)]
    if getattr(config, "num_batchers", 0) > 0:
        return config.batcher_addresses[
            rng.randrange(config.num_batchers)]
    return leader_fallback()


def pick_array_destination(config, rng: random.Random,
                           leader_fallback: Callable):
    """Destination for a staged ClientRequestArray: ingest batchers >
    ``leader_fallback()``. Arrays bypass plain batchers -- they are
    already transport-level coalesced, and the batcher tier only
    re-buckets singles."""
    if getattr(config, "num_ingest_batchers", 0) > 0:
        return config.ingest_batcher_addresses[
            rng.randrange(config.num_ingest_batchers)]
    return leader_fallback()
