"""ClientRequest routing for the run pipeline.

The paxingest destination ladder the multipaxos and mencius clients
each hard-coded: ingest disseminators absorb client fan-in when the
config deploys them (a resend re-rolls the pick, so a dead batcher
costs a retry, not a wedge), plain batchers come next, and the
protocol's own leader-selection rule is the fallback. Protocols that
route differently (per-group leaders, rounds) pass that rule in as
``leader_fallback`` -- the ladder itself is protocol-neutral.

paxfan: with a :class:`~frankenpaxos_tpu.ingest.fan.ShardRouter`
(``fan``) and a session key, the ingest tier is no longer a random
pick -- the key pins to one batcher on the consistent ring, a dead
batcher's keys fail over to clockwise survivors, and every other key
keeps its shard. The random pick remains the keyless fallback (and
the single-batcher degenerate case routes identically either way).
"""

from __future__ import annotations

import random
from typing import Callable, Optional


def make_fan_router(config, *, revive_after_s: float = 1.0):
    """A ShardRouter over the config's ingest tier, or None when the
    config deploys no ingest batchers (the ladder falls through)."""
    if getattr(config, "num_ingest_batchers", 0) <= 0:
        return None
    from frankenpaxos_tpu.ingest.fan import ShardRouter

    return ShardRouter(config.num_ingest_batchers,
                       revive_after_s=revive_after_s)


def pick_request_destination(config, rng: random.Random,
                             leader_fallback: Callable,
                             fan=None, key: Optional[tuple] = None):
    """Destination for a single ClientRequest:
    ingest batchers (ring-pinned when ``fan``+``key`` are given,
    random otherwise) > batchers > ``leader_fallback()``."""
    if getattr(config, "num_ingest_batchers", 0) > 0:
        if fan is not None and key is not None:
            return config.ingest_batcher_addresses[
                fan.route(key[0], key[1])]
        return config.ingest_batcher_addresses[
            rng.randrange(config.num_ingest_batchers)]
    if getattr(config, "num_batchers", 0) > 0:
        return config.batcher_addresses[
            rng.randrange(config.num_batchers)]
    return leader_fallback()


def pick_array_destination(config, rng: random.Random,
                           leader_fallback: Callable,
                           fan=None, key: Optional[tuple] = None):
    """Destination for a staged ClientRequestArray: ingest batchers >
    ``leader_fallback()``. Arrays bypass plain batchers -- they are
    already transport-level coalesced, and the batcher tier only
    re-buckets singles. A staged array spans many pseudonyms of one
    client, so its ring key is the client-scoped sentinel the caller
    passes (conventionally ``(client_token, -1)``)."""
    if getattr(config, "num_ingest_batchers", 0) > 0:
        if fan is not None and key is not None:
            return config.ingest_batcher_addresses[
                fan.route(key[0], key[1])]
        return config.ingest_batcher_addresses[
            rng.randrange(config.num_ingest_batchers)]
    return leader_fallback()
