"""Chosen-run record helpers shared by replica roles.

The run pipeline delivers decided values as (start_slot, stride,
values) runs over lazy value arrays. Logging a run into a BufferMap log
and appending its NEW entries to the WAL is identical across protocols
(multipaxos: stride 1; mencius: stride = num leader groups) -- only the
value-array codec is protocol-owned, so it is passed in rather than
imported (keeps ``runs/`` free of ``protocols/`` imports).
"""

from __future__ import annotations

from typing import Callable

from frankenpaxos_tpu.wal import WalChosenRun


def log_chosen_values(log, executed_watermark: int, start_slot: int,
                      stride: int, values) -> tuple[int, int]:
    """Put a (possibly strided) run of chosen values into ``log``.

    Slots below the executed watermark are duplicates by definition
    (everything below it is chosen and executed; the log is GC'd to
    it). Returns ``(new_count, high_slot)`` where ``high_slot`` is the
    largest slot this run newly filled, or -1 when none were new.
    Shared by the live ChosenRun handlers and WAL replay.
    """
    new = 0
    high = -1
    slot = start_slot
    for value in values:
        if slot >= executed_watermark and log.get(slot) is None:
            log.put(slot, value)
            new += 1
            high = slot
        slot += stride
    return new, high


def wal_log_chosen_run(wal, log_get: Callable, start_slot: int,
                       stride: int, values, all_new: bool,
                       encode: Callable) -> None:
    """Append a freshly-logged run's NEW entries to ``wal``.

    The common case -- every slot new -- logs the inbound lazy value
    array as ONE raw-copy record; a partially-duplicate run (rare: a
    resend or post-failover overlap) falls back to per-new-slot records,
    identified by the entry this run put (``log_get(slot) is value``).
    ``encode`` is the protocol's value-array encoder.
    """
    if all_new:
        wal.append(WalChosenRun(start_slot=start_slot, stride=stride,
                                values=encode(values)))
        return
    for i, value in enumerate(values):
        slot = start_slot + i * stride
        if log_get(slot) is value:
            wal.append(WalChosenRun(start_slot=slot, stride=1,
                                    values=encode((value,))))
