"""Client-side retry/admission discipline for the run pipeline.

This is the ~130-line mirror the multipaxos and mencius clients carried
as accepted duplication (flagged in PR 6), extracted verbatim-in-spirit:
retry-budget bookkeeping, the Rejected backoff/reissue path, and the
coalesce-writes staging machinery. A protocol's client subclasses both
mixins and keeps only its own message construction and ``self.send``
call sites (so the paxflow graphs still attribute every edge to the
protocol module).

Pending-operation states are duck-typed: any object with ``id``,
``callback``, ``resend`` (a timer), ``attempts``, and -- for operations
that can draw a Rejected -- ``backoff_pending``. States without
``backoff_pending`` (e.g. the multipaxos MaxSlot quorum phase, which
acceptors never reject) are skipped by the Rejected path via the
``getattr`` default.
"""

from __future__ import annotations

from frankenpaxos_tpu.serve.backoff import RETRY_EXHAUSTED


class RetryAdmissionMixin:
    """The paxload retry discipline (serve/backoff.py, docs/SERVING.md).

    Subclass contract -- attributes set in ``__init__``:

      * ``states``: dict pseudonym -> pending-operation state,
      * ``rng``: a ``random.Random``,
      * ``_retry_budget``: int; 0 keeps the pre-paxload behavior
        (unlimited resends, Rejected = immediate-backoff retry, no cap).
        With a budget, EVERY retry (Rejected backoff or timeout
        failover) consumes it, and exhaustion completes the operation
        with ``serve.RETRY_EXHAUSTED`` -- no request wedges silently;
      * ``_retry_backoff``: a ``serve.backoff.Backoff``;

    and one hook:

      * ``_reissue(pseudonym, state)``: re-send the operation after a
        backoff expiry (the protocol's own request construction and
        ``send`` call sites live here).
    """

    def _consume_retry(self, pseudonym: int, state, kind: str) -> bool:
        """Retry-budget bookkeeping: True = proceed with the retry;
        False = the budget is exhausted and the operation just
        completed with RETRY_EXHAUSTED."""
        budget = self._retry_budget
        if budget <= 0:
            return True
        metrics = self.transport.runtime_metrics
        if state.attempts >= budget:
            state.resend.stop()
            del self.states[pseudonym]
            if metrics is not None:
                metrics.client_retry("giveup")
            state.callback(RETRY_EXHAUSTED)
            return False
        state.attempts += 1
        if metrics is not None:
            metrics.client_retry(kind)
        return True

    def _handle_rejected(self, src, rejected) -> None:
        """Admission refused these commands: the server is ALIVE but
        saturated. Back off (jittered exponential, the server's
        retry_after_ms as a floor) and re-issue to the SAME destination
        class -- unlike a timeout, no failover. Each backoff consumes
        the retry budget when one is set.

        paxfan: ``_note_shed_source`` attributes the shed to the
        SHARD that sent it -- clients with a fan router record a
        per-shard shed deadline there and return it as an extra floor,
        so one hot batcher's retry-after never delays keys pinned to
        the other shards."""
        shard_floor_s = self._note_shed_source(src, rejected)
        for pseudonym, client_id in rejected.entries:
            state = self.states.get(pseudonym)
            if state is None or client_id != getattr(state, "id", None):
                self.logger.debug(
                    f"stale Rejected entry for pseudonym {pseudonym}")
                continue
            if getattr(state, "backoff_pending", True):
                # Under overload the resend and the original both reach
                # the leader and each draws a Rejected; one backoff per
                # operation, or the budget is double-consumed and the
                # shedding leader gets duplicate reissues. The True
                # default drops states that cannot be rejected at all.
                continue
            state.resend.stop()
            if not self._consume_retry(pseudonym, state, "backoff"):
                continue
            delay_s = self._retry_backoff.delay_s(
                state.attempts - 1 if self._retry_budget > 0
                else state.attempts, self.rng,
                floor_s=max(rejected.retry_after_ms / 1000.0,
                            shard_floor_s))
            if self._retry_budget <= 0:
                # No budget: attempts still drive the backoff curve.
                state.attempts += 1
            self._schedule_reissue(pseudonym, state, delay_s)

    def _schedule_reissue(self, pseudonym: int, state,
                          delay_s: float) -> None:
        """One-shot jittered-backoff timer re-issuing ``state``'s
        operation through the ``_reissue`` hook. The closure
        re-validates the pending state at fire time: a completion (or
        a newer operation) in the backoff window makes it a no-op."""
        expected_id = state.id
        state.backoff_pending = True

        def reissue():
            current = self.states.get(pseudonym)
            if current is not state \
                    or getattr(current, "id", None) != expected_id:
                return
            current.backoff_pending = False
            self._reissue(pseudonym, current)
            current.resend.start()

        timer = self.timer(f"backoff{pseudonym}", delay_s, reissue)
        timer.start()

    def _note_shed_source(self, src, rejected) -> float:
        """Hook: attribute a Rejected to its sending shard and return
        the extra per-shard backoff floor in seconds (0.0 = none).
        Default keeps the pre-paxfan tier-wide behavior."""
        return 0.0

    def _reissue(self, pseudonym: int, state) -> None:
        raise NotImplementedError


class StagedWriteMixin:
    """The coalesce-writes staging machinery.

    Writes staged in one event-loop pass ship as ONE array message (each
    command still gets its own slot -- transport-level coalescing, not
    slot sharing). On a real event-loop transport the flush is deferred
    to the END of the pass via ``call_soon_threadsafe`` (write() may be
    driven from off-loop threads); SimTransport has no loop -- there
    ``on_drain`` / an explicit ``flush_writes()`` ships them.

    Subclass contract: call ``_init_staging()`` in ``__init__`` and
    implement ``_flush_staged(staged)`` (destination pick + the array
    ``send`` stay in the protocol module).
    """

    def _init_staging(self) -> None:
        self._staged_writes: list = []
        self._flush_scheduled = False

    def _stage_write(self, command) -> None:
        self._staged_writes.append(command)
        loop = getattr(self.transport, "loop", None)
        if loop is not None and not self._flush_scheduled:
            self._flush_scheduled = True
            loop.call_soon_threadsafe(self._deferred_flush)

    def flush_writes(self) -> None:
        """Ship the staged writes as one array via ``_flush_staged``."""
        if not self._staged_writes:
            return
        staged, self._staged_writes = self._staged_writes, []
        self._flush_staged(staged)

    def _deferred_flush(self) -> None:
        self._flush_scheduled = False
        self.flush_writes()

    def on_drain(self) -> None:
        self.flush_writes()

    def _flush_staged(self, staged: list) -> None:
        raise NotImplementedError
