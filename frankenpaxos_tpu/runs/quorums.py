"""Fast Flexible Paxos quorum specs for the run layer.

Fast Paxos variants need three quorum predicates per configuration
(Fast Flexible Paxos / "Flexible Paxos + fast rounds"):

  * ``classic``: the phase-1 read / classic phase-2 write quorum (q1);
  * ``fast``: the fast-path choose quorum (qf);
  * ``recovery``: after phase 1, value v MAY have been fast-chosen iff
    a fast quorum voted v -- and every fast quorum intersects the
    leader's classic quorum in >= q1 + qf - n nodes, so v must be
    adopted exactly when it has that many votes among the phase-1
    replies.

All three are plain majority-style predicates, so they compile to the
matrix form ``quorums/spec.py`` already factors every quorum system
into -- evaluated by the host oracle or the unchanged fused device
checker (``ops/quorum``), never a new kernel family.

The spec builders derive the recovery threshold from the LIVE classic
and fast sizes rather than re-deriving it from ``f``: a configuration
with a weakened fast quorum yields a correspondingly weakened (unsafe)
recovery rule, which is exactly what safety sims must be able to
catch. The intersection-condition validators below are therefore
deliberately NOT called on any protocol path; they exist for tests and
deployment-time config vetting.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional, Sequence

import numpy as np

from frankenpaxos_tpu.quorums.spec import ANY, QuorumSpec


def _majority_spec(universe: tuple[int, ...], threshold: int) -> QuorumSpec:
    n = len(universe)
    return QuorumSpec(masks=np.ones((1, n), dtype=np.uint8),
                      thresholds=np.asarray([threshold], dtype=np.int32),
                      combine=ANY, universe=universe)


@dataclasses.dataclass(frozen=True)
class FastFlexibleSpecs:
    """The three predicates of one fast-capable configuration."""

    classic: QuorumSpec
    fast: QuorumSpec
    recovery: QuorumSpec


def fast_flexible_specs(n: int, classic_quorum_size: int,
                        fast_quorum_size: int,
                        universe: Optional[Sequence[int]] = None
                        ) -> FastFlexibleSpecs:
    """Specs for an ``n``-acceptor configuration with the given quorum
    sizes. ``universe`` defaults to acceptor indices ``0..n-1``.

    The recovery threshold is ``max(1, q1 + qf - n)`` -- the guaranteed
    intersection of a fast quorum with the leader's classic quorum,
    computed from the sizes actually configured (see module docstring
    for why it is not re-derived from f).
    """
    ids = tuple(range(n)) if universe is None else tuple(universe)
    if len(ids) != n:
        raise ValueError(f"universe has {len(ids)} nodes, expected {n}")
    return FastFlexibleSpecs(
        classic=_majority_spec(ids, classic_quorum_size),
        fast=_majority_spec(ids, fast_quorum_size),
        recovery=_majority_spec(
            ids, max(1, classic_quorum_size + fast_quorum_size - n)))


def check_fast_flexible(n: int, classic_quorum_size: int,
                        fast_quorum_size: int,
                        classic_quorum_size2: Optional[int] = None
                        ) -> list[str]:
    """Violations of the Fast Flexible Paxos intersection conditions.

    With phase-1 quorums of size q1 and phase-2 classic quorums of size
    q2 (= q1 for the symmetric protocols here), safety needs

      * q1 + q2 > n        (classic rounds: read sees every write), and
      * q1 + 2*qf > 2*n    (two fast quorums + a read quorum share a
                            node, so at most one value can be popular).

    Returns human-readable violation strings (empty = valid). NOT
    called by the protocols -- see the module docstring.
    """
    q1, qf = classic_quorum_size, fast_quorum_size
    q2 = q1 if classic_quorum_size2 is None else classic_quorum_size2
    violations = []
    if q1 + q2 <= n:
        violations.append(
            f"classic intersection: q1 + q2 = {q1 + q2} <= n = {n}")
    if q1 + 2 * qf <= 2 * n:
        violations.append(
            f"fast intersection: q1 + 2*qf = {q1 + 2 * qf} <= 2n = {2 * n}")
    return violations


class SpecChecker:
    """Evaluate one QuorumSpec, host or device.

    ``backend="host"`` runs the NumPy oracle (``QuorumSpec.evaluate``);
    ``backend="tpu"`` routes rows through ``ops/quorum``'s fused checker
    (``MultiConfigQuorumChecker`` over a single config -- the same
    factored-matmul kernel the multipaxos vote trackers use). Both are
    bit-identical; the sims default to host.
    """

    def __init__(self, spec: QuorumSpec, backend: str = "host",
                 metrics=None):
        if backend not in ("host", "tpu"):
            raise ValueError(f"unknown quorum backend {backend!r}")
        self.spec = spec
        self.backend = backend
        self._device = None
        # Zero-arg callable -> the owning role's RuntimeMetrics (or
        # None): resolved per check because the CLI attaches
        # transport.runtime_metrics after some roles construct their
        # checkers.
        self.metrics = metrics

    def check_batch(self, present: np.ndarray) -> np.ndarray:
        """``[B, N]`` responder rows -> ``[B]`` bool."""
        present = np.asarray(present, dtype=np.uint8)
        if self.metrics is not None:
            metrics = self.metrics()
            if metrics is not None:
                metrics.fastquorum_check(present.shape[0])
        if self.backend == "tpu":
            if self._device is None:
                from frankenpaxos_tpu.ops.quorum import (
                    MultiConfigQuorumChecker,
                )
                self._device = MultiConfigQuorumChecker([self.spec])
            return self._device.check_batch(
                present, np.zeros(present.shape[0], dtype=np.int32))
        return np.asarray(self.spec.evaluate(present))

    def check(self, nodes: Iterable[int]) -> bool:
        present = self.spec.present_vector(list(nodes))
        return bool(self.check_batch(present[None, :])[0])
