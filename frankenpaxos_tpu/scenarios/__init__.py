"""paxworld: the planet-scale serving scenario matrix.

The paxgeo x paxload fusion (docs/GLOBAL.md): the SoA open-loop load
tier (serve/loadgen.py) drives WPaxos/CRAQ deployments over
GeoSimTransport WAN topologies through deterministic, seeded
paxchaos fault schedules (frankenpaxos_tpu/faults/) -- zone outages
at the diurnal peak, cross-region partitions, follow-the-sun traffic
migration under the adaptive placement policy, two-continent
hot-object contention, cloud storage pathologies (periodic-window
fsync stalls), and CRAQ chain reconfiguration under tail kill -- and
every scenario is GATED on explicit SLO clauses: a goodput floor,
admitted p99/p999 ceilings, zero acked-write loss, exactly-once
execution, a control plane that is never shed, and bounded recovery
time.

``bench/global_lt.py`` runs the matrix and commits
``bench_results/global_lt.json``; the CI ``global-smoke`` job
enforces the gates on a reduced scale every PR, and the
``deployed-chaos`` job replays the zone-outage schedule against a
REAL deployment (bench/deployed_twin.py).
"""

from frankenpaxos_tpu.scenarios.matrix import (  # noqa: F401
    FULL,
    history_digest,
    run_matrix,
    run_scenario,
    Scale,
    SCENARIOS,
    SMOKE,
)
