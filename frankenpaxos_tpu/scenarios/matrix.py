"""The fused paxgeo x paxload scenario matrix (paxworld).

Every scenario drives the SoA open-loop load tier
(:class:`~frankenpaxos_tpu.serve.loadgen.GeoOverloadDriver`) against a
WPaxos (or CRAQ) deployment over a :class:`GeoSimTransport` WAN
topology, entirely on VIRTUAL time with ONE clock (the transport's):
arrivals, admission token buckets, client backoff timers, link
latencies, fault schedules, and the SLO measurements all read the same
virtual instant, so a scenario is a pure function of its seed -- the
golden test (tests/test_scenarios.py) pins byte-identical delivery
history AND an identical SLO row per seed.

THE SLO CONTRACT (every scenario row records these clauses, and
``bench/global_lt.py`` gates CI on them):

  * ``goodput_floor``        -- in-SLO completions/s over the measured
                                window stays above the floor;
  * ``p99_admitted_ceiling`` / ``p999_admitted_ceiling`` -- latency of
                                requests admitted on arrival (client
                                backoff excluded -- the latency the
                                serving path actually delivered);
  * ``zero_acked_write_loss`` -- an acked write is NEVER missing from
                                the (healed, settled) replicated state;
  * ``control_plane_never_shed`` -- no bounded inbox ever refuses a
                                control-lane frame (votes, Phase1,
                                epoch commits, chain hops);
  * ``no_silent_wedge``      -- every issued request concludes: ack,
                                explicit Rejected-driven backoff
                                conclusion, or bounded-retry
                                RETRY_EXHAUSTED (pending == 0 after
                                settle);
  * ``bounded_recovery``     -- where the scenario injects an outage/
                                partition, time from repair to the
                                first affected-lane completion is
                                bounded;
  * plus per-scenario extras (steal ping-pong bound, queue bound,
    zone-local read p99, fsync tail amplification).

paxchaos (ISSUE 14): every scenario's fault plan is a deterministic,
string-seeded ``faults.FaultSchedule`` compiled onto the sim backend
-- the SAME schedule objects the deployed-TCP twins
(``bench/deployed_twin.py``) replay over real sockets, real WALs, and
real SIGKILLs, with both worlds recording the schedule digest. The
follow-the-sun and hot-contention placement controllers are gone:
the REAL adaptive policy (request-origin EWMA + dominance +
hysteresis + min-dwell on the owning leader) is what the clauses now
gate.

The scenarios (ISSUE 13 + 14):

  1. ``zone_outage_peak``    -- SIGKILL a whole zone at its diurnal
                                maximum; WAL relaunch + steal repair.
                                (Deployed twin: CI ``deployed-chaos``.)
  2. ``region_partition``    -- cross-region partition: majority side
                                within SLO, minority sheds loudly and
                                heals without duplicate execution.
  3. ``follow_the_sun``      -- the diurnal peak walks across regions
                                and ADAPTIVE placement chases it from
                                measured traffic alone.
  4. ``hot_contention``      -- Zipf-hot objects contended from two
                                continents under a demand flip;
                                hysteresis + min-dwell bound the churn.
  5. ``fsync_stalls``        -- deterministic periodic-window WAL
                                fsync stalls (wal/faults.py): quorums
                                mask single stalls, overlap amplifies
                                p999 only. (Deployed twin: blocking
                                stalls over real FileStorage.)
  6. ``craq_read_scaling``   -- WPaxos-style global writes + CRAQ
                                zone-local chain reads under the same
                                admission/Rejected/backoff discipline.
  7. ``craq_chain_reconfig`` -- tail kill + chain re-link with the
                                dirty-version handoff: the craq chaos
                                exemption is over.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time

from frankenpaxos_tpu.bench.workload import OpenLoopWorkload
from frankenpaxos_tpu.geo import GeoTopology
from frankenpaxos_tpu.serve.backoff import Backoff
from frankenpaxos_tpu.serve.lanes import frame_lane, LANE_CONTROL
from frankenpaxos_tpu.serve.loadgen import GeoOverloadDriver, TrafficLane

#: The virtual service model shared by every scenario: cluster
#: capacity in commands/virtual-second, per-delivered-frame CPU cost,
#: tick width, and the serving SLO deadline. Sized for ~40% steady
#: utilization at the healthy offered load: the scenarios study
#: FAULTS under load, not baseline congestion collapse -- retry
#: amplification on top of a saturated baseline drowns every signal
#: the clauses gate (and real planetary fleets are not provisioned
#: at the knee either).
CAPACITY_CMDS_S = 900.0
MSG_COST_S = 0.0001
DT_S = 0.02
SLO_DEADLINE_S = 1.0

#: Per-leader admission knobs (serve/admission.py, flat so they map
#: onto WPaxosLeaderOptions verbatim): a token bucket above the
#: healthy per-zone rate, a watermark-tied in-flight budget, and a
#: bounded reject-newest client-lane inbox.
ADMISSION = dict(
    admission_token_rate=150.0,
    admission_token_burst=30.0,
    admission_inflight_limit=96,
    admission_inbox_capacity=256,
    admission_inbox_policy="reject",
    admission_retry_after_ms=100,
)
#: Client retry discipline: total retries (timeouts + rejections) per
#: op before a LOUD RETRY_EXHAUSTED conclusion. The resend timer is
#: FIXED (adaptive RTT timeouts read a 100ms fsync stall or a
#: transient queue as zone death and steal-failover out of a
#: perfectly alive zone -- patience is the right failure detector
#: when the fault model includes sub-outage stalls). Budget 2 with
#: the client's 1.5x-widening resend schedule bounds an unservable
#: op's lifetime to ~4.75 virtual seconds -- long enough to ride out
#: an outage dwell, short enough that a cross-region partition
#: visibly EXHAUSTS budgets (the loud-degradation clause) at both
#: scales.
RETRY_BUDGET = 2
RESEND_PERIOD_S = 1.0
REJECT_BACKOFF = Backoff(initial_s=0.1, max_s=1.0, multiplier=2.0,
                         jitter=0.5)


@dataclasses.dataclass(frozen=True)
class Scale:
    """One knob for smoke-vs-full sizing; everything else is shared so
    the smoke exercises exactly the committed code paths."""

    name: str
    sessions_per_lane: int
    per_zone_rate: float
    duration_s: float
    settle_s: float
    outage_dwell_s: float


SMOKE = Scale("smoke", sessions_per_lane=20_000, per_zone_rate=50.0,
              duration_s=9.0, settle_s=10.0, outage_dwell_s=1.5)
#: 3 lanes x 400k sessions = 1.2M open-loop sessions per scenario --
#: the "millions of users worldwide" configuration (ROADMAP).
FULL = Scale("full", sessions_per_lane=400_000, per_zone_rate=60.0,
             duration_s=21.0, settle_s=12.0, outage_dwell_s=2.0)


# --- clause / oracle helpers -------------------------------------------------


def clause(value, bound, kind: str = "max") -> dict:
    """One SLO clause row: ``kind`` is "max" (value <= bound), "min"
    (value >= bound), or "zero". A missing measurement (None) FAILS --
    an SLO you could not measure is not an SLO you met."""
    if value is None:
        passed = False
    elif kind == "max":
        passed = value <= bound
    elif kind == "min":
        passed = value >= bound
    else:
        passed = value == 0
    if isinstance(value, float):
        value = round(value, 4)
    return {"value": value, "bound": bound, "kind": kind,
            "passed": bool(passed)}


def _arm_control_oracle(transport) -> list:
    """Record any control-lane frame a bounded inbox refuses (the
    clause demands the list stays empty)."""
    refused: list = []
    original = transport._admit_to_inbox

    def checked(src, dst, data):
        verdict = original(src, dst, data)
        if not verdict and frame_lane(data) == LANE_CONTROL:
            refused.append((str(src), str(dst)))
        return verdict

    transport._admit_to_inbox = checked
    return refused


def _wpaxos_safety(sim, acked) -> list:
    """The paxgeo safety oracle over the healed, settled cluster --
    chosen-value uniqueness, replica prefix compatibility,
    exactly-once execution (the SAME invariant body the geo-chaos
    soak enforces, so the scenario gate and the soak gate can never
    silently drift apart) -- plus the matrix's own clause: no acked
    write missing from the replicated state."""
    from tests.protocols.test_wpaxos import WPaxosGeoSimulated

    violations: list = []
    # state_invariant reads only `sim`; borrow the soak's body
    # unbound so there is exactly one implementation.
    failure = WPaxosGeoSimulated.state_invariant(None, sim)
    if failure is not None:
        violations.append(failure)
    executed_union: set = set()
    for replica in sim.replicas:
        for seq in replica.executed:
            executed_union.update(seq)
    lost = [p for p in acked if p not in executed_union]
    if lost:
        violations.append(
            f"{len(lost)} acked writes missing from every replica "
            f"(first: {lost[0]!r})")
    return violations


def history_digest(transport) -> str:
    """sha256 over the delivered/triggered event history -- the golden
    determinism test's byte-identity check."""
    from frankenpaxos_tpu.runtime.sim_transport import DeliverMessage

    h = hashlib.sha256()
    for event in transport.history:
        if isinstance(event, DeliverMessage):
            m = event.message
            h.update(b"D|%d|%s|%s|" % (m.id, str(m.src).encode(),
                                       str(m.dst).encode()))
            h.update(m.data)
        else:
            h.update(b"T|%d|%s|%s" % (event.timer_id,
                                      str(event.address).encode(),
                                      event.name.encode()))
    return h.hexdigest()


# --- cluster + lane builders -------------------------------------------------


def _keys_for_zone(config, zone: int, n: int,
                   exclude: tuple = ()) -> list:
    """``n`` keys whose object groups are homed in ``zone`` (and not
    in ``exclude``d groups)."""
    keys: list = []
    i = 0
    while len(keys) < n:
        key = b"obj-%d" % i
        group = config.group_of_key(key)
        if config.initial_home[group] == zone \
                and group not in exclude:
            keys.append(key)
        i += 1
    return keys


#: The adaptive-placement knobs armed by the follow-the-sun and
#: hot-contention scenarios (paxchaos): request-origin EWMA on the
#: owning leader, 0.55 dominance over 2 consecutive 0.25 s checks,
#: 0.5 s minimum dwell -- hysteresis + dwell are what make the PR 13
#: steal boomerang unconstructible by construction.
PLACEMENT = dict(
    placement_check_period_s=0.25,
    placement_ewma_alpha=0.5,
    placement_dominance=0.55,
    placement_min_dwell_s=0.5,
    placement_hysteresis_checks=2,
    placement_min_samples=4,
)


def _wpaxos_cluster(seed: int, num_groups: int = 6,
                    num_zones: int = 3, admission: bool = True,
                    leader_knobs: dict | None = None):
    from frankenpaxos_tpu.protocols.wpaxos import (
        WPaxosClientOptions,
        WPaxosLeaderOptions,
    )
    from tests.protocols.wpaxos_harness import make_wpaxos

    regions = {f"r{z}": [f"zone-{z}"] for z in range(num_zones)}
    topo = GeoTopology(regions, seed=seed)
    sim = make_wpaxos(
        num_zones=num_zones, row_width=3, num_groups=num_groups,
        num_clients=num_zones, topology=topo, wal=True,
        leader_options=WPaxosLeaderOptions(
            **(ADMISSION if admission else {}),
            **(leader_knobs or {})),
        client_options=WPaxosClientOptions(
            resend_period_s=RESEND_PERIOD_S,
            adaptive_timeouts=False,
            retry_budget=RETRY_BUDGET,
            reject_backoff=REJECT_BACKOFF),
        seed=seed)
    return sim, topo


def _write_lane(name: str, client, keys: list, sessions: tuple,
                workload: OpenLoopWorkload) -> TrafficLane:
    def issue(client, pseudonym, payload, key_index, callback,
              _keys=keys):
        client.write(pseudonym, payload, callback,
                     key=_keys[key_index % len(_keys)])

    return TrafficLane(name, client, workload, sessions, issue)


def _driver(sim, lanes, seed: int) -> GeoOverloadDriver:
    return GeoOverloadDriver(
        sim.transport, lanes, capacity_cmds_per_s=CAPACITY_CMDS_S,
        msg_cost_s=MSG_COST_S, dt=DT_S,
        slo_deadline_s=SLO_DEADLINE_S, seed=seed)


def _finish_wpaxos(sim, topo, driver, scale: Scale) -> list:
    """Heal every fault, settle, and run the safety oracle."""
    topo.heal_all()
    driver.settle(scale.settle_s)
    return _wpaxos_safety(sim, driver.acked)


def _recovery_s(driver, lane_index: int, t_repair: float):
    """Virtual seconds from ``t_repair`` to the first completion on
    ``lane_index`` at or after it; None if the lane never recovers."""
    times = [t0 + lat for t0, lat, _, li in driver.completions
             if li == lane_index and t0 + lat >= t_repair]
    return min(times) - t_repair if times else None


def _base_row(name: str, seed: int, scale: Scale, driver, transport,
              t_measure: float, t_end: float, refused_control: list,
              violations: list, t_wall: float) -> dict:
    stats = driver.stats(t_measure, t_end, t_end - t_measure)
    return {
        "scenario": name,
        "seed": seed,
        "scale": scale.name,
        "virtual_seconds": round(transport.now, 2),
        "wall_seconds": round(time.perf_counter() - t_wall, 1),
        "stats": stats,
        "safety": {
            "violations": violations,
            "acked_writes": len(driver.acked),
            "giveups": driver.giveups,
            "control_frames_refused": len(refused_control),
        },
        "history_sha256": history_digest(transport),
    }


def _quantiles(driver, lanes: set, lo: float, hi: float):
    """(p99, p999) of ADMITTED completion latencies over ``lanes``
    issued in [lo, hi) -- the population each scenario's latency
    ceilings gate (the lanes the fault should NOT have touched; the
    affected lane is gated by its own recovery/loudness clauses)."""
    lats = sorted(lat for t0, lat, first, li in driver.completions
                  if li in lanes and first and lo <= t0 < hi)
    if not lats:
        return None, None
    return (lats[min(len(lats) - 1, int(0.99 * len(lats)))],
            lats[min(len(lats) - 1, int(0.999 * len(lats)))])


def _common_clauses(row: dict, *, goodput_floor: float,
                    p99_s, p99_ceiling_s: float,
                    p999_s, p999_ceiling_s: float) -> dict:
    stats = row["stats"]
    safety = row["safety"]
    return {
        "goodput_floor": clause(stats["goodput_cmds_per_s"],
                                goodput_floor, "min"),
        "p99_admitted_ceiling_s": clause(p99_s, p99_ceiling_s),
        "p999_admitted_ceiling_s": clause(p999_s, p999_ceiling_s),
        "zero_acked_write_loss": clause(
            len(safety["violations"]), 0, "zero"),
        "control_plane_never_shed": clause(
            safety["control_frames_refused"], 0, "zero"),
        "no_silent_wedge": clause(stats["pending_after_settle"], 0,
                                  "zero"),
    }


def _seal(row: dict, clauses: dict) -> dict:
    row["slo"] = clauses
    row["gate_passed"] = all(c["passed"] for c in clauses.values())
    return row


# --- scenario 1: zone outage during the regional peak ------------------------


def scenario_zone_outage_peak(seed: int, scale: Scale) -> dict:
    """SIGKILL zone 0 (leader + acceptor row + replica) exactly at its
    diurnal maximum, dwell, relaunch the acceptors from their WALs
    (leader/replica restart amnesiac), and let client failover + the
    fresh-ballot steal discipline repair ownership -- under sustained
    global load, with admission holding the surviving zones' p99.

    paxchaos: the fault plan is a :mod:`frankenpaxos_tpu.faults`
    FaultSchedule compiled onto the sim backend -- the SAME schedule
    object the deployed twin (bench/deployed_twin.py) replays over
    real sockets, with both rows recording its digest."""
    from frankenpaxos_tpu.faults import (
        ScheduleRunner,
        SimWPaxosBackend,
        zone_outage_schedule,
    )

    t_wall = time.perf_counter()
    sim, topo = _wpaxos_cluster(seed, num_groups=6)
    period = scale.duration_s
    warm = 1.0
    lanes = []
    n = scale.sessions_per_lane
    for z in range(3):
        keys = _keys_for_zone(sim.config, z, 24)
        # Zone 0 carries the diurnal swing; the other regions run
        # flat -- the "regional peak" shape. The phase shifts the
        # ramp by the warm-up so the maximum lands EXACTLY at
        # t_kill = warm + period/4 (the scenario's contract).
        workload = OpenLoopWorkload(
            rate=scale.per_zone_rate, zipf_s=1.1, num_keys=len(keys),
            diurnal_amplitude=0.8 if z == 0 else 0.0,
            diurnal_period_s=period, diurnal_phase_s=-warm)
        lanes.append(_write_lane(f"zone-{z}", sim.clients[z], keys,
                                 (z * n, (z + 1) * n), workload))
    driver = _driver(sim, lanes, seed)
    refused = _arm_control_oracle(sim.transport)

    schedule = zone_outage_schedule(
        t_kill=warm + period / 4, dwell_s=scale.outage_dwell_s,
        zone=0, seed=seed)
    runner = ScheduleRunner(schedule, SimWPaxosBackend(sim, topo,
                                                       seed=seed))
    driver.run_for(warm)
    t_measure = sim.transport.now
    runner.drive(driver, t_measure + scale.duration_s)
    t_end = sim.transport.now
    assert runner.done()
    violations = _finish_wpaxos(sim, topo, driver, scale)

    row = _base_row("zone_outage_peak", seed, scale, driver,
                    sim.transport, t_measure, t_end, refused,
                    violations, t_wall)
    t_kill = next(t for t, e in runner.fired if e.kind == "crash_zone")
    t_restart = next(t for t, e in runner.fired
                     if e.kind == "restart_zone")
    recovery = _recovery_s(driver, 0, t_restart)
    row["events"] = {
        "fault_schedule_sha256": schedule.digest(),
        "t_kill": round(t_kill, 2),
        "t_restart": round(t_restart, 2),
        "outage_dwell_s": scale.outage_dwell_s,
        "recovery_after_relaunch_s":
            round(recovery, 3) if recovery is not None else None,
    }
    offered = 3 * scale.per_zone_rate  # diurnal mean == base rate
    # The latency ceilings gate the SURVIVING zones: admission holds
    # their p99 while a third of the fleet is down; the dead zone's
    # lane is gated by recovery + the goodput floor + loud-conclusion
    # clauses instead (its in-outage completions are outage-shaped by
    # definition).
    p99, p999 = _quantiles(driver, {1, 2}, t_measure, t_end)
    clauses = _common_clauses(
        row, goodput_floor=0.55 * offered,
        p99_s=p99, p99_ceiling_s=0.15,
        p999_s=p999, p999_ceiling_s=0.4)
    clauses["bounded_recovery_s"] = clause(recovery, 6.0)
    return _seal(row, clauses)


# --- scenario 2: cross-region partition with SLO-gated degradation -----------


def scenario_region_partition(seed: int, scale: Scale) -> dict:
    """Cut region r2 off from r0+r1 mid-window. The majority side
    keeps committing zone-locally within SLO (WPaxos Phase2 never
    leaves the home row); the minority's cross-region traffic sheds
    LOUDLY -- timeouts walk the bounded retry budget into
    RETRY_EXHAUSTED, steals block safely on the unreachable rows, the
    client-lane queue stays bounded -- and the heal completes the
    parked steals without duplicate execution."""
    t_wall = time.perf_counter()
    sim, topo = _wpaxos_cluster(seed, num_groups=6)
    n = scale.sessions_per_lane
    lanes = []
    for z in range(2):  # the majority side: zone-local traffic
        keys = _keys_for_zone(sim.config, z, 24)
        lanes.append(_write_lane(
            f"zone-{z}", sim.clients[z], keys, (z * n, (z + 1) * n),
            OpenLoopWorkload(rate=scale.per_zone_rate, zipf_s=1.1,
                             num_keys=len(keys))))
    # The minority lane drives objects homed ACROSS the partition
    # (zone 0): the cross-region dependence that must degrade loudly.
    keys0 = _keys_for_zone(sim.config, 0, 24)
    lanes.append(_write_lane(
        "zone-2-remote", sim.clients[2], keys0, (2 * n, 3 * n),
        OpenLoopWorkload(rate=scale.per_zone_rate, zipf_s=1.1,
                         num_keys=len(keys0))))
    driver = _driver(sim, lanes, seed)
    refused = _arm_control_oracle(sim.transport)

    warm = 1.0
    # 20% healthy / 60% partitioned / 20% healed: the partition must
    # outlive the client retry walk (~4s) so budgets visibly exhaust.
    # The cut/heal plan rides the paxchaos fault plane like every
    # other scenario's faults.
    from frankenpaxos_tpu.faults import (
        FaultSchedule,
        ScheduleRunner,
        SimWPaxosBackend,
    )

    t_cut = warm + 0.2 * scale.duration_s
    t_heal = warm + 0.8 * scale.duration_s
    schedule = FaultSchedule("region_partition", seed=seed)
    for other in ("r0", "r1"):
        schedule.add(t_cut, "partition", region_a="r2", region_b=other)
        schedule.add(t_heal, "heal", region_a="r2", region_b=other)
    runner = ScheduleRunner(schedule, SimWPaxosBackend(sim, topo,
                                                       seed=seed))
    driver.run_for(warm)
    t_measure = sim.transport.now
    runner.drive(driver, t_measure + scale.duration_s)
    t_end = sim.transport.now
    assert runner.done()
    violations = _finish_wpaxos(sim, topo, driver, scale)

    row = _base_row("region_partition", seed, scale, driver,
                    sim.transport, t_measure, t_end, refused,
                    violations, t_wall)
    recovery = _recovery_s(driver, 2, t_heal)
    # Majority-side admitted p99 measured over the PARTITION window
    # only -- the clause is "the majority never noticed".
    majority = [lat for t0, lat, first, li in driver.completions
                if li < 2 and first and t_cut <= t0 < t_heal]
    majority.sort()
    majority_p99 = (majority[int(0.99 * (len(majority) - 1))]
                    if majority else None)
    row["events"] = {
        "fault_schedule_sha256": schedule.digest(),
        "t_cut": round(t_cut, 2),
        "t_heal": round(t_heal, 2),
        "minority_giveups": driver.giveups,
        "recovery_after_heal_s":
            round(recovery, 3) if recovery is not None else None,
    }
    offered_majority = 2 * scale.per_zone_rate
    # Ceilings gate the MAJORITY side up to the heal (post-heal the
    # minority's parked steal completes and ownership legitimately
    # migrates -- zone 0's lane then pays the WAN to the new owner,
    # which is routing policy, not an SLO violation).
    p99, p999 = _quantiles(driver, {0, 1}, t_measure, t_heal)
    clauses = _common_clauses(
        row, goodput_floor=0.75 * offered_majority,
        p99_s=p99, p99_ceiling_s=0.1,
        p999_s=p999, p999_ceiling_s=0.3)
    clauses["majority_p99_during_partition_s"] = clause(
        majority_p99, 0.1)
    # Loud, bounded degradation: the minority concluded un-servable
    # ops explicitly (bounded-retry exhaustion), and queues never
    # grew silently.
    clauses["minority_sheds_loudly"] = clause(
        driver.giveups, 1, "min")
    clauses["queue_depth_bounded"] = clause(
        driver.max_queue_depth, 80 * scale.per_zone_rate)
    clauses["bounded_recovery_s"] = clause(recovery, 6.0)
    return _seal(row, clauses)


# --- scenario 3: follow-the-sun ----------------------------------------------


def scenario_follow_the_sun(seed: int, scale: Scale) -> dict:
    """One diurnal day split across three regions: each zone's lane
    runs the same ramp phase-shifted a third of a period, and the
    REAL adaptive placement policy (paxchaos: per-group request-origin
    EWMA on the owning leader, dominance + hysteresis + min-dwell)
    steals the shared "sun" object groups to whichever region is
    hottest -- no deterministic controller feeding it the answer.
    WPaxos's locality argument as a gated scenario: the hot region's
    commits are zone-local (sub-WAN-RTT p50) for the bulk of its
    shift, with the sun chased by measured traffic alone."""
    t_wall = time.perf_counter()
    sim, topo = _wpaxos_cluster(seed, num_groups=6,
                                leader_knobs=PLACEMENT)
    period = scale.duration_s
    warm = 1.0
    # The sun keys: objects every region serves in its shift
    # (initially homed in zone 0; the controller re-homes them).
    sun_keys = _keys_for_zone(sim.config, 0, 24)
    sun_groups = sorted({sim.config.group_of_key(k) for k in sun_keys})
    n = scale.sessions_per_lane
    lanes = []
    for z in range(3):
        # Zone z's shift peaks at t = warm + (z + 0.5) * period / 3:
        # sin peaks when (t + phase) = period/4 (mod period). `warm`
        # appears here because measurement windows are computed from
        # t_measure = warm -- the phases must track it.
        phase = period / 4 - (warm + (z + 0.5) * period / 3)
        # Uniform across the sun keys (skew is hot_contention's job):
        # a Zipf tail would starve the minority sun group below the
        # placement policy's min-samples floor and strand it on the
        # wrong side of the planet for a whole shift.
        lanes.append(_write_lane(
            f"zone-{z}", sim.clients[z], sun_keys,
            (z * n, (z + 1) * n),
            OpenLoopWorkload(rate=scale.per_zone_rate,
                             num_keys=len(sun_keys),
                             diurnal_amplitude=0.9,
                             diurnal_period_s=period,
                             diurnal_phase_s=phase)))
    driver = _driver(sim, lanes, seed)
    refused = _arm_control_oracle(sim.transport)

    driver.run_for(warm)
    t_measure = sim.transport.now
    driver.run_for(period)
    t_end = sim.transport.now
    handoffs = [h for leader in sim.leaders
                for h in leader.placement_handoffs]
    violations = _finish_wpaxos(sim, topo, driver, scale)

    row = _base_row("follow_the_sun", seed, scale, driver,
                    sim.transport, t_measure, t_end, refused,
                    violations, t_wall)
    # Per-shift hot-lane locality: admitted completions of zone z's
    # lane issued in the second half of z's shift (the first half
    # absorbs the steal + client rerouting).
    wan = topo.wan_rtt()
    shift_p50 = {}
    for z in range(3):
        lo = t_measure + (z + 0.5) * period / 3
        hi = t_measure + (z + 1) * period / 3
        lats = sorted(lat for t0, lat, first, li in driver.completions
                      if li == z and first and lo <= t0 < hi)
        shift_p50[f"zone-{z}"] = (
            round(lats[len(lats) // 2], 4) if lats else None)
    row["events"] = {
        "sun_groups": sun_groups,
        "placement_handoffs": len(handoffs),
        "handoff_log": handoffs[:24],
        "hot_shift_p50_s": shift_p50,
        "wan_rtt_s": wan,
    }
    offered = 3 * scale.per_zone_rate  # phase-shifted ramps sum flat
    # Every lane here is sometimes-hot and sometimes-remote (there is
    # no untouched lane to gate tightly): the latency ceilings bind
    # the whole population to the serving deadline -- migration
    # windows may queue remote traffic, but never silently past SLO
    # scale (the goodput floor holds the in-SLO mass up).
    p99, p999 = _quantiles(driver, {0, 1, 2}, t_measure, t_end)
    clauses = _common_clauses(
        row, goodput_floor=0.6 * offered,
        p99_s=p99, p99_ceiling_s=SLO_DEADLINE_S,
        p999_s=p999, p999_ceiling_s=2 * SLO_DEADLINE_S)
    worst = (None if any(v is None for v in shift_p50.values())
             else max(shift_p50.values()))
    clauses["hot_region_p50_below_quarter_wan_rtt"] = clause(
        worst, 0.25 * wan)
    # The measured-traffic policy actually chased the sun (each
    # later shift needs a hand-off into its zone), and the hysteresis
    # + min-dwell bound the churn: roughly one hand-off per sun group
    # per shift boundary, with slack for EWMA crossings at the
    # boundaries themselves -- a policy without hysteresis/dwell
    # livelocks into dozens (the PR 13 boomerang).
    clauses["placement_follows_the_sun"] = clause(
        len(handoffs), 2 * len(sun_groups), "min")
    clauses["placement_handoffs_bounded"] = clause(
        len(handoffs), 4 * len(sun_groups))
    return _seal(row, clauses)


# --- scenario 4: Zipf hot objects contended from two continents --------------


def scenario_hot_contention(seed: int, scale: Scale) -> dict:
    """Zones 0 and 2 (two continents) contend for one Zipf-hot object
    set under the REAL adaptive placement policy (paxchaos) -- no
    fixed-cadence controller tugging groups on a metronome. Continent
    0 hammers the hot keys from the start; continent 2's demand ramps
    from silence to 2x over the window. The policy must (a) move the
    hot groups to continent 0 once it dominates, (b) move them to
    continent 2 when IT comes to dominate, and (c) do nothing else:
    hysteresis + min-dwell keep the near-balanced crossover from
    ping-ponging ownership (the PR 13 boomerang, now structurally
    bounded), while the PR 9 nacked-steal backoff keeps each completed
    steal ~1 WAN RTT. Zone 1 serves cold objects in disjoint groups
    and must never notice."""
    t_wall = time.perf_counter()
    sim, topo = _wpaxos_cluster(seed, num_groups=9,
                                leader_knobs=PLACEMENT)
    # Hot objects live in two zone-1-homed groups; cold traffic uses
    # zone 1's OTHER groups, so the two interfere only through shared
    # infrastructure (leader event loops, acceptor rows) -- exactly
    # what the "cold objects unaffected" clause measures.
    zone1_groups = [g for g in range(9)
                    if sim.config.initial_home[g] == 1]
    hot_groups = zone1_groups[:2]
    hot_keys = []
    i = 0
    while len(hot_keys) < 16:
        key = b"hot-%d" % i
        if sim.config.group_of_key(key) in hot_groups:
            hot_keys.append(key)
        i += 1
    cold_keys = _keys_for_zone(sim.config, 1, 24,
                               exclude=tuple(hot_groups))
    n = scale.sessions_per_lane
    warm = 1.0
    # Continent 2's ramp: a half-period diurnal starting at its trough
    # (rate ~0 at t_measure, 2x continent 0 at the end), so dominance
    # flips exactly once mid-window -- the shape that would have made
    # the old fixed-cadence duel thrash and must NOT move adaptive
    # ownership more than twice.
    ramp = OpenLoopWorkload(rate=scale.per_zone_rate, zipf_s=1.2,
                            num_keys=len(hot_keys),
                            diurnal_amplitude=1.0,
                            diurnal_period_s=2 * scale.duration_s,
                            diurnal_phase_s=(-warm
                                             - scale.duration_s / 2))
    lanes = [
        _write_lane("continent-0", sim.clients[0], hot_keys, (0, n),
                    OpenLoopWorkload(rate=scale.per_zone_rate,
                                     zipf_s=1.2,
                                     num_keys=len(hot_keys))),
        _write_lane("cold", sim.clients[1], cold_keys, (n, 2 * n),
                    OpenLoopWorkload(rate=scale.per_zone_rate,
                                     zipf_s=1.1,
                                     num_keys=len(cold_keys))),
        _write_lane("continent-2", sim.clients[2], hot_keys,
                    (2 * n, 3 * n), ramp),
    ]
    driver = _driver(sim, lanes, seed)
    refused = _arm_control_oracle(sim.transport)

    driver.run_for(warm)
    t_measure = sim.transport.now
    driver.run_for(scale.duration_s)
    t_end = sim.transport.now
    handoffs = [h for leader in sim.leaders
                for h in leader.placement_handoffs
                if h["group"] in hot_groups]
    violations = _finish_wpaxos(sim, topo, driver, scale)

    row = _base_row("hot_contention", seed, scale, driver,
                    sim.transport, t_measure, t_end, refused,
                    violations, t_wall)
    wan = topo.wan_rtt()
    events = [e for leader in sim.leaders
              for e in leader.steal_events
              if e["group"] in hot_groups and "active_s" in e]
    steal_latencies = sorted(e["active_s"] - e["started_s"]
                             for e in events)
    # The churn bound: bootstrap (zone 1 self-acquires its home
    # groups) + the two demand-driven migrations, per hot group, with
    # one spare for an EWMA crossing at the flip. A policy without
    # hysteresis/dwell re-creates the duel and blows through this.
    steal_bound = 4 * len(hot_groups)
    row["events"] = {
        "hot_groups": hot_groups,
        "completed_steals": len(events),
        "placement_handoffs": len(handoffs),
        "handoff_log": handoffs[:24],
        "steal_bound": steal_bound,
        "steal_p50_s": (round(steal_latencies[len(steal_latencies)
                                              // 2], 4)
                        if steal_latencies else None),
        "wan_rtt_s": wan,
    }
    offered = 3 * scale.per_zone_rate  # the ramp's window mean is 1x
    # The latency ceilings gate the COLD lane: hot-object contention
    # may not leak into disjoint groups through shared leaders/rows.
    p99, p999 = _quantiles(driver, {1}, t_measure, t_end)
    clauses = _common_clauses(
        row, goodput_floor=0.6 * offered,
        p99_s=p99, p99_ceiling_s=0.1,
        p999_s=p999, p999_ceiling_s=0.3)
    clauses["steal_ping_pong_bounded"] = clause(len(events),
                                                steal_bound)
    # The policy adapted at all (ownership followed demand across the
    # flip: at least one hand-off per hot group)...
    clauses["placement_adapts"] = clause(len(handoffs),
                                         len(hot_groups), "min")
    clauses["steal_p50_within_3_wan_rtt"] = clause(
        row["events"]["steal_p50_s"], 3 * wan)
    return _seal(row, clauses)


# --- scenario 5: cloud pathologies (fsync stalls) ----------------------------


def scenario_fsync_stalls(seed: int, scale: Scale) -> dict:
    """Deterministic periodic-window WAL fsync stalls on two of zone
    0's three acceptors (wal/faults.py, plan built by
    ``faults.fsync_stall_schedule`` -- the SAME schedule the deployed
    twin replays over real FileStorage with blocking sleeps). Each
    target's disk is slow for the first 0.15 s of its period; the two
    periods separate the two phenomena: acceptor 0 stalls often
    (every 0.8 s) but usually ALONE -- the row quorum masks those
    (commit = 2nd-fastest ack), so the common case never sees storage
    jitter; acceptor 1's period is a multiple (2.4 s), so each of its
    windows OVERLAPS one of acceptor 0's -- the only drains where a
    quorum must include a stalled fsync -- and exactly those reach
    the client tail: the "Paxos in the Cloud" p999 amplification,
    reproduced on schedule, with group commit + admission keeping it
    bounded. A fault-off arm (same seed) pins the amplification
    factor."""
    from frankenpaxos_tpu.faults import (
        fsync_stall_schedule,
        ScheduleRunner,
        SimWPaxosBackend,
    )

    rows = {}
    schedule = fsync_stall_schedule(zone=0, seed=seed)
    for arm in ("fault_off", "fault_on"):
        t_wall = time.perf_counter()
        sim, topo = _wpaxos_cluster(seed, num_groups=6)
        stall_log: dict = {}
        if arm == "fault_on":
            # The same schedule object the deployed twin replays:
            # storage faults arm at t=0 through the sim backend (the
            # FsyncStallStorage wrap + the virtual-time stall_sender
            # bridge).
            backend = SimWPaxosBackend(sim, topo, seed=seed)
            ScheduleRunner(schedule, backend).poll(0.0)
            stall_log = backend.stall_storages
        n = scale.sessions_per_lane
        lanes = []
        for z in range(3):
            keys = _keys_for_zone(sim.config, z, 24)
            lanes.append(_write_lane(
                f"zone-{z}", sim.clients[z], keys,
                (z * n, (z + 1) * n),
                OpenLoopWorkload(rate=scale.per_zone_rate,
                                 zipf_s=1.1, num_keys=len(keys))))
        driver = _driver(sim, lanes, seed)
        refused = _arm_control_oracle(sim.transport)
        warm = 1.0
        driver.run_for(warm)
        t_measure = sim.transport.now
        driver.run_for(scale.duration_s)
        t_end = sim.transport.now
        violations = _finish_wpaxos(sim, topo, driver, scale)
        row = _base_row(f"fsync_stalls/{arm}", seed, scale, driver,
                        sim.transport, t_measure, t_end, refused,
                        violations, t_wall)
        row["_completions"] = driver.completions
        row["events"] = {
            "fault_schedule_sha256": schedule.digest(),
            "stalls_injected": {a: {"count": len(s.stalls),
                                    "total_s": round(sum(s.stalls), 3)}
                                for a, s in stall_log.items()},
        }
        rows[arm] = row

    on, off = rows["fault_on"], rows["fault_off"]
    zone0_on = on["stats"]["lanes"]["zone-0"]
    zone0_off = off["stats"]["lanes"]["zone-0"]
    p999_on = zone0_on["p999_admitted_s"]
    p999_off = zone0_off["p999_admitted_s"]
    # Fraction of the faulted zone's admitted completions slower than
    # a stall could make a MASKED commit (2nd-fastest ack clean): if
    # single stalls leaked past the quorum this would sit at acceptor
    # 0's stall duty cycle (~3x the bound).
    zone0 = [lat for _, lat, first, li in on["_completions"]
             if li == 0 and first]
    affected = (sum(1 for lat in zone0 if lat > 0.04) / len(zone0)
                if zone0 else None)
    del on["_completions"], off["_completions"]
    on["events"]["fault_off_p999_s"] = p999_off
    on["events"]["zone0_affected_fraction"] = (
        round(affected, 4) if affected is not None else None)
    amplification = (round(p999_on / p999_off, 2)
                     if p999_on is not None and p999_off else None)
    on["events"]["p999_amplification"] = amplification
    offered = 3 * scale.per_zone_rate
    # The whole-population ceilings sit just above one stall WINDOW
    # (0.15s): the ~2% overlap-affected slice may pay up to a window,
    # never more -- an unmasked or compounding stall would blow
    # through both.
    clauses = _common_clauses(
        on, goodput_floor=0.8 * offered,
        p99_s=on["stats"]["p99_admitted_s"], p99_ceiling_s=0.2,
        p999_s=on["stats"]["p999_admitted_s"], p999_ceiling_s=0.3)
    # Quorum masking: acceptor 0 is inside a stall window ~19% of the
    # time (0.15s of every 0.8s period), but only overlap-affected
    # commits -- the deliberate ~5% -- are slow. If single stalls
    # leaked past the row quorum this would sit at the full duty
    # cycle, ~2.3x the bound.
    clauses["quorum_masks_single_stalls"] = clause(affected, 0.08)
    # And the pathology actually REPRODUCES: the overlap tail is an
    # order of magnitude over the clean arm's p999 (else the fault
    # hook silently stopped injecting).
    clauses["p999_amplified_vs_fault_off"] = clause(
        amplification, 3.0, "min")
    on["fault_off_row"] = {
        k: off[k] for k in ("stats", "safety", "history_sha256")}
    return _seal(on, clauses)


# --- the CRAQ serving tier (scenarios 6 + 7) ---------------------------------


class _MonotoneAuditState(dict):
    """A chain node's state machine, instrumented by the HARNESS (the
    protocol never pays for this): per-session audit keys (``w<id>``)
    carry monotone op counters, so any apply that moves one BACKWARD
    is a stale-value resurrection -- the transient not-exactly-once
    failure a post-hoc final-state check can never see. Dirty-lane
    keys (``r<k>``) are written concurrently by many sessions and
    have no per-key order to violate; they are not audited."""

    def __init__(self):
        super().__init__()
        self.regressions: list = []

    def __setitem__(self, key, value):
        if key.startswith("w"):
            old = self.get(key)
            if old is not None and \
                    int(old.split(".")[2]) > int(value.split(".")[2]):
                self.regressions.append((key, old, value))
        super().__setitem__(key, value)


def _craq_cluster(seed: int, scale: Scale, *,
                  read_rate_mult: float = 3.2, num_zones: int = 3):
    """One CRAQ chain node per zone + one pinned client per zone,
    with paxload admission on every node's client edge and the
    monotone-audit state machine armed (chaos scenarios read its
    regression log; the chaos-free row just sees an empty list)."""
    from frankenpaxos_tpu.protocols.craq import ChainNode, CraqClient, CraqConfig
    from frankenpaxos_tpu.geo import GeoSimTransport
    from frankenpaxos_tpu.runtime import FakeLogger, LogLevel
    from frankenpaxos_tpu.serve.admission import AdmissionOptions

    regions = {f"r{z}": [f"zone-{z}"] for z in range(num_zones)}
    topo = GeoTopology(regions, seed=seed)
    logger = FakeLogger(LogLevel.FATAL)
    transport = GeoSimTransport(topo, logger)
    config = CraqConfig(chain_node_addresses=tuple(
        f"chain-{z}" for z in range(num_zones)))
    # The per-node token bucket sits just above the steady per-zone
    # read rate, so Poisson bursts actually exercise the read path's
    # Rejected -> jittered-backoff -> retry discipline inside the
    # committed run (not only in unit tests).
    node_admission = AdmissionOptions(
        token_rate=read_rate_mult * scale.per_zone_rate,
        token_burst=25.0, inbox_capacity=512, inbox_policy="reject",
        retry_after_ms=100)
    nodes = []
    for z, address in enumerate(config.chain_node_addresses):
        topo.place(address, f"zone-{z}")
        node = ChainNode(address, transport, logger, config,
                         resend_period_s=0.5,
                         admission=node_admission)
        node.state_machine = _MonotoneAuditState()
        nodes.append(node)
    clients = []
    for z in range(num_zones):
        address = f"client-{z}"
        topo.place(address, f"zone-{z}")
        clients.append(CraqClient(
            address, transport, logger, config, resend_period_s=1.0,
            seed=seed + z, retry_budget=8, backoff=REJECT_BACKOFF,
            read_node=z))
    return topo, transport, config, nodes, clients


def _craq_lanes(scale: Scale, clients, *,
                read_rate_mult: float = 3.0) -> tuple:
    """The CRAQ serving lane set: zone-local read lanes, the
    acked-loss audit write lane (per-session keys), and a dirty write
    lane keeping a sliver of the read keyspace in flight so the
    apportioned-queries forward path is actually exercised."""
    read_keys = 256
    n = scale.sessions_per_lane
    lanes = []
    for z in range(len(clients)):
        def read_issue(client, pseudonym, payload, key_index,
                       callback):
            client.read(pseudonym, "r%d" % key_index, callback)

        lanes.append(TrafficLane(
            f"reads-zone-{z}", clients[z],
            OpenLoopWorkload(rate=read_rate_mult * scale.per_zone_rate,
                             zipf_s=1.1, num_keys=read_keys),
            (z * n, (z + 1) * n), read_issue, record_acked=False))

    def audit_write_issue(client, pseudonym, payload, key_index,
                          callback):
        client.write(pseudonym, "w%d" % pseudonym, payload.decode(),
                     lambda result=None: callback(result))

    def dirty_write_issue(client, pseudonym, payload, key_index,
                          callback):
        client.write(pseudonym, "r%d" % (key_index % read_keys),
                     payload.decode(),
                     lambda result=None: callback(result))

    lanes.append(TrafficLane(
        "writes-audit", clients[0],
        OpenLoopWorkload(rate=0.2 * scale.per_zone_rate,
                         num_keys=read_keys),
        (3 * n, 4 * n), audit_write_issue))
    lanes.append(TrafficLane(
        "writes-dirty", clients[1],
        OpenLoopWorkload(rate=0.15 * scale.per_zone_rate,
                         num_keys=read_keys),
        (4 * n, 5 * n), dirty_write_issue, record_acked=False))
    return lanes, read_keys


def _craq_audit(tail, acked) -> list:
    """Zero acked-write loss at the (current) tail: for every session
    that ever got an ack, the tail's committed value must be at least
    as new as the LAST ACKED write (chain seq + head dedup make
    per-session versions monotone) -- plus any monotonicity
    regressions the instrumented state machine recorded."""
    violations: list = []
    last_acked: dict[int, int] = {}
    for payload in acked:
        parts = payload.decode().split(".")
        session = int(parts[1][1:])
        op = int(parts[2])
        last_acked[session] = max(last_acked.get(session, -1), op)
    for session, op in sorted(last_acked.items()):
        value = tail.state_machine.get("w%d" % session)
        got = int(value.split(".")[2]) if value else -1
        if got < op:
            violations.append(
                f"acked write lost: session {session} acked op {op}, "
                f"tail has {value!r}")
    for key, old, new in tail.state_machine.regressions:
        violations.append(
            f"stale resurrection at tail: {key} went {old!r} -> "
            f"{new!r}")
    return violations


# --- scenario 6: geo read scaling (WPaxos writes + CRAQ reads) ---------------


def scenario_craq_read_scaling(seed: int, scale: Scale) -> dict:
    """The headline global-serving read path: a CRAQ chain with one
    node per zone serves ZONE-LOCAL reads under the same admission /
    client-lane / Rejected-backoff discipline as the write paths.
    Clean reads never leave the zone (p50/p99 local); only the dirty
    tail pays the apportioned-queries forward to the (WAN) tail node.
    An audit write lane with per-session keys carries the zero-
    acked-write-loss clause; a dirty write lane keeps a sliver of the
    read keyspace in flight so the forward path is actually
    exercised."""
    t_wall = time.perf_counter()
    topo, transport, config, nodes, clients = _craq_cluster(
        seed, scale, read_rate_mult=3.2)
    lanes, read_keys = _craq_lanes(scale, clients, read_rate_mult=3.0)

    driver = GeoOverloadDriver(
        transport, lanes, capacity_cmds_per_s=2 * CAPACITY_CMDS_S,
        msg_cost_s=MSG_COST_S, dt=DT_S,
        slo_deadline_s=SLO_DEADLINE_S, seed=seed)
    refused = _arm_control_oracle(transport)

    warm = 1.0
    driver.run_for(warm)
    t_measure = transport.now
    driver.run_for(scale.duration_s)
    t_end = transport.now
    driver.settle(scale.settle_s)

    violations = _craq_audit(nodes[-1], driver.acked)
    rejected = sum(
        sum(node.admission.rejected.values())
        for node in nodes if node.admission is not None)

    row = _base_row("craq_read_scaling", seed, scale, driver,
                    transport, t_measure, t_end, refused, violations,
                    t_wall)
    wan = topo.wan_rtt()
    row["events"] = {
        "wan_rtt_s": wan,
        "chain": [str(a) for a in config.chain_node_addresses],
        "admission_rejected": rejected,
        "client_giveups": driver.giveups,
    }
    offered = 3 * 3 * scale.per_zone_rate + 0.35 * scale.per_zone_rate
    # The ceilings gate the READ lanes: clean reads stay zone-local
    # (p99 well under a WAN round trip); only the dirty tail pays the
    # apportioned-queries forward to the (WAN) tail -- bounded by ~1
    # WAN RTT + chain service, not SLO collapse.
    p99, p999 = _quantiles(driver, {0, 1, 2}, t_measure, t_end)
    clauses = _common_clauses(
        row, goodput_floor=0.7 * offered,
        p99_s=p99, p99_ceiling_s=0.25 * wan,
        p999_s=p999, p999_ceiling_s=2 * wan)
    # Writes walk the whole chain: head -> mid -> tail is two
    # cross-region hops one way, plus the tail's cross-region reply
    # -- ~1.5 WAN RTTs end to end before jitter and in-order batch
    # queueing.
    wp99, _ = _quantiles(driver, {3, 4}, t_measure, t_end)
    clauses["chain_write_p99_s"] = clause(wp99, 2.5 * wan)
    return _seal(row, clauses)


# --- scenario 7: CRAQ chain reconfiguration under node kill ------------------


def scenario_craq_chain_reconfig(seed: int, scale: Scale) -> dict:
    """END OF THE CRAQ CHAOS EXEMPTION (paxchaos): the TAIL node --
    the one whose death puts acked writes at risk, because only
    predecessors' pending (dirty) versions still hold them -- is
    killed mid-run under full serving load, and after a detection
    dwell the chain re-links around it (``ChainReconfigure``): the new
    tail drains its dirty backlog (apply + reply + ack upstream), the
    version fence drops the dead era's in-flight frames, and pinned
    readers re-target on their own resend schedule. Gated on the same
    matrix clauses as everything else: ZERO acked writes lost (the
    dead tail acked them; the dirty handoff must re-materialize every
    one), exactly-once via the monotone audit state machine (a stale
    resurrection during the handoff would show as a backward apply),
    loud bounded conclusions, control plane never shed, bounded
    recovery for the orphaned read lane."""
    from frankenpaxos_tpu.faults import (
        craq_chain_kill_schedule,
        ScheduleRunner,
        SimCraqBackend,
    )

    t_wall = time.perf_counter()
    topo, transport, config, nodes, clients = _craq_cluster(
        seed, scale, read_rate_mult=1.8)
    lanes, _read_keys = _craq_lanes(scale, clients,
                                    read_rate_mult=1.5)
    driver = GeoOverloadDriver(
        transport, lanes, capacity_cmds_per_s=2 * CAPACITY_CMDS_S,
        msg_cost_s=MSG_COST_S, dt=DT_S,
        slo_deadline_s=SLO_DEADLINE_S, seed=seed)
    refused = _arm_control_oracle(transport)

    warm = 1.0
    t_kill = warm + 0.35 * scale.duration_s
    reconfigure_after = 0.5
    schedule = craq_chain_kill_schedule(
        t_kill=t_kill, node=len(nodes) - 1,
        reconfigure_after_s=reconfigure_after, seed=seed)
    backend = SimCraqBackend(transport, nodes, clients)
    runner = ScheduleRunner(schedule, backend)

    driver.run_for(warm)
    t_measure = transport.now
    runner.drive(driver, t_measure + scale.duration_s)
    t_end = transport.now
    assert runner.done()
    driver.settle(scale.settle_s)

    # The surviving tail after the re-link (node kill shortened the
    # chain by one).
    new_tail = nodes[len(backend.reconfigured_to) - 1]
    assert new_tail.is_tail and new_tail.address \
        == backend.reconfigured_to[-1]
    violations = _craq_audit(new_tail, driver.acked)
    # The dead tail's audit: anything IT acked must also survive at
    # the new tail -- same oracle, the acked set already spans the
    # whole run including pre-kill acks.
    rejected = sum(
        sum(node.admission.rejected.values())
        for node in nodes if node.admission is not None)

    row = _base_row("craq_chain_reconfig", seed, scale, driver,
                    transport, t_measure, t_end, refused, violations,
                    t_wall)
    t_repair = next(t for t, e in runner.fired if e.kind == "repair")
    # The orphaned lane: zone 2's readers were pinned to the killed
    # tail; recovery = repair -> their first completion (the clamped
    # re-target on their own resend schedule).
    orphan_lane = len(nodes) - 1
    recovery = _recovery_s(driver, orphan_lane, t_repair)
    wan = topo.wan_rtt()
    row["events"] = {
        "fault_schedule_sha256": schedule.digest(),
        "killed_node": str(nodes[-1].address),
        "t_kill": round(t_kill, 2),
        "t_repair": round(t_repair, 2),
        "surviving_chain": [str(a) for a in backend.reconfigured_to],
        "chain_version": new_tail.chain_version,
        "handoff_regressions": len(new_tail.state_machine.regressions),
        "admission_rejected": rejected,
        "client_giveups": driver.giveups,
        "recovery_after_repair_s":
            round(recovery, 3) if recovery is not None else None,
        "wan_rtt_s": wan,
    }
    offered = (3 * 1.5 + 0.35) * scale.per_zone_rate
    # Latency ceilings gate the UNAFFECTED zone-0/zone-1 read lanes
    # (their chain node survived; the orphaned lane is gated by its
    # own recovery clause); the goodput floor spans everything and
    # absorbs the outage+handoff dip.
    p99, p999 = _quantiles(driver, {0, 1}, t_measure, t_end)
    clauses = _common_clauses(
        row, goodput_floor=0.55 * offered,
        p99_s=p99, p99_ceiling_s=0.25 * wan,
        p999_s=p999, p999_ceiling_s=3 * wan)
    clauses["exactly_once_no_stale_resurrection"] = clause(
        len(new_tail.state_machine.regressions), 0, "zero")
    clauses["bounded_recovery_s"] = clause(recovery, 6.0)
    return _seal(row, clauses)


# --- the matrix --------------------------------------------------------------


SCENARIOS = (
    ("zone_outage_peak", scenario_zone_outage_peak),
    ("region_partition", scenario_region_partition),
    ("follow_the_sun", scenario_follow_the_sun),
    ("hot_contention", scenario_hot_contention),
    ("fsync_stalls", scenario_fsync_stalls),
    ("craq_read_scaling", scenario_craq_read_scaling),
    ("craq_chain_reconfig", scenario_craq_chain_reconfig),
)


def run_scenario(name: str, seed: int = 0,
                 scale: Scale = SMOKE) -> dict:
    for candidate, fn in SCENARIOS:
        if candidate == name:
            return fn(seed, scale)
    raise ValueError(f"unknown scenario {name!r}; "
                     f"known: {[n for n, _ in SCENARIOS]}")


def run_matrix(seed: int = 0, scale: Scale = FULL,
               only: str | None = None) -> dict:
    rows = []
    for name, fn in SCENARIOS:
        if only and only not in name:
            continue
        rows.append(fn(seed, scale))
    return {
        "seed": seed,
        "scale": scale.name,
        "model": {
            "capacity_cmds_per_s": CAPACITY_CMDS_S,
            "msg_cost_s": MSG_COST_S,
            "dt_s": DT_S,
            "slo_deadline_s": SLO_DEADLINE_S,
            "sessions_per_lane": scale.sessions_per_lane,
            "per_zone_rate": scale.per_zone_rate,
            "admission_knobs": ADMISSION,
            "client_retry_budget": RETRY_BUDGET,
        },
        "rows": rows,
        "gate_passed": all(r["gate_passed"] for r in rows),
    }
