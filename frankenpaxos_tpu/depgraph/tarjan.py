"""Tarjan-based dependency graph with interlaced eligibility.

Reference behavior: depgraph/TarjanDependencyGraph.scala:149-450.
Tarjan's SCC algorithm emits components in reverse topological order in
a single pass -- exactly the execution order a dependency graph needs --
and eligibility (all transitive deps committed) is computed during the
same pass: hitting an uncommitted dependency marks the whole stack
ineligible and unwinds immediately (TarjanDependencyGraph.scala:354-446).

This implementation is iterative (explicit frame stack): EPaxos logs
routinely hold dependency chains far deeper than Python's recursion
limit.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Hashable, Iterable, Optional, TypeVar

from frankenpaxos_tpu.depgraph.base import DependencyGraph

K = TypeVar("K", bound=Hashable)


@dataclasses.dataclass
class _Vertex:
    sequence_number: object
    dependencies: set


@dataclasses.dataclass
class _Meta:
    number: int
    low_link: int
    stack_index: int
    eligible: bool


class TarjanDependencyGraph(DependencyGraph[K]):
    def __init__(self, key_sort: Callable = None):
        self.vertices: dict[K, _Vertex] = {}
        self.executed: set[K] = set()
        self._key_sort = key_sort or (lambda k: k)

    # --- API --------------------------------------------------------------
    def commit(self, key: K, sequence_number, dependencies: Iterable[K]
               ) -> None:
        if key in self.executed or key in self.vertices:
            return  # already committed/executed (debug-warn in reference)
        self.vertices[key] = _Vertex(sequence_number, set(dependencies))

    def update_executed(self, keys: Iterable[K]) -> None:
        for key in keys:
            self.executed.add(key)
            self.vertices.pop(key, None)

    def execute_by_component(self, num_blockers: Optional[int] = None
                             ) -> tuple[list[list[K]], set[K]]:
        self._metadatas: dict[K, _Meta] = {}
        self._stack: list[K] = []
        components: list[list[K]] = []
        blockers: set[K] = set()
        for key in list(self.vertices):
            if key in self._metadatas:
                continue
            self._strong_connect(key, components, blockers)
            # An ineligible root leaves its whole path on the stack; clear
            # it (TarjanDependencyGraph.scala:326-332).
            if not self._metadatas[key].eligible:
                self._stack.clear()
            if num_blockers is not None and len(blockers) >= num_blockers:
                break
        # Returned components leave the graph permanently.
        for component in components:
            for key in component:
                self.executed.add(key)
                self.vertices.pop(key, None)
        return components, blockers

    @property
    def num_vertices(self) -> int:
        return len(self.vertices)

    # --- the interlaced Tarjan pass ---------------------------------------
    def _strong_connect(self, root: K, components: list[list[K]],
                        blockers: set[K]) -> None:
        vertices, md, stack = self.vertices, self._metadatas, self._stack

        # frame = [key, dependency iterator, aborted]
        frames: list[list] = []

        def enter(v: K) -> None:
            md[v] = _Meta(number=len(md), low_link=len(md),
                          stack_index=len(stack), eligible=True)
            stack.append(v)
            deps = vertices[v].dependencies - self.executed
            frames.append([v, iter(sorted(deps, key=self._key_sort)), False])

        enter(root)
        while frames:
            frame = frames[-1]
            v = frame[0]
            descended = False
            if not frame[2]:
                for w in frame[1]:
                    if w not in vertices:
                        # Uncommitted dependency: v (and the whole stack
                        # above) is ineligible; record the blocker.
                        md[v].eligible = False
                        blockers.add(w)
                        frame[2] = True
                        break
                    if w not in md:
                        enter(w)
                        descended = True
                        break
                    if not md[w].eligible:
                        md[v].eligible = False
                        frame[2] = True
                        break
                    if md[w].stack_index != -1:
                        # On-stack child: classic Tarjan lowlink update
                        # uses the child's *number*.
                        md[v].low_link = min(md[v].low_link, md[w].number)
                    # Off-stack eligible child: nothing to do.
                if descended:
                    continue
            # Frame finished (deps exhausted or aborted).
            frames.pop()
            if not frame[2] and md[v].low_link == md[v].number:
                # v roots its SCC: everything at/above its stack index.
                idx = md[v].stack_index
                component = stack[idx:]
                del stack[idx:]
                for w in component:
                    md[w].stack_index = -1
                component.sort(key=lambda k: (vertices[k].sequence_number,
                                              self._key_sort(k)))
                components.append(component)
            if frames:
                parent = frames[-1]
                p = parent[0]
                if not md[v].eligible:
                    md[p].eligible = False
                    parent[2] = True
                else:
                    md[p].low_link = min(md[p].low_link, md[v].low_link)
