"""A from-scratch dependency graph used as a test oracle.

The reference tests its fast Tarjan implementation against library-backed
ones (JgraphtDependencyGraph.scala:23, ScalaGraphDependencyGraph.scala:19;
depgraph/DependencyGraphTest.scala runs all implementations against each
other). This plays that role: recompute eligibility and Kosaraju-style
SCCs from scratch on every ``execute`` -- slow and obviously correct.
"""

from __future__ import annotations

from typing import Hashable, Iterable, Optional, TypeVar

from frankenpaxos_tpu.depgraph.base import DependencyGraph

K = TypeVar("K", bound=Hashable)


class NaiveDependencyGraph(DependencyGraph[K]):
    def __init__(self, key_sort=None):
        self.committed: dict[K, tuple[object, set]] = {}
        self.executed: set[K] = set()
        self._key_sort = key_sort or (lambda k: k)

    def commit(self, key, sequence_number, dependencies) -> None:
        if key in self.executed or key in self.committed:
            return
        self.committed[key] = (sequence_number, set(dependencies))

    def update_executed(self, keys: Iterable[K]) -> None:
        for key in keys:
            self.executed.add(key)
            self.committed.pop(key, None)

    @property
    def num_vertices(self) -> int:
        return len(self.committed)

    def _eligible_and_blockers(self) -> tuple[set[K], set[K]]:
        """Eligible = transitive closure stays within committed."""
        eligible: set[K] = set()
        blockers: set[K] = set()
        for start in self.committed:
            seen: set[K] = set()
            frontier = [start]
            ok = True
            while frontier:
                v = frontier.pop()
                if v in seen or v in self.executed:
                    continue
                seen.add(v)
                if v not in self.committed:
                    ok = False
                    blockers.add(v)
                    continue
                frontier.extend(self.committed[v][1])
            if ok:
                eligible.add(start)
        return eligible, blockers

    def execute_by_component(self, num_blockers: Optional[int] = None
                             ) -> tuple[list[list[K]], set[K]]:
        eligible, blockers = self._eligible_and_blockers()
        # Kosaraju on the eligible subgraph.
        graph = {v: [w for w in self.committed[v][1]
                     if w in eligible and w not in self.executed]
                 for v in eligible}
        order: list[K] = []
        seen: set[K] = set()
        for start in graph:
            if start in seen:
                continue
            # Iterative DFS with postorder.
            stack = [(start, iter(graph[start]))]
            seen.add(start)
            while stack:
                v, it = stack[-1]
                advanced = False
                for w in it:
                    if w not in seen:
                        seen.add(w)
                        stack.append((w, iter(graph[w])))
                        advanced = True
                        break
                if not advanced:
                    order.append(v)
                    stack.pop()
        reverse: dict[K, list[K]] = {v: [] for v in graph}
        for v, ws in graph.items():
            for w in ws:
                reverse[w].append(v)
        assigned: set[K] = set()
        components: list[list[K]] = []
        for v in reversed(order):
            if v in assigned:
                continue
            component = []
            frontier = [v]
            while frontier:
                u = frontier.pop()
                if u in assigned:
                    continue
                assigned.add(u)
                component.append(u)
                frontier.extend(reverse[u])
            component.sort(key=lambda k: (self.committed[k][0],
                                          self._key_sort(k)))
            components.append(component)
        # Kosaraju (on reversed postorder over the forward graph) yields
        # components in topological order; execution wants reverse.
        components.reverse()
        for component in components:
            for key in component:
                self.executed.add(key)
                self.committed.pop(key, None)
        return components, blockers
