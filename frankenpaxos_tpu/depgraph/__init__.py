"""Dependency graphs for generalized consensus (EPaxos/BPaxos executors).

Reference behavior: depgraph/ (DependencyGraph.scala:127-193 abstract API;
TarjanDependencyGraph.scala:149+ the fast one; Jgrapht/ScalaGraph
library-backed variants used as oracles in tests). Commit command
vertices with dependency sets; emit strongly-connected components in
reverse topological order for execution.
"""

from frankenpaxos_tpu.depgraph.base import DependencyGraph
from frankenpaxos_tpu.depgraph.incremental import (
    IncrementalTarjanDependencyGraph,
)
from frankenpaxos_tpu.depgraph.naive import NaiveDependencyGraph
from frankenpaxos_tpu.depgraph.tarjan import TarjanDependencyGraph
from frankenpaxos_tpu.depgraph.zigzag import ZigzagTarjanDependencyGraph

def make_dependency_graph(name: str, *, num_leaders: int = None,
                          make=None, key_sort=None) -> DependencyGraph:
    """Select an implementation by name, the way the reference's role
    mains do (epaxos/ReplicaMain.scala:12-14,127 hardwires Zigzag;
    DependencyGraphTest runs every impl). ``num_leaders`` and ``make``
    are required by "zigzag", whose keys must decompose into dense
    per-leader (leader_index, id) vertex ids."""
    if name == "tarjan":
        return TarjanDependencyGraph(key_sort)
    if name == "incremental":
        return IncrementalTarjanDependencyGraph(key_sort)
    if name == "naive":
        return NaiveDependencyGraph(key_sort)
    if name == "zigzag":
        if num_leaders is None:
            raise ValueError("zigzag needs num_leaders")
        return ZigzagTarjanDependencyGraph(
            num_leaders, make=make or (lambda l, i: (l, i)),
            key_sort=key_sort)
    raise ValueError(f"unknown dependency graph {name!r}")


__all__ = [
    "DependencyGraph",
    "IncrementalTarjanDependencyGraph",
    "NaiveDependencyGraph",
    "TarjanDependencyGraph",
    "ZigzagTarjanDependencyGraph",
    "make_dependency_graph",
]
