"""Dependency graphs for generalized consensus (EPaxos/BPaxos executors).

Reference behavior: depgraph/ (DependencyGraph.scala:127-193 abstract API;
TarjanDependencyGraph.scala:149+ the fast one; Jgrapht/ScalaGraph
library-backed variants used as oracles in tests). Commit command
vertices with dependency sets; emit strongly-connected components in
reverse topological order for execution.
"""

from frankenpaxos_tpu.depgraph.base import DependencyGraph
from frankenpaxos_tpu.depgraph.naive import NaiveDependencyGraph
from frankenpaxos_tpu.depgraph.tarjan import TarjanDependencyGraph

__all__ = [
    "DependencyGraph",
    "NaiveDependencyGraph",
    "TarjanDependencyGraph",
]
