"""DependencyGraph contract.

Reference behavior: depgraph/DependencyGraph.scala:127-193. A vertex is
*eligible* for execution iff it and everything transitively reachable
from it is committed. ``execute`` returns eligible vertices in an order
compatible with the graph: reverse topological order of strongly
connected components, with components internally ordered by
(sequence number, key) for determinism. Once returned, a vertex is never
returned again. ``blockers`` are uncommitted keys found blocking
eligibility -- the protocol recovers those (EPaxos explicit prepare /
BPaxos vertex recovery).
"""

from __future__ import annotations

import abc
from typing import Generic, Hashable, Iterable, Optional, TypeVar

K = TypeVar("K", bound=Hashable)


class DependencyGraph(abc.ABC, Generic[K]):
    @abc.abstractmethod
    def commit(self, key: K, sequence_number, dependencies: Iterable[K]
               ) -> None:
        """Add a committed vertex; does not execute anything."""

    def execute(self, num_blockers: Optional[int] = None
                ) -> tuple[list[K], set[K]]:
        components, blockers = self.execute_by_component(num_blockers)
        return [key for component in components for key in component], blockers

    def append_execute(self, num_blockers: Optional[int],
                       executables: list[K], blockers: set[K]) -> None:
        new_executables, new_blockers = self.execute(num_blockers)
        executables.extend(new_executables)
        blockers.update(new_blockers)

    @abc.abstractmethod
    def execute_by_component(self, num_blockers: Optional[int] = None
                             ) -> tuple[list[list[K]], set[K]]:
        ...

    @abc.abstractmethod
    def update_executed(self, keys: Iterable[K]) -> None:
        """Inform the graph that ``keys`` were executed out-of-band
        (e.g. learned via snapshot)."""

    @property
    @abc.abstractmethod
    def num_vertices(self) -> int:
        ...
