"""Zigzag Tarjan dependency graph.

Reference behavior: depgraph/ZigzagTarjanDependencyGraph.scala:135+.
Specialized to BPaxos/EPaxos-style vertex ids -- keys that decompose
into a ``(leader_index, id)`` pair with dense per-leader id spaces.
Vertices live in one BufferMap per leader column and the traversal
*zigzags* across columns in executed-watermark order
(ZigzagTarjanDependencyGraph.scala:330-348): try to execute the vertex
at each column's watermark, round-robin; a column whose watermark vertex
is missing (reported as a blocker) or ineligible drops out of the
rotation; the pass ends when no column can advance. Visiting vertices in
id order makes the log prefix dense behind the watermarks, so garbage
collection is a pure BufferMap prefix drop, run every
``gc_every_n_commands`` executed commands
(ZigzagTarjanDependencyGraph.scala:225-231).

The SCC walk itself is the same interlaced-eligibility Tarjan pass as
TarjanDependencyGraph (strongConnect,
ZigzagTarjanDependencyGraph.scala:408-538), with its single-vertex fast
path. Implemented iteratively: EPaxos dependency chains routinely exceed
Python's recursion limit.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Hashable, Iterable, Optional, TypeVar

from frankenpaxos_tpu.depgraph.base import DependencyGraph
from frankenpaxos_tpu.utils.buffer_map import BufferMap
from frankenpaxos_tpu.utils.topk import TUPLE_VERTEX_LIKE, VertexIdLike

K = TypeVar("K", bound=Hashable)


@dataclasses.dataclass
class _Vertex:
    sequence_number: object
    dependencies: set


@dataclasses.dataclass
class _Meta:
    number: int
    low_link: int
    stack_index: int
    eligible: bool


class ZigzagTarjanDependencyGraph(DependencyGraph[K]):
    def __init__(self, num_leaders: int,
                 like: VertexIdLike = TUPLE_VERTEX_LIKE,
                 make: Callable[[int, int], K] = lambda l, i: (l, i),
                 grow_size: int = 1000,
                 gc_every_n_commands: int = 1000,
                 key_sort: Callable = None):
        self.num_leaders = num_leaders
        self.like = like
        self.make = make
        self.gc_every_n_commands = gc_every_n_commands
        self.vertices: list[BufferMap[_Vertex]] = [
            BufferMap(grow_size) for _ in range(num_leaders)]
        self.executed_watermark = [0] * num_leaders
        self.executed: set[K] = set()
        self._key_sort = key_sort or (lambda k: k)
        self._num_vertices = 0
        self._num_commands_since_gc = 0

    # --- API --------------------------------------------------------------
    def commit(self, key: K, sequence_number, dependencies: Iterable[K]
               ) -> None:
        leader, vid = self.like.leader_index(key), self.like.id(key)
        if self._is_executed(key) or self.vertices[leader].contains(vid):
            return
        self.vertices[leader].put(vid, _Vertex(sequence_number,
                                               set(dependencies)))
        self._num_vertices += 1

    def update_executed(self, keys: Iterable[K]) -> None:
        for key in keys:
            if self._is_executed(key):
                continue
            self.executed.add(key)
            if self._get(key) is not None:
                self._num_vertices -= 1
        # GC accounting happens when execute()'s watermark skip passes
        # these keys -- counting here too would double-count.

    def execute_by_component(self, num_blockers: Optional[int] = None
                             ) -> tuple[list[list[K]], set[K]]:
        metadatas: dict[K, _Meta] = {}
        stack: list[K] = []
        components: list[list[K]] = []
        blockers: set[K] = set()

        columns = list(range(self.num_leaders))
        index = 0
        # GC is a prefix drop at the watermarks, so the GC trigger counts
        # watermark *advances*: every vertex passes under its column's
        # watermark exactly once -- via the skip loop (executed
        # out-of-band or as a cross-column component member) or via the
        # post-execute advance below -- never both.
        advances = 0
        while columns:
            leader = columns[index]
            # Skip ids executed out-of-band (executed.leaderIndexWatermark
            # in the reference's watermark advance,
            # ZigzagTarjanDependencyGraph.scala:334-337).
            while self.make(leader, self.executed_watermark[leader]) \
                    in self.executed:
                self.executed.discard(
                    self.make(leader, self.executed_watermark[leader]))
                self.executed_watermark[leader] += 1
                advances += 1
            vid = self.executed_watermark[leader]
            if self._execute_key(leader, vid, metadatas, stack,
                                 components, blockers):
                self.executed_watermark[leader] += 1
                advances += 1
                index += 1
            else:
                columns.pop(index)
            if index >= len(columns):
                index = 0
            # num_blockers is deliberately NOT an early exit: every column
            # must get its turn or eligible vertices in later columns
            # starve (the reference's zigzag executeImpl ignores
            # numBlockers for the same reason,
            # ZigzagTarjanDependencyGraph.scala:330-348).

        self._num_vertices -= sum(len(c) for c in components)
        self._num_commands_since_gc += advances
        if self._num_commands_since_gc >= self.gc_every_n_commands:
            self._garbage_collect()
            self._num_commands_since_gc = 0
        return components, blockers

    @property
    def num_vertices(self) -> int:
        return self._num_vertices

    # --- internals --------------------------------------------------------
    def _is_executed(self, key: K) -> bool:
        """Ids below a column's executed watermark are provably executed;
        the ``executed`` set only carries the sparse above-watermark tail
        (the reference's watermark-compressed VertexIdPrefixSet)."""
        return (self.like.id(key)
                < self.executed_watermark[self.like.leader_index(key)]
                or key in self.executed)

    def _get(self, key: K) -> Optional[_Vertex]:
        return self.vertices[self.like.leader_index(key)].get(
            self.like.id(key))

    def _garbage_collect(self) -> None:
        for leader in range(self.num_leaders):
            self.vertices[leader].garbage_collect(
                self.executed_watermark[leader])
        self.executed = {
            k for k in self.executed
            if self.like.id(k)
            >= self.executed_watermark[self.like.leader_index(k)]}

    def _execute_key(self, leader: int, vid: int, metadatas, stack,
                     components, blockers) -> bool:
        key = self.make(leader, vid)
        if self._is_executed(key):
            return True
        if self.vertices[leader].get(vid) is None:
            # Only a genuine hole -- a missing id with committed vertices
            # above it in the same column -- is a blocker worth
            # recovering. A merely-drained column would otherwise hand
            # EPaxos/BPaxos a never-proposed instance to recover,
            # noop-committing in a perpetual cycle on an idle cluster.
            # (Deviation from the reference, which reports the tail
            # unconditionally, ZigzagTarjanDependencyGraph.scala:361-364;
            # dependency-driven blockers still surface via
            # _strong_connect.)
            if vid <= self.vertices[leader].largest_key:
                blockers.add(key)
            return False
        meta = metadatas.get(key)
        if meta is not None:
            return meta.eligible
        eligible = self._strong_connect(key, metadatas, stack, components,
                                        blockers)
        if not eligible:
            # Everything left on the stack is ineligible too
            # (ZigzagTarjanDependencyGraph.scala:384-394).
            for w in stack:
                metadatas[w].eligible = False
                metadatas[w].stack_index = -1
            stack.clear()
        return eligible

    def _strong_connect(self, root: K, md, stack, components,
                        blockers) -> bool:
        """Iterative interlaced-eligibility Tarjan from ``root``; returns
        the root's eligibility. Components formed along the way are
        appended to ``components`` and marked executed immediately;
        BufferMap pruning is deferred to GC."""
        frames: list[list] = []

        def enter(v: K) -> None:
            meta = _Meta(number=len(md), low_link=len(md),
                         stack_index=len(stack), eligible=True)
            md[v] = meta
            stack.append(v)
            deps = [d for d in sorted(self._get(v).dependencies,
                                      key=self._key_sort)
                    if not self._is_executed(d)]
            frames.append([v, iter(deps), False])

        enter(root)
        while frames:
            frame = frames[-1]
            v = frame[0]
            meta = md[v]
            descended = False
            if not frame[2]:
                for w in frame[1]:
                    if self._is_executed(w):
                        continue
                    if self._get(w) is None:
                        meta.eligible = False
                        meta.stack_index = -1
                        blockers.add(w)
                        frame[2] = True
                        break
                    wmeta = md.get(w)
                    if wmeta is None:
                        enter(w)
                        descended = True
                        break
                    if not wmeta.eligible:
                        meta.eligible = False
                        meta.stack_index = -1
                        frame[2] = True
                        break
                    if wmeta.stack_index != -1:
                        meta.low_link = min(meta.low_link, wmeta.number)
                if descended:
                    continue
            frames.pop()
            if not frame[2] and meta.low_link == meta.number:
                component = stack[meta.stack_index:]
                del stack[meta.stack_index:]
                for w in component:
                    md[w].stack_index = -1
                    self.executed.add(w)
                component.sort(
                    key=lambda k: (self._get(k).sequence_number,
                                   self._key_sort(k)))
                components.append(component)
            if frames:
                parent = frames[-1]
                pmeta = md[parent[0]]
                if not meta.eligible:
                    pmeta.eligible = False
                    pmeta.stack_index = -1
                    parent[2] = True
                else:
                    pmeta.low_link = min(pmeta.low_link, meta.low_link)
        return md[root].eligible
