"""Incremental Tarjan dependency graph.

Reference behavior: depgraph/IncrementalTarjanDependencyGraph.scala:29+.
Unlike TarjanDependencyGraph -- which reruns Tarjan's algorithm from
scratch on every ``execute`` -- the incremental variant keeps the
traversal state (metadata, SCC stack, explicit call stack) across calls.
When the walk reaches an uncommitted dependency it *pauses*: the call
stack is left in place, the uncommitted key is reported as the (single)
blocker, and the next ``execute`` resumes exactly where the walk
stopped. It never redoes work, at the cost of sometimes delaying the
execution of vertices that are already eligible (neither strictly better
nor worse than the from-scratch variant; see the reference's comment at
IncrementalTarjanDependencyGraph.scala:10-27).

Implementation notes mirroring the reference:
- ``commit`` prunes executed dependencies and orders committed
  dependencies before uncommitted ones so a pass runs as far as possible
  before pausing (IncrementalTarjanDependencyGraph.scala:96-108).
- ``execute`` returns at most one blocker per call.
- ``update_executed`` is only supported between passes (the reference
  leaves it unimplemented outright,
  IncrementalTarjanDependencyGraph.scala:111-116).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Hashable, Iterable, Optional, TypeVar

from frankenpaxos_tpu.depgraph.base import DependencyGraph

K = TypeVar("K", bound=Hashable)

_PAUSED = "paused"
_SUCCESS = "success"


@dataclasses.dataclass
class _Vertex:
    sequence_number: object
    dependencies: list  # committed-first at commit time


@dataclasses.dataclass
class _Meta:
    number: int
    low_link: int
    on_stack: bool
    current_dependency: int


class IncrementalTarjanDependencyGraph(DependencyGraph[K]):
    def __init__(self, key_sort: Callable = None):
        self.vertices: dict[K, _Vertex] = {}
        self.executed: set[K] = set()
        self._key_sort = key_sort or (lambda k: k)
        # Pass state persisted across execute() calls.
        self._metadatas: dict[K, _Meta] = {}
        self._stack: list[K] = []
        self._callstack: list[K] = []
        self._executables: list[list[K]] = []
        self._blocker: Optional[K] = None

    # --- API --------------------------------------------------------------
    def commit(self, key: K, sequence_number, dependencies: Iterable[K]
               ) -> None:
        if key in self.vertices or key in self.executed:
            return
        deps = set(dependencies) - self.executed
        committed = [d for d in deps if d in self.vertices]
        uncommitted = [d for d in deps if d not in self.vertices]
        order = self._key_sort
        self.vertices[key] = _Vertex(
            sequence_number,
            sorted(committed, key=order) + sorted(uncommitted, key=order))

    def update_executed(self, keys: Iterable[K]) -> None:
        if self._callstack:
            raise NotImplementedError(
                "update_executed mid-pass is unsupported (the reference "
                "leaves it unimplemented entirely, "
                "IncrementalTarjanDependencyGraph.scala:111-116)")
        for key in keys:
            self.executed.add(key)
            self.vertices.pop(key, None)

    def execute_by_component(self, num_blockers: Optional[int] = None
                             ) -> tuple[list[list[K]], set[K]]:
        # Resume a paused walk first.
        if self._callstack:
            if self._strong_connect() == _PAUSED:
                return self._collect_executables(), self._take_blocker()

        for key in list(self.vertices):
            if key not in self._metadatas:
                self._callstack.append(key)
                if self._strong_connect() == _PAUSED:
                    return self._collect_executables(), self._take_blocker()

        # Completed a full pass: safe to start numbering afresh next time.
        assert not self._callstack
        assert not self._stack
        self._metadatas.clear()
        return self._collect_executables(), self._take_blocker()

    @property
    def num_vertices(self) -> int:
        return len(self.vertices)

    # --- internals --------------------------------------------------------
    def _take_blocker(self) -> set[K]:
        blocker = {self._blocker} if self._blocker is not None else set()
        self._blocker = None
        return blocker

    def _collect_executables(self) -> list[list[K]]:
        for component in self._executables:
            for key in component:
                self.vertices.pop(key, None)
                self.executed.add(key)
        out = self._executables
        self._executables = []
        return out

    def _strong_connect(self) -> str:
        """Run the manually-stacked Tarjan walk until the call stack
        drains (_SUCCESS) or an uncommitted dependency pauses it
        (_PAUSED). Mirrors IncrementalTarjanDependencyGraph.scala:172-266."""
        md, stack, callstack = self._metadatas, self._stack, self._callstack
        while callstack:
            v = callstack[-1]
            meta = md.get(v)
            if meta is None:
                meta = _Meta(number=len(md), low_link=len(md),
                             on_stack=True, current_dependency=0)
                md[v] = meta
                stack.append(v)

            deps = self.vertices[v].dependencies
            descended = False
            while meta.current_dependency < len(deps):
                w = deps[meta.current_dependency]
                if w in self.executed:
                    pass  # executed mid-pass: satisfied.
                elif w not in self.vertices:
                    self._blocker = w
                    return _PAUSED
                elif w not in md:
                    callstack.append(w)
                    descended = True
                    break
                elif md[w].on_stack:
                    meta.low_link = min(meta.low_link, md[w].number)
                meta.current_dependency += 1
            if descended:
                continue

            # All dependencies processed: maybe root an SCC, then unwind.
            if meta.low_link == meta.number:
                component: list[K] = []
                while True:
                    w = stack.pop()
                    md[w].on_stack = False
                    component.append(w)
                    if w == v:
                        break
                component.sort(
                    key=lambda k: (self.vertices[k].sequence_number,
                                   self._key_sort(k)))
                self._executables.append(component)
            callstack.pop()
            if callstack:
                parent = md[callstack[-1]]
                parent.low_link = min(parent.low_link, meta.low_link)
                parent.current_dependency += 1
        return _SUCCESS
