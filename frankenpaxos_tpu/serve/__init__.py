"""paxload: million-session overload robustness (docs/SERVING.md).

The serving tier the ROADMAP "million-client serving tier" item asks
for, in four pieces:

  * ``messages``/``wire`` -- the ``Rejected`` wire reply (extended tag
    page, tag 132): the explicit drop/reject signal that replaces
    silent timeout storms when the edge sheds.
  * ``lanes`` -- frame-layer priority lanes: client-request frames are
    classified by their leading wire tag (one byte inspected, no
    decode), so bounded inboxes and CoDel shedding only ever touch the
    client lane -- Phase1/reconfig/heartbeat/vote traffic is NEVER
    shed.
  * ``admission`` -- the server-side robustness layer: token-bucket +
    in-flight-slot admission (the slot budget is the run pipeline's
    proposed-minus-chosen watermark span, so admission is
    drain-granular), CoDel-style queue-delay shedding at the drain
    boundary, and the bounded-inbox drop/reject policies both
    transports enforce.
  * ``backoff`` -- client-side jittered exponential backoff with retry
    budgets that distinguish ``Rejected`` (back off, same leader) from
    timeout (failover/resend); ``loadgen`` -- the vectorized load tier
    that simulates 1M+ client sessions as SoA numpy arrays (open-loop
    Poisson/heavy-tailed arrivals, Zipf key skew, diurnal ramps)
    without a Python object per session.

"The Performance of Paxos in the Cloud" (PAPERS.md) documents the
overload pathologies this tier exists to fix: at offered loads past
capacity the system must degrade by SHEDDING (bounded queues, explicit
rejects, preserved goodput) -- never by OOM or timeout amplification.
The SLO gate lives in ``bench/overload_lt.py`` ->
``bench_results/overload_lt.json``.
"""

# Codec registration (tag 132 on the extended page) is an import side
# effect, like every other wire module.
from frankenpaxos_tpu.serve import wire  # noqa: F401
from frankenpaxos_tpu.serve.admission import (
    AdmissionController,
    AdmissionOptions,
    reject_replies_for,
)
from frankenpaxos_tpu.serve.backoff import Backoff, RETRY_EXHAUSTED
from frankenpaxos_tpu.serve.lanes import frame_lane, LANE_CLIENT, LANE_CONTROL
from frankenpaxos_tpu.serve.messages import Rejected

__all__ = [
    "AdmissionController",
    "AdmissionOptions",
    "Backoff",
    "LANE_CLIENT",
    "LANE_CONTROL",
    "RETRY_EXHAUSTED",
    "Rejected",
    "frame_lane",
    "reject_replies_for",
]
