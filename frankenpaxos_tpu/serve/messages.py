"""paxload messages.

``Rejected`` is the explicit admission-control reply: a role that
cannot admit a client request says so IMMEDIATELY instead of letting
the request age out in a queue and present as a timeout. Clients treat
the two signals differently (backoff.py): Rejected -> the leader is
alive but saturated, back off with jitter and retry the SAME leader;
timeout -> the leader may be gone, fail over (the existing resend /
leader-discovery path).

One Rejected can cover a whole coalesced ``ClientRequestArray`` -- the
entries tuple mirrors ClientReplyArray's shape (the client address
rides the wire header; per-entry addresses would be dead bytes).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Rejected:
    """Admission refused for these commands of ONE client.

    ``entries`` are (client_pseudonym, client_id) pairs;
    ``retry_after_ms`` is the server's backoff hint (0 = client
    default). ``reason`` is a small enum: 1 tokens, 2 inflight,
    3 queue, 4 codel."""

    entries: tuple  # tuple[(int, int), ...]
    retry_after_ms: int = 0
    reason: int = 0


#: Rejection reason codes (wire-stable; string names for metrics).
REASON_TOKENS = 1
REASON_INFLIGHT = 2
REASON_QUEUE = 3
REASON_CODEL = 4

REASON_NAMES = {
    0: "unspecified",
    REASON_TOKENS: "tokens",
    REASON_INFLIGHT: "inflight",
    REASON_QUEUE: "queue",
    REASON_CODEL: "codel",
}
