"""Client-side retry discipline (paxload): jittered exponential
backoff with retry budgets.

The contract (docs/SERVING.md):

  * ``Rejected`` means the leader is ALIVE but saturated -> back off
    (jittered exponential, honoring the server's ``retry_after_ms``
    hint as a floor) and retry the SAME leader. Re-sending immediately
    would feed the congestion the server just shed.
  * Timeout means the leader may be GONE -> the existing
    resend/failover path (re-send, leader discovery on NotLeader) at
    the configured resend period.
  * Both consume the per-operation RETRY BUDGET when one is set; an
    exhausted budget completes the operation with the
    :data:`RETRY_EXHAUSTED` sentinel instead of retrying forever --
    every request ends in an ack, an explicit rejection give-up, or a
    bounded-retry exhaustion, never a silent wedge.

A budget of 0 (the default) preserves the pre-paxload behavior:
unlimited resends, no backoff -- sims and benches that predate the
serving tier are untouched.
"""

from __future__ import annotations

import dataclasses
import random


class _RetryExhausted:
    """Sentinel delivered to a write/read callback when the retry
    budget runs out (compare by identity: ``result is
    RETRY_EXHAUSTED``). Falsy so naive truthiness checks treat it as
    'no result'."""

    __slots__ = ()

    def __bool__(self) -> bool:
        return False

    def __repr__(self) -> str:
        return "RETRY_EXHAUSTED"


RETRY_EXHAUSTED = _RetryExhausted()


@dataclasses.dataclass(frozen=True)
class Backoff:
    """Jittered exponential backoff schedule: attempt k (0-based)
    sleeps ``initial * multiplier**k``, capped at ``max_s``, with
    uniform jitter of ±``jitter`` fraction. Full-jitter-style spread
    keeps a synchronized burst of rejected clients from re-arriving as
    a synchronized retry storm."""

    initial_s: float = 0.05
    max_s: float = 5.0
    multiplier: float = 2.0
    jitter: float = 0.5

    def delay_s(self, attempt: int, rng: random.Random,
                floor_s: float = 0.0) -> float:
        base = min(self.max_s, self.initial_s * self.multiplier ** attempt)
        lo = base * (1.0 - self.jitter)
        hi = base * (1.0 + self.jitter)
        return max(floor_s, lo + (hi - lo) * rng.random())
