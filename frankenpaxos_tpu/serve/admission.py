"""Server-side admission control, queue-delay shedding, and bounded
inboxes (paxload -- docs/SERVING.md).

Three mechanisms, composed per role by :class:`AdmissionController`:

  * **Token bucket** -- a rate/burst cap on admitted client commands
    (the blunt front door: an aggregate-rate promise independent of
    where the commands would land in the pipeline).
  * **In-flight slot budget** -- at most ``inflight_limit`` commands
    between proposal and the chosen watermark. The LEADER feeds the
    live span (``next_slot - chosen_watermark``) via
    :meth:`AdmissionController.set_inflight` on every drain and every
    watermark advance, so admission is drain-granular: capacity frees
    the moment a drain's quorums land, not when replies trickle out.
  * **CoDel-style queue-delay shedding** -- the drain boundary is the
    queue: when a drain batch's sojourn (first delivery -> on_drain)
    stays above ``codel_target_s`` for a full ``codel_interval_s``,
    the controller enters shed mode and client-lane arrivals are
    rejected until a drain comes in under target again. Like CoDel,
    the signal is DELAY, not depth -- a deep-but-fast queue is healthy,
    a shallow-but-stalled one is not.

Rejection is explicit: :func:`reject_replies_for` turns the refused
client request into ``Rejected`` wire replies (serve/messages.py) so
clients back off instead of re-sending into the congestion
(backoff.py). Priority lanes (lanes.py) keep every mechanism away from
control-plane traffic by construction.

The whole layer is pay-for-what-you-use: a role without a controller
costs the transports one attribute load + ``is None`` test per frame
(the paxtrace hook discipline; gated <3% in
bench_results/overload_lt.json admission_overhead).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

from frankenpaxos_tpu.serve.messages import (
    REASON_CODEL,
    REASON_INFLIGHT,
    REASON_NAMES,
    REASON_QUEUE,
    REASON_TOKENS,
    Rejected,
)


@dataclasses.dataclass(frozen=True)
class AdmissionOptions:
    """Per-role admission knobs. Every mechanism is off at 0 (the
    default options object admits everything and arms nothing), so a
    role constructed without explicit limits behaves exactly as before
    paxload."""

    # Token bucket: admitted client commands per second / burst depth.
    token_rate: float = 0.0          # 0 disables the bucket
    token_burst: float = 0.0         # 0 -> defaults to token_rate
    # In-flight slot budget (proposed - chosen watermark span).
    inflight_limit: int = 0          # 0 disables
    # Bounded client-lane inbox (transports enforce; see
    # SimTransport.set_inbox_policy / TcpTransport delivery).
    inbox_capacity: int = 0          # 0 = unbounded
    inbox_policy: str = "reject"     # "reject" (newest) | "drop" (oldest)
    # CoDel-style drain-sojourn shedding.
    codel_target_s: float = 0.0      # 0 disables
    codel_interval_s: float = 0.1
    # Backoff hint stamped on Rejected replies (0 = client default).
    retry_after_ms: int = 0

    def any_enabled(self) -> bool:
        return bool(self.token_rate or self.inflight_limit
                    or self.inbox_capacity or self.codel_target_s)


def options_from_flat(obj) -> Optional[AdmissionOptions]:
    """Build AdmissionOptions from an options dataclass carrying the
    flat ``admission_*`` fields (flat so the CLI's ``--options.*``
    overrides coerce them by declared type). None when nothing is
    armed -- the caller then skips building a controller entirely."""
    options = AdmissionOptions(
        token_rate=obj.admission_token_rate,
        token_burst=obj.admission_token_burst,
        inflight_limit=obj.admission_inflight_limit,
        inbox_capacity=obj.admission_inbox_capacity,
        inbox_policy=obj.admission_inbox_policy,
        codel_target_s=obj.admission_codel_target_s,
        codel_interval_s=obj.admission_codel_interval_s,
        retry_after_ms=obj.admission_retry_after_ms)
    return options if options.any_enabled() else None


class TokenBucket:
    """A monotonic-clock token bucket; ``clock`` is injectable so sims
    stay deterministic (the overload driver feeds virtual time)."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float]):
        self.rate = rate
        self.burst = burst or rate
        self.clock = clock
        self.tokens = self.burst
        self._last = clock()

    def take(self, n: float = 1.0) -> bool:
        now = self.clock()
        if now > self._last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._last) * self.rate)
            self._last = now
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


class AdmissionController:
    """One per admitting role (leader/proxy/replica), attached as
    ``actor.admission`` so both transports find it with one attribute
    load. All methods run on the role's event loop -- no locks."""

    def __init__(self, options: AdmissionOptions, role: str = "",
                 clock: Callable[[], float] = time.monotonic,
                 metrics=None):
        self.options = options
        self.role = role
        self.clock = clock
        self.metrics = metrics  # obs.RuntimeMetrics or None
        self.bucket = (TokenBucket(options.token_rate,
                                   options.token_burst, clock)
                       if options.token_rate else None)
        self.inflight = 0
        # CoDel state: sojourn-above-target bookkeeping.
        self._above_since: Optional[float] = None
        self.shedding = False
        self._last_feed = clock()
        # Counters (also mirrored to metrics when attached): cheap
        # plain ints readable by benches/tests without a collector.
        self.admitted = 0
        self.rejected: dict[str, int] = {}
        self.last_reason = 0

    # --- the admit decision ------------------------------------------------
    def admit(self, n: int = 1) -> bool:
        """Admit ``n`` client commands? False sets ``last_reason``.
        Order: shed mode (congestion beats rate), slot budget, bucket."""
        if self.shed_active():
            return self._reject(REASON_CODEL, n)
        limit = self.options.inflight_limit
        if limit and self.inflight + n > limit:
            return self._reject(REASON_INFLIGHT, n)
        if self.bucket is not None and not self.bucket.take(n):
            return self._reject(REASON_TOKENS, n)
        self.admitted += n
        if limit:
            self.inflight += n
        if self.metrics is not None:
            self.metrics.admission_admitted(n)
            if limit:
                self.metrics.admission_inflight(self.inflight)
        self.last_reason = 0
        return True

    def admit_up_to(self, n: int) -> int:
        """Admit as many of ``n`` client commands as the limits allow
        (0..n). A coalesced drain's array degrades gracefully: the
        prefix that fits the slot budget/bucket is served, the suffix
        is rejected -- all-or-nothing would collapse goodput the
        moment arrays outgrow the remaining budget. Rejection
        accounting for the suffix (with the binding constraint as the
        reason) happens here; ``last_reason`` reflects it."""
        if n <= 0:
            return 0
        if self.shed_active():
            self._reject(REASON_CODEL, n)
            return 0
        k = n
        reason = 0
        limit = self.options.inflight_limit
        if limit:
            avail = max(0, limit - self.inflight)
            if avail < k:
                k = avail
                reason = REASON_INFLIGHT
        if self.bucket is not None and k and not self.bucket.take(k):
            have = int(self.bucket.tokens)
            took = min(k, have)
            if took and self.bucket.take(took):
                pass
            else:
                took = 0
            if took < k:
                reason = REASON_TOKENS
            k = took
        if n - k:
            self._reject(reason or REASON_INFLIGHT, n - k)
        if k:
            self.admitted += k
            if limit:
                self.inflight += k
            if self.metrics is not None:
                self.metrics.admission_admitted(k)
                if limit:
                    self.metrics.admission_inflight(self.inflight)
            if k == n:
                self.last_reason = 0
        return k

    def _reject(self, reason: int, n: int) -> bool:
        self.last_reason = reason
        name = REASON_NAMES[reason]
        self.rejected[name] = self.rejected.get(name, 0) + n
        if self.metrics is not None:
            self.metrics.admission_rejected(name, n)
        return False

    # --- in-flight budget (watermark-tied) ---------------------------------
    def set_inflight(self, span: int) -> None:
        """The leader's live proposed-minus-chosen span: called on
        drains and ChosenWatermark advances, making the budget
        drain-granular (capacity frees when quorums land)."""
        self.inflight = max(0, span)
        if self.metrics is not None:
            self.metrics.admission_inflight(self.inflight)

    def release(self, n: int = 1) -> None:
        self.set_inflight(self.inflight - n)

    # --- CoDel-style drain-sojourn shedding --------------------------------
    def note_drain_delay(self, delay_s: float) -> None:
        """Feed one drain batch's sojourn (first delivery ->
        on_drain). Above target for a full interval -> shed mode;
        one under-target drain exits it (queues drain fast once
        arrivals stop, so recovery should too)."""
        target = self.options.codel_target_s
        if not target:
            return
        now = self.clock()
        self._last_feed = now
        if delay_s < target:
            self._above_since = None
            self.shedding = False
            return
        if self._above_since is None:
            self._above_since = now
        elif now - self._above_since >= self.options.codel_interval_s:
            self.shedding = True

    def shed_active(self) -> bool:
        """Is shed mode binding right now? Shed mode self-expires one
        CoDel interval after the last drain-sojourn observation:
        shedding every client frame pre-delivery also stops the drains
        that would report the under-target sojourn which exits shed
        mode, so without the expiry an actor whose inbound traffic is
        purely client-lane (a replica serving reads in a write-free
        period) latches shedding forever -- while the queue it was
        shedding for has long since emptied."""
        if not self.shedding:
            return False
        if (self.clock() - self._last_feed
                >= self.options.codel_interval_s):
            self.shedding = False
            self._above_since = None
        return self.shedding

    # --- bounded-inbox policy (transports call these) ----------------------
    def inbox_full(self, depth: int) -> bool:
        cap = self.options.inbox_capacity
        return bool(cap) and depth >= cap

    def note_inbox_depth(self, depth: int) -> None:
        if self.metrics is not None:
            self.metrics.admission_queue_depth(depth)

    def note_shed(self, policy: str, n: int = 1) -> None:
        name = f"shed_{policy}"
        self.rejected[name] = self.rejected.get(name, 0) + n
        if self.metrics is not None:
            self.metrics.admission_shed(policy, n)

    def retry_after_ms(self) -> int:
        return self.options.retry_after_ms


def reject_replies_for(message, retry_after_ms: int = 0,
                       reason: int = REASON_QUEUE) -> list:
    """Turn a refused client request into explicit ``Rejected``
    replies: [(client_address, Rejected)]. Handles the three shared
    request shapes (multipaxos + mencius); anything else (reads --
    which are rejected at role level where the command id is in hand)
    gets no wire reply here and falls back to client timeout."""
    name = type(message).__name__
    if name == "ClientRequest":
        cid = message.command.command_id
        return [(cid.client_address, Rejected(
            entries=((cid.client_pseudonym, cid.client_id),),
            retry_after_ms=retry_after_ms, reason=reason))]
    if name == "ClientRequestArray":
        # All commands in one array come from ONE client by
        # construction (the client stages its own writes).
        entries = tuple(
            (c.command_id.client_pseudonym, c.command_id.client_id)
            for c in message.commands)
        if not entries:
            return []
        return [(message.commands[0].command_id.client_address,
                 Rejected(entries=entries,
                          retry_after_ms=retry_after_ms, reason=reason))]
    if name == "IngestRun":
        # paxingest: a disseminator's run descriptor -- entries are
        # one-command batches spanning clients; prefer the zero-decode
        # column route, fall back to decoding (refusal is cold).
        from frankenpaxos_tpu.ingest.columns import value_view

        view = value_view(message.values)
        if view is not None:
            return view.reject_entries(0, retry_after_ms, reason)
        per_client: dict = {}
        for value in message.values:
            for command in getattr(value, "commands", ()):
                cid = command.command_id
                per_client.setdefault(cid.client_address, []).append(
                    (cid.client_pseudonym, cid.client_id))
        return [(address, Rejected(entries=tuple(entries),
                                   retry_after_ms=retry_after_ms,
                                   reason=reason))
                for address, entries in per_client.items()]
    if name == "ClientRequestBatch":
        # A batcher's batch spans clients: group entries per client.
        per_client: dict = {}
        for command in message.batch.commands:
            cid = command.command_id
            per_client.setdefault(cid.client_address, []).append(
                (cid.client_pseudonym, cid.client_id))
        return [(address, Rejected(entries=tuple(entries),
                                   retry_after_ms=retry_after_ms,
                                   reason=reason))
                for address, entries in per_client.items()]
    return []
