"""Fixed-layout codec for ``Rejected`` (extended tag page, tag 132).

Follows the repo codec conventions (reconfig/wire.py is the extended
page's style reference): little-endian fixed-width structs, hostile
count validation inside decode so the registry-wide corrupt-frame fuzz
(tests/test_wire_codecs.py) can hold it to the ValueError containment
contract.
"""

from __future__ import annotations

import struct

from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec
from frankenpaxos_tpu.serve.messages import Rejected

_HDR = struct.Struct("<iib")  # count, retry_after_ms, reason
_I64I64 = struct.Struct("<qq")

#: Per-frame entry-count sanity bound: a hostile count must not size an
#: allocation. A drain's coalesced array tops out far below this.
_MAX_ENTRIES = 1 << 20


class RejectedCodec(MessageCodec):
    message_type = Rejected
    tag = 132

    def encode(self, out, message):
        out += _HDR.pack(len(message.entries), message.retry_after_ms,
                         message.reason)
        for pseudonym, client_id in message.entries:
            out += _I64I64.pack(pseudonym, client_id)

    def decode(self, buf, at):
        n, retry_after_ms, reason = _HDR.unpack_from(buf, at)
        at += _HDR.size
        if not 0 <= n <= _MAX_ENTRIES:
            raise ValueError(f"malformed Rejected: count {n}")
        if at + 16 * n > len(buf):
            raise ValueError("truncated Rejected entries")
        entries = tuple(_I64I64.unpack_from(buf, at + 16 * i)
                        for i in range(n))
        return Rejected(entries=entries, retry_after_ms=retry_after_ms,
                        reason=reason), at + 16 * n


register_codec(RejectedCodec())
