"""The vectorized million-session load tier (paxload).

Simulates 1M+ client SESSIONS as SoA numpy arrays -- per-session state
is one byte + two floats in vectorized columns, never a Python object
-- while the bounded set of IN-FLIGHT operations rides the real client
actor (so the wire path, coalescing, Rejected handling, and backoff
under test are the production code paths). Arrivals come from the
shared :class:`~frankenpaxos_tpu.bench.workload.OpenLoopWorkload`
(open-loop Poisson / Pareto-burst processes, Zipf key skew, diurnal
ramps) -- the SAME generator the deployed driver uses
(bench/client_main.py --open_loop), so sim and deployed arms mean the
same thing by "10x offered load".

:class:`SimOverloadDriver` adds the virtual-time service model that
makes overload meaningful on SimTransport: the cluster gets a CPU
budget of one virtual second per virtual second, each delivered
message costs ``msg_cost_s`` and each completed command
``1/capacity_cmds_per_s``; offered load beyond capacity therefore
builds REAL queues (in the transport buffer) with REAL queueing delay
(in virtual seconds), deterministically -- seeds fully reproduce every
curve in bench_results/overload_lt.json. Timers fire on virtual
deadlines (delay_s from each timer), so client resends and backoff
behave as deployed.
"""

from __future__ import annotations

import numpy as np

from frankenpaxos_tpu.serve.backoff import RETRY_EXHAUSTED

IDLE, PENDING = 0, 1


class SessionArrays:
    """SoA state for ``n`` sessions: one uint8 + two float64 columns
    (25 MB at n=1M), vectorized arrival sampling against them."""

    def __init__(self, n: int):
        self.n = n
        self.state = np.zeros(n, dtype=np.uint8)
        self.issue_time = np.zeros(n, dtype=np.float64)
        self.ops_issued = np.zeros(n, dtype=np.int32)
        # Did the CURRENT op ever get a Rejected? Cleared at issue;
        # separates admitted-on-arrival completions (the gate's
        # "admitted-request p99") from backoff-retried ones whose
        # latency is dominated by client-side backoff sleeps.
        self.rejected_once = np.zeros(n, dtype=np.uint8)

    @property
    def pending(self) -> int:
        return int(np.count_nonzero(self.state == PENDING))

    def touched(self) -> int:
        """Distinct sessions that ever issued (the active working set
        a window this short actually exercises out of the n)."""
        return int(np.count_nonzero(self.ops_issued))


class SimOverloadDriver:
    """Drive one open-loop arm against a SimTransport cluster under
    the virtual-time service model. ``sim`` is a multipaxos harness
    object (tests/protocols/multipaxos_harness.make_multipaxos) whose
    clients[0] is the coalescing gateway client."""

    def __init__(self, sim, workload, *, num_sessions: int = 1_000_000,
                 capacity_cmds_per_s: float = 400.0,
                 msg_cost_s: float = 0.0002, dt: float = 0.02,
                 slo_deadline_s: float = 1.0, seed: int = 0,
                 payload_bytes: int = 8):
        self.sim = sim
        self.workload = workload
        self.sessions = SessionArrays(num_sessions)
        self.capacity = capacity_cmds_per_s
        self.cmd_cost = 1.0 / capacity_cmds_per_s
        self.msg_cost = msg_cost_s
        self.dt = dt
        self.slo_deadline_s = slo_deadline_s
        self.payload_bytes = payload_bytes
        self.np_rng = np.random.default_rng(seed)
        self.now = 0.0
        self.budget = 0.0
        # Outcome accounting. Completions are (issue_t, latency_s,
        # admitted_first_try); giveups are explicit RETRY_EXHAUSTED
        # conclusions.
        self.completions: list[tuple[float, float, bool]] = []
        self.giveups = 0
        self.suppressed = 0
        self.issued = 0
        self.max_queue_depth = 0
        #: timer id -> (virtual deadline, SimTimer.starts generation at
        #: stamp time). The generation detects a stop+restart between
        #: ticks (clients reuse one resend timer per pseudonym): a
        #: restarted timer gets a FRESH deadline, not the old op's.
        self._timer_deadlines: dict[int, tuple[float, int]] = {}
        self._bind_virtual_clocks()
        self._hook_rejections()

    # --- virtual time plumbing ---------------------------------------------
    def _bind_virtual_clocks(self) -> None:
        """Point every admission controller's clock (token-bucket
        refill, CoDel interval) at the driver's virtual clock so the
        arm is deterministic and rate limits mean virtual rates."""
        clock = lambda: self.now  # noqa: E731

        for actor in self.sim.transport.actors.values():
            admission = actor.admission
            if admission is not None:
                admission.clock = clock
                if admission.bucket is not None:
                    admission.bucket.clock = clock
                    admission.bucket._last = 0.0

    def _hook_rejections(self) -> None:
        """Mark sessions whose current op got a ``Rejected`` (wrapping
        the client's handler): their completion latency is dominated
        by client-side backoff sleeps, so the SLO gate's
        "admitted-request p99" excludes them (they still count for
        goodput when they finish inside the deadline, and for the
        giveup accounting when they exhaust the budget)."""
        sessions = self.sessions
        for client in self.sim.clients:
            original = client._handle_rejected

            def wrapped(*args, _original=original):
                rejected = args[-1]
                for pseudonym, _client_id in rejected.entries:
                    if pseudonym < sessions.n:
                        sessions.rejected_once[pseudonym] = 1
                return _original(*args)

            client._handle_rejected = wrapped

    def _pump_timers(self) -> None:
        """Fire running sim timers on virtual deadlines: a timer first
        seen running at t fires once now >= t + delay_s (resend and
        backoff discipline in virtual time)."""
        transport = self.sim.transport
        running = {t.id: t for t in transport.running_timers()}
        for tid, timer in running.items():
            rec = self._timer_deadlines.get(tid)
            if rec is None or rec[1] != timer.starts:
                self._timer_deadlines[tid] = (self.now + timer.delay_s,
                                              timer.starts)
        stale = [tid for tid in self._timer_deadlines
                 if tid not in running]
        for tid in stale:
            del self._timer_deadlines[tid]
        due = sorted((d, tid)
                     for tid, (d, _) in self._timer_deadlines.items()
                     if d <= self.now)
        for _, tid in due:
            del self._timer_deadlines[tid]
            transport.trigger_timer(tid)

    # --- the tick loop -----------------------------------------------------
    def _issue_arrivals(self) -> None:
        sessions = self.sessions
        k = self.workload.arrival_count(self.np_rng, self.now, self.dt)
        if k <= 0:
            return
        sids = self.np_rng.integers(0, sessions.n, k)
        keys = self.workload.sample_keys(self.np_rng, k)
        client = self.sim.clients[0]
        for s, key in zip(sids.tolist(), keys.tolist()):
            if sessions.state[s] != IDLE:
                # Open-loop thinning: the session's previous op is
                # still pending (rare at 1M sessions); counted, not
                # queued client-side -- client-side queues are the
                # unbounded-latency pathology this tier exists to
                # remove.
                self.suppressed += 1
                continue
            sessions.state[s] = PENDING
            sessions.issue_time[s] = self.now
            sessions.rejected_once[s] = 0
            sessions.ops_issued[s] += 1
            payload = b"k%d.s%d.%d" % (key, s, sessions.ops_issued[s])
            client.write(s, payload, self._completion_callback(s))
            self.issued += 1
        client.flush_writes()

    def _completion_callback(self, s: int):
        sessions = self.sessions

        def done(result) -> None:
            sessions.state[s] = IDLE
            if result is RETRY_EXHAUSTED:
                self.giveups += 1
            else:
                issued_at = float(sessions.issue_time[s])
                # Completion lands somewhere inside the current tick;
                # crediting the tick's END makes latency >= dt (a
                # same-tick completion is "one service quantum", not
                # zero) and keeps percentiles honest at tick
                # granularity.
                self.completions.append(
                    (issued_at, self.now + self.dt - issued_at,
                     not sessions.rejected_once[s]))

        return done

    def _deliver_budgeted(self) -> None:
        """Spend the tick's CPU budget delivering messages in
        coalesced waves: ``msg_cost_s`` per delivery plus
        ``1/capacity`` per command completion. Whatever the budget
        cannot cover stays queued -- THE queue overload builds."""
        transport = self.sim.transport
        while self.budget > 0 and transport.messages:
            wave = transport.messages[:4096]
            touched: list = []
            seen: set = set()
            for message in wave:
                if self.budget <= 0:
                    break
                # Only genuine completions cost server capacity; a
                # giveup (RETRY_EXHAUSTED concluded inside a Rejected
                # delivery) is client-local bookkeeping -- charging it
                # cmd_cost would make SHEDDING as expensive as serving
                # and spiral the budget into debt exactly when the
                # edge is doing its job.
                before = len(self.completions)
                actor = transport._deliver(message)
                after = len(self.completions)
                self.budget -= self.msg_cost \
                    + (after - before) * self.cmd_cost
                if actor is not None and id(actor) not in seen:
                    seen.add(id(actor))
                    touched.append(actor)
            for actor in touched:
                transport._drain(actor)

    def queue_depth(self) -> int:
        staged = sum(len(getattr(c, "_staged_writes", ()))
                     for c in self.sim.clients)
        return len(self.sim.transport.messages) + staged

    def tick(self, arrivals: bool = True) -> None:
        if arrivals:
            self._issue_arrivals()
        self._pump_timers()
        # Backoff expiries re-stage through the coalescing client;
        # ship them even when arrivals are off (the settle phase).
        for client in self.sim.clients:
            client.flush_writes()
        self.budget = min(self.budget + self.dt, 4 * self.dt) \
            if self.budget > 0 else self.budget + self.dt
        self._deliver_budgeted()
        self.max_queue_depth = max(self.max_queue_depth,
                                   self.queue_depth())
        self.now += self.dt

    def run(self, duration_s: float, warmup_s: float = 0.0,
            settle_s: float = 5.0) -> dict:
        """Run the arm: warmup + measured window + a no-arrivals
        settle phase (pending operations conclude -- complete, get
        rejected into give-up, or exhaust retries). Returns the stats
        dict the overload bench records."""
        t_measure = self.now + warmup_s
        t_end = t_measure + duration_s
        while self.now < t_end:
            self.tick(arrivals=True)
        settle_deadline = self.now + settle_s
        while self.now < settle_deadline and (
                self.sessions.pending or self.sim.transport.messages):
            self.tick(arrivals=False)
        measured = [(t0, lat, first) for t0, lat, first in self.completions
                    if t_measure <= t0 < t_end]
        latencies = np.array([lat for _, lat, _ in measured]) \
            if measured else np.zeros(0)
        admitted = np.array([lat for _, lat, first in measured if first]) \
            if measured else np.zeros(0)
        in_slo = int(np.count_nonzero(latencies <= self.slo_deadline_s))
        stats = {
            "offered_rate": self.workload.rate,
            "num_sessions": self.sessions.n,
            "sessions_touched": self.sessions.touched(),
            "issued": self.issued,
            "suppressed_arrivals": self.suppressed,
            "completed": len(measured),
            "completed_first_try": int(len(admitted)),
            "completed_in_slo": in_slo,
            "goodput_cmds_per_s": round(in_slo / duration_s, 2),
            "giveups": self.giveups,
            "pending_after_settle": self.sessions.pending,
            "max_queue_depth": self.max_queue_depth,
        }
        for q in (50, 99, 99.9):
            suffix = str(q).replace(".", "")
            stats[f"p{suffix}_latency_s"] = (
                round(float(np.percentile(latencies, q)), 4)
                if len(latencies) else None)
            # The ADMITTED-request percentile: ops served on first
            # admission, no client backoff in the number -- the
            # latency the server actually delivered to admitted work
            # (the ISSUE gate's p99).
            stats[f"p{suffix}_admitted_s"] = (
                round(float(np.percentile(admitted, q)), 4)
                if len(admitted) else None)
        stats["admission"] = self.admission_stats()
        return stats

    def admission_stats(self) -> dict:
        out: dict = {"admitted": 0, "rejected": {}, "shed": {}}
        for actor in self.sim.transport.actors.values():
            admission = actor.admission
            if admission is None:
                continue
            out["admitted"] += admission.admitted
            for reason, n in admission.rejected.items():
                bucket = ("shed" if reason.startswith("shed_")
                          else "rejected")
                key = reason[len("shed_"):] if bucket == "shed" else reason
                out[bucket][key] = out[bucket].get(key, 0) + n
        return out
