"""The vectorized million-session load tier (paxload).

Simulates 1M+ client SESSIONS as SoA numpy arrays -- per-session state
is one byte + two floats in vectorized columns, never a Python object
-- while the bounded set of IN-FLIGHT operations rides the real client
actor (so the wire path, coalescing, Rejected handling, and backoff
under test are the production code paths). Arrivals come from the
shared :class:`~frankenpaxos_tpu.bench.workload.OpenLoopWorkload`
(open-loop Poisson / Pareto-burst processes, Zipf key skew, diurnal
ramps) -- the SAME generator the deployed driver uses
(bench/client_main.py --open_loop), so sim and deployed arms mean the
same thing by "10x offered load".

:class:`SimOverloadDriver` adds the virtual-time service model that
makes overload meaningful on SimTransport: the cluster gets a CPU
budget of one virtual second per virtual second, each delivered
message costs ``msg_cost_s`` and each completed command
``1/capacity_cmds_per_s``; offered load beyond capacity therefore
builds REAL queues (in the transport buffer) with REAL queueing delay
(in virtual seconds), deterministically -- seeds fully reproduce every
curve in bench_results/overload_lt.json. Timers fire on virtual
deadlines (delay_s from each timer), so client resends and backoff
behave as deployed.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from frankenpaxos_tpu.serve.backoff import RETRY_EXHAUSTED

IDLE, PENDING = 0, 1


class SessionArrays:
    """SoA state for ``n`` sessions: one uint8 + two float64 columns
    (25 MB at n=1M), vectorized arrival sampling against them."""

    def __init__(self, n: int):
        self.n = n
        self.state = np.zeros(n, dtype=np.uint8)
        self.issue_time = np.zeros(n, dtype=np.float64)
        self.ops_issued = np.zeros(n, dtype=np.int32)
        # Did the CURRENT op ever get a Rejected? Cleared at issue;
        # separates admitted-on-arrival completions (the gate's
        # "admitted-request p99") from backoff-retried ones whose
        # latency is dominated by client-side backoff sleeps.
        self.rejected_once = np.zeros(n, dtype=np.uint8)

    @property
    def pending(self) -> int:
        return int(np.count_nonzero(self.state == PENDING))

    def touched(self) -> int:
        """Distinct sessions that ever issued (the active working set
        a window this short actually exercises out of the n)."""
        return int(np.count_nonzero(self.ops_issued))


class SimOverloadDriver:
    """Drive one open-loop arm against a SimTransport cluster under
    the virtual-time service model. ``sim`` is a multipaxos harness
    object (tests/protocols/multipaxos_harness.make_multipaxos) whose
    clients[0] is the coalescing gateway client."""

    def __init__(self, sim, workload, *, num_sessions: int = 1_000_000,
                 capacity_cmds_per_s: float = 400.0,
                 msg_cost_s: float = 0.0002, dt: float = 0.02,
                 slo_deadline_s: float = 1.0, seed: int = 0,
                 payload_bytes: int = 8):
        self.sim = sim
        self.workload = workload
        self.sessions = SessionArrays(num_sessions)
        self.capacity = capacity_cmds_per_s
        self.cmd_cost = 1.0 / capacity_cmds_per_s
        self.msg_cost = msg_cost_s
        self.dt = dt
        self.slo_deadline_s = slo_deadline_s
        self.payload_bytes = payload_bytes
        self.np_rng = np.random.default_rng(seed)
        self.now = 0.0
        self.budget = 0.0
        # Outcome accounting. Completions are (issue_t, latency_s,
        # admitted_first_try); giveups are explicit RETRY_EXHAUSTED
        # conclusions.
        self.completions: list[tuple[float, float, bool]] = []
        self.giveups = 0
        self.suppressed = 0
        self.issued = 0
        self.max_queue_depth = 0
        #: timer id -> (virtual deadline, SimTimer.starts generation at
        #: stamp time). The generation detects a stop+restart between
        #: ticks (clients reuse one resend timer per pseudonym): a
        #: restarted timer gets a FRESH deadline, not the old op's.
        self._timer_deadlines: dict[int, tuple[float, int]] = {}
        self._bind_virtual_clocks()
        self._hook_rejections()

    # --- virtual time plumbing ---------------------------------------------
    def _bind_virtual_clocks(self) -> None:
        """Point every admission controller's clock (token-bucket
        refill, CoDel interval) at the driver's virtual clock so the
        arm is deterministic and rate limits mean virtual rates."""
        clock = lambda: self.now  # noqa: E731

        bind_virtual_clocks(self.sim.transport.actors.values(), clock)

    def _hook_rejections(self) -> None:
        hook_rejections(self.sim.clients, self.sessions)

    def _pump_timers(self) -> None:
        """Fire running sim timers on virtual deadlines: a timer first
        seen running at t fires once now >= t + delay_s (resend and
        backoff discipline in virtual time)."""
        transport = self.sim.transport
        running = {t.id: t for t in transport.running_timers()}
        for tid, timer in running.items():
            rec = self._timer_deadlines.get(tid)
            if rec is None or rec[1] != timer.starts:
                self._timer_deadlines[tid] = (self.now + timer.delay_s,
                                              timer.starts)
        stale = [tid for tid in self._timer_deadlines
                 if tid not in running]
        for tid in stale:
            del self._timer_deadlines[tid]
        due = sorted((d, tid)
                     for tid, (d, _) in self._timer_deadlines.items()
                     if d <= self.now)
        for _, tid in due:
            del self._timer_deadlines[tid]
            transport.trigger_timer(tid)

    # --- the tick loop -----------------------------------------------------
    def _issue_arrivals(self) -> None:
        sessions = self.sessions
        k = self.workload.arrival_count(self.np_rng, self.now, self.dt)
        if k <= 0:
            return
        sids = self.np_rng.integers(0, sessions.n, k)
        keys = self.workload.sample_keys(self.np_rng, k)
        client = self.sim.clients[0]
        for s, key in zip(sids.tolist(), keys.tolist()):
            if sessions.state[s] != IDLE:
                # Open-loop thinning: the session's previous op is
                # still pending (rare at 1M sessions); counted, not
                # queued client-side -- client-side queues are the
                # unbounded-latency pathology this tier exists to
                # remove.
                self.suppressed += 1
                continue
            sessions.state[s] = PENDING
            sessions.issue_time[s] = self.now
            sessions.rejected_once[s] = 0
            sessions.ops_issued[s] += 1
            payload = b"k%d.s%d.%d" % (key, s, sessions.ops_issued[s])
            client.write(s, payload, self._completion_callback(s))
            self.issued += 1
        client.flush_writes()

    def _completion_callback(self, s: int):
        sessions = self.sessions

        def done(result) -> None:
            sessions.state[s] = IDLE
            if result is RETRY_EXHAUSTED:
                self.giveups += 1
            else:
                issued_at = float(sessions.issue_time[s])
                # Completion lands somewhere inside the current tick;
                # crediting the tick's END makes latency >= dt (a
                # same-tick completion is "one service quantum", not
                # zero) and keeps percentiles honest at tick
                # granularity.
                self.completions.append(
                    (issued_at, self.now + self.dt - issued_at,
                     not sessions.rejected_once[s]))

        return done

    def _deliver_budgeted(self) -> None:
        """Spend the tick's CPU budget delivering messages in
        coalesced waves: ``msg_cost_s`` per delivery plus
        ``1/capacity`` per command completion. Whatever the budget
        cannot cover stays queued -- THE queue overload builds.

        paxworld: delivery rides the paxsim wave engine
        (``deliver_all_coalesced`` -> ``_run_wave`` ->
        ``Actor.receive_batch``) instead of a per-message
        ``_deliver`` loop -- the budget sizes each wave up front and
        the completion cost is settled after the wave, so the
        1M-session study exercises the same batched delivery path
        every other sim does. The wave is sized so even an
        all-completions wave cannot overdraw the budget by more than
        ~one frame's costs -- the same debt bound the legacy loop's
        per-message ``budget <= 0`` break enforced (an uncapped
        frame-cost-only wave could charge capacity-scale debt in one
        shot and turn steady overload into serve-burst/dead-stretch
        cycles)."""
        transport = self.sim.transport
        while self.budget > 0 and transport.messages:
            # Only genuine completions cost server capacity; a giveup
            # (RETRY_EXHAUSTED concluded inside a Rejected delivery)
            # is client-local bookkeeping -- charging it cmd_cost
            # would make SHEDDING as expensive as serving and spiral
            # the budget into debt exactly when the edge is doing its
            # job.
            wave_cap = min(
                4096,
                max(1, int(self.budget / self.msg_cost)),
                max(1, int(self.budget / self.cmd_cost) + 1))
            before = len(self.completions)
            delivered = transport.deliver_all_coalesced(
                max_steps=wave_cap)
            if delivered == 0:
                break
            self.budget -= delivered * self.msg_cost \
                + (len(self.completions) - before) * self.cmd_cost

    def queue_depth(self) -> int:
        staged = sum(len(getattr(c, "_staged_writes", ()))
                     for c in self.sim.clients)
        return len(self.sim.transport.messages) + staged

    def tick(self, arrivals: bool = True) -> None:
        if arrivals:
            self._issue_arrivals()
        self._pump_timers()
        # Backoff expiries re-stage through the coalescing client;
        # ship them even when arrivals are off (the settle phase).
        for client in self.sim.clients:
            client.flush_writes()
        self.budget = min(self.budget + self.dt, 4 * self.dt) \
            if self.budget > 0 else self.budget + self.dt
        self._deliver_budgeted()
        self.max_queue_depth = max(self.max_queue_depth,
                                   self.queue_depth())
        self.now += self.dt

    def run(self, duration_s: float, warmup_s: float = 0.0,
            settle_s: float = 5.0) -> dict:
        """Run the arm: warmup + measured window + a no-arrivals
        settle phase (pending operations conclude -- complete, get
        rejected into give-up, or exhaust retries). Returns the stats
        dict the overload bench records."""
        t_measure = self.now + warmup_s
        t_end = t_measure + duration_s
        while self.now < t_end:
            self.tick(arrivals=True)
        settle_deadline = self.now + settle_s
        while self.now < settle_deadline and (
                self.sessions.pending or self.sim.transport.messages):
            self.tick(arrivals=False)
        measured = [(t0, lat, first) for t0, lat, first in self.completions
                    if t_measure <= t0 < t_end]
        latencies = np.array([lat for _, lat, _ in measured]) \
            if measured else np.zeros(0)
        admitted = np.array([lat for _, lat, first in measured if first]) \
            if measured else np.zeros(0)
        in_slo = int(np.count_nonzero(latencies <= self.slo_deadline_s))
        stats = {
            "offered_rate": self.workload.rate,
            "num_sessions": self.sessions.n,
            "sessions_touched": self.sessions.touched(),
            "issued": self.issued,
            "suppressed_arrivals": self.suppressed,
            "completed": len(measured),
            "completed_first_try": int(len(admitted)),
            "completed_in_slo": in_slo,
            "goodput_cmds_per_s": round(in_slo / duration_s, 2),
            "giveups": self.giveups,
            "pending_after_settle": self.sessions.pending,
            "max_queue_depth": self.max_queue_depth,
        }
        # The ADMITTED-request percentiles: ops served on first
        # admission, no client backoff in the number -- the latency
        # the server actually delivered to admitted work (the ISSUE
        # gate's p99).
        stats.update(percentile_rows(latencies, admitted))
        stats["admission"] = self.admission_stats()
        return stats

    def admission_stats(self) -> dict:
        return admission_stats(self.sim.transport)


def bind_virtual_clocks(actors, clock) -> None:
    """Point every attached admission controller's clock (token-bucket
    refill, CoDel interval, shed expiry) at ``clock`` -- ONE time
    source per sim (craq nodes default to time.monotonic; wpaxos
    leaders already bind the transport clock, so rebinding is
    idempotent)."""
    for actor in actors:
        admission = actor.admission
        if admission is not None:
            admission.clock = clock
            admission._last_feed = 0.0
            if admission.bucket is not None:
                admission.bucket.clock = clock
                admission.bucket._last = 0.0


def _rejected_entry_is_current(client, pseudonym, client_id) -> bool:
    """Does a ``Rejected`` entry refer to the client's CURRENT op for
    this pseudonym? A stale duplicate (the original and a resend both
    refused, the second arriving after the op concluded) must not
    taint the NEXT op's admitted-latency attribution. Duck-typed over
    the client shapes the load tier drives: multipaxos ``states``
    (``.id``), wpaxos ``pending`` (``.command_id.client_id``), craq
    ``pending`` (``.id``); unknown shapes mark conservatively."""
    ops = getattr(client, "pending", None)
    if not isinstance(ops, dict):
        ops = getattr(client, "states", None)
    if not isinstance(ops, dict):
        return True
    op = ops.get(pseudonym)
    if op is None:
        return False
    cid = getattr(op, "command_id", None)
    if cid is not None:
        return cid.client_id == client_id
    return getattr(op, "id", client_id) == client_id


def hook_rejections(clients, sessions: SessionArrays) -> None:
    """Wrap each client's ``Rejected`` handler to mark sessions whose
    CURRENT op was refused: their completion latency is dominated by
    client-side backoff sleeps, so the SLO gates' "admitted-request"
    percentiles exclude them (they still count for goodput when they
    finish inside the deadline, and for the giveup accounting when
    they exhaust the budget). Idempotent per client."""
    for client in clients:
        original = getattr(client, "_handle_rejected", None)
        if original is None or getattr(original, "_loadgen_hook",
                                       False):
            continue

        def wrapped(*args, _original=original, _client=client):
            rejected = args[-1]
            for pseudonym, client_id in rejected.entries:
                if pseudonym < sessions.n and _rejected_entry_is_current(
                        _client, pseudonym, client_id):
                    sessions.rejected_once[pseudonym] = 1
            return _original(*args)

        wrapped._loadgen_hook = True
        client._handle_rejected = wrapped


def admission_stats(transport) -> dict:
    """Aggregate every attached AdmissionController's counters."""
    out: dict = {"admitted": 0, "rejected": {}, "shed": {}}
    for actor in transport.actors.values():
        admission = actor.admission
        if admission is None:
            continue
        out["admitted"] += admission.admitted
        for reason, n in admission.rejected.items():
            bucket = ("shed" if reason.startswith("shed_")
                      else "rejected")
            key = reason[len("shed_"):] if bucket == "shed" else reason
            out[bucket][key] = out[bucket].get(key, 0) + n
    return out


def percentile_rows(latencies, admitted) -> dict:
    """The shared p50/p99/p999 row shape (overload_lt + global_lt)."""
    rows: dict = {}
    for q in (50, 99, 99.9):
        suffix = str(q).replace(".", "")
        rows[f"p{suffix}_latency_s"] = (
            round(float(np.percentile(latencies, q)), 4)
            if len(latencies) else None)
        rows[f"p{suffix}_admitted_s"] = (
            round(float(np.percentile(admitted, q)), 4)
            if len(admitted) else None)
    return rows


# --- the geo-fused tier (paxworld, scenarios/) ------------------------------


@dataclasses.dataclass
class TrafficLane:
    """One zone's open-loop arrival stream: a client actor, its
    workload, a contiguous session block [lo, hi) in the shared
    SessionArrays, and the ``issue`` hook that turns one arrival into
    a client operation -- ``issue(client, pseudonym, payload,
    key_index, callback)``. The hook owns the per-protocol client
    signature (wpaxos write-with-key, craq zone-local read, ...), so
    one driver fans one session array across heterogeneous serving
    tiers. ``record_acked`` is False for read lanes: reads feed the
    latency gates but not the acked-write-loss oracle."""

    name: str
    client: object
    workload: object
    sessions: tuple
    issue: object
    record_acked: bool = True


class GeoOverloadDriver:
    """Drive open-loop lanes against a virtual-clock transport
    (GeoSimTransport): the paxgeo x paxload fusion.

    ONE time source per sim: the transport's virtual clock is THE
    clock -- arrivals are sampled against it, admission token buckets
    refill from it, completion latencies are exact virtual durations
    measured on it, and client resend/backoff timers fire inside
    ``run_until`` on their native virtual deadlines (no shadow
    deadline table like the plain-transport driver keeps). A driver
    clock advancing independently of the transport's would silently
    skew offered load against delivery -- the bug class this class
    exists to make unconstructible.

    The service model is the SimOverloadDriver's (a CPU budget of one
    virtual second per virtual second, ``msg_cost_s`` per delivered
    frame + ``1/capacity`` per completion), applied as a ``max_steps``
    bound on the virtual-clock event loop: frames the budget cannot
    cover stay queued past their arrival stamps, which IS queueing
    delay in virtual time. Delivery rides the wave engine end to end
    (``run_until`` -> ``_run_wave`` -> ``Actor.receive_batch``).

    Oracle bookkeeping for the scenario matrix: ``acked`` payloads
    (an acked write may never be lost), ``giveup_payloads``
    (RETRY_EXHAUSTED conclusions -- the bounded, loud degradation
    path), and per-lane completion attribution for per-region SLO
    clauses."""

    def __init__(self, transport, lanes, *,
                 capacity_cmds_per_s: float = 400.0,
                 msg_cost_s: float = 0.0002, dt: float = 0.02,
                 slo_deadline_s: float = 1.0, seed: int = 0):
        if not hasattr(transport, "now") \
                or not hasattr(transport, "run_until"):
            raise ValueError(
                "GeoOverloadDriver needs a virtual-clock transport "
                "(GeoSimTransport); plain SimTransport arms use "
                "SimOverloadDriver")
        self.transport = transport
        self.lanes = list(lanes)
        n = max(hi for _, hi in (lane.sessions for lane in self.lanes))
        self.sessions = SessionArrays(n)
        #: session id -> lane index (blocks are disjoint by contract).
        self._lane_of = np.zeros(n, dtype=np.int16)
        seen: list = []
        for i, lane in enumerate(self.lanes):
            lo, hi = lane.sessions
            for plo, phi in seen:
                if lo < phi and plo < hi:
                    raise ValueError(
                        f"lane session blocks overlap: ({lo}, {hi}) "
                        f"vs ({plo}, {phi})")
            seen.append((lo, hi))
            self._lane_of[lo:hi] = i
        self.capacity = capacity_cmds_per_s
        self.cmd_cost = 1.0 / capacity_cmds_per_s
        self.msg_cost = msg_cost_s
        self.dt = dt
        self.slo_deadline_s = slo_deadline_s
        self.np_rng = np.random.default_rng(seed)
        self.budget = 0.0
        #: (issue_t, latency_s, admitted_first_try, lane_index)
        self.completions: list[tuple] = []
        self.acked: list[bytes] = []
        self.giveups = 0
        self.giveup_payloads: list[bytes] = []
        self._inflight_payload: dict[int, bytes] = {}
        self.suppressed = 0
        self.issued = 0
        self.max_queue_depth = 0
        self._bind_virtual_clocks()
        self._hook_rejections()

    @property
    def now(self) -> float:
        """THE clock -- a read-through to the transport's virtual
        clock, never an independently-advancing copy."""
        return self.transport.now

    # --- virtual time plumbing ---------------------------------------------
    def _bind_virtual_clocks(self) -> None:
        transport = self.transport
        bind_virtual_clocks(transport.actors.values(),
                            lambda: transport.now)

    def _hook_rejections(self) -> None:
        hook_rejections([lane.client for lane in self.lanes],
                        self.sessions)

    # --- the tick loop -----------------------------------------------------
    def _issue_arrivals(self) -> None:
        sessions = self.sessions
        now = self.transport.now
        for li, lane in enumerate(self.lanes):
            k = lane.workload.arrival_count(self.np_rng, now, self.dt)
            if k <= 0:
                continue
            lo, hi = lane.sessions
            sids = self.np_rng.integers(lo, hi, k)
            keys = lane.workload.sample_keys(self.np_rng, k)
            for s, key in zip(sids.tolist(), keys.tolist()):
                if sessions.state[s] != IDLE:
                    self.suppressed += 1
                    continue
                sessions.state[s] = PENDING
                sessions.issue_time[s] = now
                sessions.rejected_once[s] = 0
                sessions.ops_issued[s] += 1
                payload = b"%s.s%d.%d" % (lane.name.encode(), s,
                                          sessions.ops_issued[s])
                if lane.record_acked:
                    self._inflight_payload[s] = payload
                lane.issue(lane.client, s, payload, key,
                           self._completion_callback(s))
                self.issued += 1

    def _completion_callback(self, s: int):
        sessions = self.sessions
        lane_idx = int(self._lane_of[s])

        def done(result) -> None:
            sessions.state[s] = IDLE
            payload = self._inflight_payload.pop(s, None)
            if result is RETRY_EXHAUSTED:
                self.giveups += 1
                if payload is not None:
                    self.giveup_payloads.append(payload)
                return
            if payload is not None:
                self.acked.append(payload)
            issued_at = float(sessions.issue_time[s])
            # The transport clock reads the exact virtual completion
            # instant -- no tick-end crediting: geo latencies are
            # genuine simulated durations (link delays + queueing).
            self.completions.append(
                (issued_at, self.transport.now - issued_at,
                 not sessions.rejected_once[s], lane_idx))

        return done

    def _deliver_budgeted(self) -> None:
        """One tick's event-loop work: run the virtual-clock loop to
        the tick boundary under the CPU budget (``max_steps`` =
        affordable frames). Whatever the budget cannot cover stays
        queued past its arrival stamp -- queueing delay in virtual
        time -- and the clock still reaches the boundary, so offered
        load never stretches."""
        transport = self.transport
        t_end = transport.now + self.dt
        while self.budget > 0:
            # Sized so even an all-completions wave bounds the debt
            # to ~one frame's costs (see SimOverloadDriver).
            cap = min(max(1, int(self.budget / self.msg_cost)),
                      max(1, int(self.budget / self.cmd_cost) + 1))
            before = len(self.completions)
            steps = transport.run_until(t_end, max_steps=cap)
            self.budget -= steps * self.msg_cost \
                + (len(self.completions) - before) * self.cmd_cost
            if steps < cap:
                break  # everything due by t_end is delivered
        # Advance the clock to the boundary even when the budget is in
        # debt (max_steps=0 delivers nothing, moves time).
        transport.run_until(t_end, max_steps=0)

    def queue_depth(self) -> int:
        return len(self.transport.messages)

    def tick(self, arrivals: bool = True) -> None:
        if arrivals:
            self._issue_arrivals()
        self.budget = min(self.budget + self.dt, 4 * self.dt) \
            if self.budget > 0 else self.budget + self.dt
        self._deliver_budgeted()
        self.max_queue_depth = max(self.max_queue_depth,
                                   self.queue_depth())

    def run_for(self, duration_s: float, arrivals: bool = True) -> None:
        t_end = self.transport.now + duration_s - 1e-9
        while self.transport.now < t_end:
            self.tick(arrivals=arrivals)

    def settle(self, settle_s: float) -> None:
        """No-arrivals wind-down: every pending op concludes --
        completes, or walks its bounded retry schedule into an ack /
        RETRY_EXHAUSTED."""
        deadline = self.transport.now + settle_s - 1e-9
        while self.transport.now < deadline and (
                self.sessions.pending or self.transport.messages):
            self.tick(arrivals=False)

    def stats(self, t_measure: float, t_end: float,
              duration_s: float) -> dict:
        measured = [row for row in self.completions
                    if t_measure <= row[0] < t_end]
        latencies = np.array([lat for _, lat, _, _ in measured]) \
            if measured else np.zeros(0)
        admitted = np.array([lat for _, lat, first, _ in measured
                             if first]) if measured else np.zeros(0)
        in_slo = int(np.count_nonzero(latencies <= self.slo_deadline_s))
        stats = {
            "num_sessions": self.sessions.n,
            "sessions_touched": self.sessions.touched(),
            "issued": self.issued,
            "suppressed_arrivals": self.suppressed,
            "completed": len(measured),
            "completed_in_slo": in_slo,
            "goodput_cmds_per_s": round(in_slo / duration_s, 2),
            "giveups": self.giveups,
            "pending_after_settle": self.sessions.pending,
            "max_queue_depth": self.max_queue_depth,
            **percentile_rows(latencies, admitted),
            "admission": admission_stats(self.transport),
            "lanes": {},
        }
        for li, lane in enumerate(self.lanes):
            rows = [row for row in measured if row[3] == li]
            lats = np.array([lat for _, lat, _, _ in rows]) \
                if rows else np.zeros(0)
            adm = np.array([lat for _, lat, first, _ in rows if first]) \
                if rows else np.zeros(0)
            stats["lanes"][lane.name] = {
                "completed": len(rows),
                "in_slo": int(np.count_nonzero(
                    lats <= self.slo_deadline_s)),
                **percentile_rows(lats, adm),
            }
        return stats
