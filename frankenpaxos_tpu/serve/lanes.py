"""Frame-layer priority lanes (paxload).

Shedding decisions must be CHEAP (they run on every frame when a
bounded inbox is attached) and must NEVER touch the control plane --
Phase1/epoch/heartbeat/vote traffic starving behind client writes is
how an overloaded cluster loses its leader and turns congestion into
an outage. So lane classification reads exactly one or two bytes: the
frame's leading wire tag (runtime/serializer.py -- primary page tags
1..127 as the first byte, extended page 0x00 + tag byte, pickle
streams lead with 0x80+).

The CLIENT lane is the closed set of client-REQUEST message types
below, resolved to tags through the codec registry at first use.
Everything else -- votes, phase messages, epoch commits, heartbeats,
replies, and every pickled long-tail message -- is CONTROL and is
never shed (conservative by construction: an unclassifiable frame is
control).
"""

from __future__ import annotations

from frankenpaxos_tpu.runtime import serializer

LANE_CONTROL = 0
LANE_CLIENT = 1

#: Client-request message TYPE names (the shedable lane). Names, not
#: tags: the mapping survives tag reshuffles and covers every protocol
#: that registers a codec for one of these shapes (multipaxos and
#: mencius share ClientRequest/ClientRequestArray/ClientRequestBatch).
CLIENT_LANE_TYPE_NAMES = frozenset({
    "ClientRequest",
    "ClientRequestArray",
    "ClientRequestBatch",
    "MaxSlotRequest",
    "BatchMaxSlotRequest",
    "ReadRequest",
    "ReadRequestBatch",
    "SequentialReadRequest",
    "SequentialReadRequestBatch",
    "EventualReadRequest",
    "EventualReadRequestBatch",
    # Client-edge request shapes surfaced by paxflow FLOW405: every
    # protocol's client-originated traffic must be shedable, not just
    # multipaxos/mencius's. Leader-discovery requests are client-edge
    # too -- the post-failover LeaderInfo thundering herd is exactly
    # what admission should bound (replies from leaders stay control).
    "EchoRequest",
    "ProposeRequest",
    "LeaderInfoRequestClient",
    "LeaderInfoRequestBatcher",
    # paxgeo: the WPaxos client write (protocols/wpaxos). Steal-mode
    # resends ride the same type -- shedding them under overload is
    # correct (the client keeps its failover budget); the steal
    # CONTROL flow (WPhase1a/WEpochCommit) is leader-originated and
    # stays control lane.
    "WRequest",
    # paxwire: a batch frame of client requests must shed like the
    # requests themselves -- the transport's flush planner wraps runs
    # of client-lane payloads in this envelope (runtime/paxwire.py),
    # and both the tag-level and type-level classifiers need to see it.
    "ClientFrameBatch",
    # paxingest: a disseminator's pre-batched run descriptor is
    # aggregated CLIENT load -- an overloaded leader must be able to
    # shed it (one frame, whole run) exactly like the requests it
    # carries; the batcher's own Rejected replies keep clients backing
    # off. NotLeaderIngest (leader -> batcher bounce) stays control.
    "IngestRun",
})

#: Client-lane membership by EXPLICIT wire tag, for client-edge
#: shapes whose names are too generic to claim globally (paxworld:
#: CRAQ's bare Write/201 and Read/202 -- adding "Write"/"Read" to the
#: name set would silently make ANY future protocol's same-named
#: replication message sheddable). The chain's own hops (WriteBatch,
#: Ack, TailRead) stay control lane: a shed mid-chain hop would wedge
#: the chain, and it is not client-originated load anyway.
CLIENT_LANE_EXTRA_TAGS = frozenset({201, 202})

_cache: tuple[int, frozenset, frozenset] | None = None


def _lane_cache() -> tuple:
    """(registered client-lane tags, extra-tag message TYPES) --
    cached against the registry size (codecs register at protocol
    import and never unregister). Both classifiers read this one
    cache so the frame-level and message-level verdicts can never
    disagree."""
    global _cache
    registry = serializer._CODECS_BY_TAG
    if _cache is None or _cache[0] != len(registry):
        tags = frozenset(
            tag for tag, codec in registry.items()
            if codec.message_type.__name__ in CLIENT_LANE_TYPE_NAMES) \
            | (CLIENT_LANE_EXTRA_TAGS & frozenset(registry))
        extra_types = frozenset(
            registry[tag].message_type
            for tag in CLIENT_LANE_EXTRA_TAGS if tag in registry)
        _cache = (len(registry), tags, extra_types)
    return _cache


def client_lane_tags() -> frozenset:
    """Wire tags currently registered for client-lane types (names
    plus the explicit-tag members)."""
    return _lane_cache()[1]


def frame_lane(data: bytes) -> int:
    """The lane of an ENCODED frame payload, from its leading tag
    byte(s). Pickle frames (0x80+) and unknown tags are CONTROL."""
    if not data:
        return LANE_CONTROL
    tag = data[0]
    if tag == 0:  # extended page escape
        if len(data) < 2:
            return LANE_CONTROL
        tag = 128 + data[1]
    elif tag >= 128:  # pickle stream
        return LANE_CONTROL
    return LANE_CLIENT if tag in client_lane_tags() else LANE_CONTROL


def message_lane(message) -> int:
    """The lane of a DECODED message (role-level admission sites);
    agrees with :func:`frame_lane` by construction (one cache)."""
    if type(message).__name__ in CLIENT_LANE_TYPE_NAMES:
        return LANE_CLIENT
    return (LANE_CLIENT if type(message) in _lane_cache()[2]
            else LANE_CONTROL)
