"""Vectorized delivery-wave masks for the simulator core (paxsim).

A delivery WAVE is the batch of buffered frames the simulator consumes
in one step: everything currently buffered (``SimTransport`` FIFO
waves) or everything sharing the next virtual arrival time
(``GeoSimTransport``). The per-message drop decisions -- is either
endpoint partitioned? is the zone link up? -- become one mask
evaluation over the wave's SoA columns (src/dst address ids, src/dst
zone ids) instead of per-message set/dict probes.

Kernels are numpy: waves are host-side, sized tens to tens of
thousands, and feed straight into Python handler dispatch. A jit-able
variant of the combined mask is provided for schedule-scale waves
(``link_keep_mask_jit``); it pads the wave to the next power of two so
XLA compiles one program per size BUCKET, not per wave length (the
TPU2xx retrace hazard). Parity with the numpy kernels is asserted in
tests/test_sim_core.py.

The transports only call these above ``WAVE_VECTOR_MIN`` messages;
below it, per-message Python checks beat the fixed cost of array
staging (measured in bench/sim_core_ab.py).
"""

from __future__ import annotations

import os

import numpy as np

#: Wave size below which the transports keep per-message Python checks
#: (array staging costs ~5us per wave; a 4-message wave of dict probes
#: costs ~1us).
WAVE_VECTOR_MIN = 32

#: Zone id for unplaced addresses (admin/chaos senders): their links
#: are free and always up, modeled as a sentinel row/column of True in
#: the up-matrix.
UNPLACED_ZONE = -1


def keep_mask(src_ids: np.ndarray, dst_ids: np.ndarray,
              blocked_ids: np.ndarray) -> np.ndarray:
    """Partition mask: keep[i] is False when either endpoint of frame
    ``i`` is in ``blocked_ids`` (the transport's ``partitioned`` set,
    interned to address ids)."""
    if blocked_ids.size == 0:
        return np.ones(src_ids.shape, dtype=bool)
    dropped = np.isin(src_ids, blocked_ids) \
        | np.isin(dst_ids, blocked_ids)
    return ~dropped


def link_keep_mask(src_zones: np.ndarray, dst_zones: np.ndarray,
                   up: np.ndarray) -> np.ndarray:
    """Geo link mask: keep[i] = up[src_zone, dst_zone], with
    ``UNPLACED_ZONE`` (-1) endpoints always up. ``up`` is the
    topology's ``[Z+1, Z+1]`` bool matrix whose LAST row/column (the
    -1 index, by numpy wraparound) is the all-True sentinel for
    unplaced addresses -- see ``GeoTopology.up_matrix``."""
    return up[src_zones, dst_zones]


#: The link-mask kernel the geo transport dispatches through:
#: ``FPX_SIMWAVE_JIT=1`` swaps in the jit-able twin below (parity-
#: tested in tests/test_sim_core.py); the numpy kernel is the default
#: -- host-side waves are small enough that XLA dispatch overhead
#: loses to numpy except on schedule-scale runs.
LINK_KEEP_MASK = link_keep_mask


def _pad_pow2(a: np.ndarray, fill) -> np.ndarray:
    n = a.shape[0]
    cap = 1 if n == 0 else 1 << (n - 1).bit_length()
    if cap == n:
        return a
    return np.concatenate([a, np.full(cap - n, fill, dtype=a.dtype)])


def link_keep_mask_jit(src_zones: np.ndarray, dst_zones: np.ndarray,
                       up: np.ndarray) -> np.ndarray:
    """jit-able twin of :func:`link_keep_mask` for schedule-scale
    waves: pads the wave to the next power of two (one XLA program per
    size bucket) and gathers through the same sentinel-row up-matrix.
    Falls back to numpy when jax is unavailable."""
    n = src_zones.shape[0]
    try:
        import jax
    except Exception:  # pragma: no cover - jax is baked into the image
        return link_keep_mask(src_zones, dst_zones, up)
    src_p = _pad_pow2(src_zones.astype(np.int32), UNPLACED_ZONE)
    dst_p = _pad_pow2(dst_zones.astype(np.int32), UNPLACED_ZONE)
    mask = _link_keep_jax(jax.numpy.asarray(src_p),
                          jax.numpy.asarray(dst_p),
                          jax.numpy.asarray(up))
    # np.array (not asarray): device output buffers are read-only and
    # callers AND the partition mask in place.
    return np.array(mask[:n])


if os.environ.get("FPX_SIMWAVE_JIT") == "1":
    LINK_KEEP_MASK = link_keep_mask_jit


_LINK_KEEP_JAX_CACHE = {}


def _link_keep_jax(src_zones, dst_zones, up):
    import jax

    fn = _LINK_KEEP_JAX_CACHE.get("fn")
    if fn is None:
        def gather(src_z, dst_z, up_m):
            return up_m[src_z, dst_z]

        # paxlint: disable=TPU206 -- built ONCE and memoized in
        # _LINK_KEEP_JAX_CACHE (no per-call retrace); a module-scope
        # jit would force the jax import onto every simulator run,
        # jitted or not.
        fn = jax.jit(gather)
        _LINK_KEEP_JAX_CACHE["fn"] = fn
    return fn(src_zones, dst_zones, up)
