"""paxpulse device-plane telemetry: counters as DATA, not hooks.

paxlint TPU209 (correctly) bans span hooks and clock reads inside
``ops/`` kernels and jit-reachable bodies, so the fused drain loop is a
black box to paxtrace: per-shard skew, quorum-progress occupancy,
watermark lag, and pad-lane waste are invisible exactly where the
north-star budget lives. paxpulse restores visibility WITHOUT hooks: a
small SoA array tree (:class:`TelemetryState`) rides inside the
pipeline's donated carry and is accumulated by pure jit-safe reductions
woven into the same fused step -- no callbacks, no clocks, no D2H until
an explicit :func:`frankenpaxos_tpu.obs.telemetry.collect` at the
reporting interval.

Disabled means GONE: the pipeline carries ``telemetry=None`` by default,
and every accumulation site is guarded by a *Python* ``is not None``
check, so the telemetry-off trace contains byte-identical ops to the
pre-paxpulse pipeline (gated by the bit-identity tests and the paired
overhead A/B in ``bench/telemetry_overhead.py``).

Counter semantics (all cumulative since ``make_telemetry``; the host
computes interval deltas between collects):

  * ``shard_committed`` -- ``[slot_shards]`` newly-chosen commands per
    slot shard (replicated over ``group``; each slot shard holds its own
    element). The source of the per-shard gauges and the skew ratio.
  * ``proposed`` -- valid (non-pad) proposed commands, mesh-global.
  * ``occupancy`` -- ``[n_acceptors + 1]`` histogram: at the moment a
    slot is first chosen, how many acceptor votes had landed on it?
    Bucket k counts slots chosen with exactly k votes (clipped at n).
    Saturation shows up here before it shows up in wall-clock.
  * ``lag_hist`` -- ``[LAG_BUCKETS]`` histogram of the end-of-drain
    watermark lag (slots proposed but not yet chosen), bucketed by
    :func:`lag_bucket_bounds` (0, then powers of two).
  * ``pad_lanes`` -- pad-lane slots masked per drain under a
    non-divisible paxmesh split (the waste the padding costs).
  * ``drains`` -- drains accumulated (the denominator for fill rates:
    ingest batch fill = proposed / (drains * block_size)).

All dtypes are int32 and all updates are adds/scatter-adds, so the tree
is safe to donate, psum, and carry through ``fori_loop`` unchanged.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

#: Watermark-lag histogram buckets: 0, 1, 2, [3,4], [5,8], ... (log2).
LAG_BUCKETS = 16


class TelemetryState(NamedTuple):
    shard_committed: jax.Array  # [slot_shards] int32
    proposed: jax.Array         # [] int32
    occupancy: jax.Array        # [n_acceptors + 1] int32
    lag_hist: jax.Array         # [LAG_BUCKETS] int32
    pad_lanes: jax.Array        # [] int32
    drains: jax.Array           # [] int32


#: Mesh partition per leaf, in the PIPELINE_PARTITION axis-tuple idiom:
#: ``shard_committed`` lives with its slot shard; everything else is a
#: mesh-global (replicated) reduction.
TELEMETRY_PARTITION = TelemetryState(
    shard_committed=("slot",),
    proposed=(),
    occupancy=(),
    lag_hist=(),
    pad_lanes=(),
    drains=(),
)


def make_telemetry(num_acceptors: int,
                   slot_shards: int = 1) -> TelemetryState:
    """A zeroed telemetry tree for ``num_acceptors`` GLOBAL acceptors
    over ``slot_shards`` slot shards."""
    return TelemetryState(
        shard_committed=jnp.zeros((slot_shards,), jnp.int32),
        proposed=jnp.int32(0),
        occupancy=jnp.zeros((num_acceptors + 1,), jnp.int32),
        lag_hist=jnp.zeros((LAG_BUCKETS,), jnp.int32),
        pad_lanes=jnp.int32(0),
        drains=jnp.int32(0),
    )


def lag_bucket_bounds() -> np.ndarray:
    """Lower bounds of the lag buckets: bucket b counts lags in
    ``[bounds[b], bounds[b+1])`` with bucket 0 = exactly 0 and the last
    bucket open-ended. Host-side, for reporting."""
    return np.concatenate(
        ([0], 2 ** np.arange(LAG_BUCKETS - 1, dtype=np.int64)))


def lag_bucket(lag: jax.Array) -> jax.Array:
    """The jit-safe bucket index for a scalar int32 lag: counts how many
    power-of-two lower bounds the lag reaches (integer compares only --
    no float log, so the bucketing is bit-stable across backends)."""
    bounds = jnp.asarray(2 ** np.arange(LAG_BUCKETS - 1, dtype=np.int64),
                         jnp.int32)
    return jnp.sum((lag >= bounds).astype(jnp.int32))


def quorum_pass_update(tel: Optional[TelemetryState], *,
                       votes_count: jax.Array, newly: jax.Array,
                       slot_axis: Optional[str]) -> \
        Optional[TelemetryState]:
    """Accumulate one quorum pass: ``votes_count`` is the [B] per-lane
    GLOBAL vote count (already psum'd over ``group``), ``newly`` the [B]
    newly-chosen mask (group-replicated). Pure adds; ``None`` in,
    ``None`` out (the disabled arm traces nothing).

    The histogram is a one-hot compare-and-reduce, NOT a scatter:
    XLA expands a vector ``.at[idx].add`` into a SERIAL per-lane while
    loop (on CPU that made telemetry-on ~5x slower than off), while
    the [bins, B] one-hot reduction stays a fused vector op. Integer
    adds either way, so the counts are bit-identical."""
    if tel is None:
        return None
    n_bins = tel.occupancy.shape[0]
    one_hot = (jnp.clip(votes_count, 0, n_bins - 1)[None, :]
               == jnp.arange(n_bins, dtype=jnp.int32)[:, None])
    local = jnp.sum(one_hot * newly.astype(jnp.int32)[None, :],
                    axis=1, dtype=jnp.int32)
    occ = local if slot_axis is None else jax.lax.psum(local, slot_axis)
    return tel._replace(
        shard_committed=tel.shard_committed
        + newly.sum(dtype=jnp.int32),
        occupancy=tel.occupancy + occ)


def drain_update(tel: Optional[TelemetryState], *,
                 proposed_block: jax.Array,
                 lane_valid: Optional[jax.Array],
                 lag: jax.Array,
                 slot_axis: Optional[str]) -> Optional[TelemetryState]:
    """Accumulate the once-per-drain counters: valid proposals, pad-lane
    waste, the end-of-drain watermark-lag bucket, and the drain count.
    ``lag`` must be mesh-replicated (it derives from ``committed``)."""
    if tel is None:
        return None

    def _global(x):
        return x if slot_axis is None else jax.lax.psum(x, slot_axis)

    valid = _global((proposed_block != 0).sum(dtype=jnp.int32))
    if lane_valid is None:
        pads = tel.pad_lanes
    else:
        pads = tel.pad_lanes + _global(
            (~lane_valid).sum(dtype=jnp.int32))
    return tel._replace(
        proposed=tel.proposed + valid,
        pad_lanes=pads,
        lag_hist=tel.lag_hist.at[lag_bucket(lag)].add(1),
        drains=tel.drains + 1)
