"""Batched dependency-set algebra for EPaxos/BPaxos.

Reference behavior: epaxos/InstancePrefixSet.scala:12-60 — a dependency
set over vertex ids ``(leader, id)`` stored as one IntPrefixSet per leader
column. On device a batch of dependency sets is:

  * ``watermarks [B, L] int32``: per-leader prefix ("ids < w all present"),
  * ``tails [B, L, W] uint8``: sparse window of ids in
    ``[base, base + W)`` (absolute offsets from a shared GC base).

Union = max of watermarks + OR of tails; equality, containment, and
cardinality are elementwise reductions — the per-command set loops of
epaxos/Replica.scala:1159-1420 become one fused step per drain.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class DepSetBatch(NamedTuple):
    watermarks: jax.Array  # [B, L] int32
    tails: jax.Array       # [B, L, W] uint8, absolute base `tail_base`
    tail_base: jax.Array   # [] int32: id of tail column 0


@jax.jit
def union(a: DepSetBatch, b: DepSetBatch) -> DepSetBatch:
    """Rowwise union (EPaxos slow path unions deps across replies).

    PRECONDITION: ``a.tail_base == b.tail_base``. Tails are OR'd
    bit-for-bit, so both batches must window the same id range; callers
    GC all batches to a shared base before combining (use
    :func:`union_checked` from host code to enforce this).
    """
    return DepSetBatch(
        watermarks=jnp.maximum(a.watermarks, b.watermarks),
        tails=a.tails | b.tails,
        tail_base=a.tail_base,
    )


def union_checked(a: DepSetBatch, b: DepSetBatch) -> DepSetBatch:
    """Host-side union that enforces the shared-tail-base precondition."""
    if int(a.tail_base) != int(b.tail_base):
        raise ValueError(
            f"dep-set unions need a shared tail base: "
            f"{int(a.tail_base)} != {int(b.tail_base)}")
    return union(a, b)


@jax.jit
def normalized(d: DepSetBatch) -> DepSetBatch:
    """Clear tail bits already covered by the watermark, then absorb the
    contiguous run at each watermark (IntPrefixSet compaction)."""
    w = d.tails.shape[-1]
    ids = d.tail_base + jnp.arange(w, dtype=jnp.int32)          # [W]
    covered = ids[None, None, :] < d.watermarks[:, :, None]
    tails = jnp.where(covered, jnp.uint8(0), d.tails)
    # Absorb run: for each (b, l), advance watermark while next id present.
    present_from = jnp.where(ids[None, None, :] >= d.watermarks[:, :, None],
                             tails, jnp.uint8(1))
    run = jnp.cumprod(present_from, axis=-1).sum(axis=-1)       # [B, L]
    # The run from the window start is contiguous with the watermark only
    # when the watermark has reached the window (wm >= tail_base);
    # otherwise ids in [wm, tail_base) are absent and nothing absorbs.
    new_wm = jnp.where(d.watermarks >= d.tail_base,
                       jnp.maximum(d.watermarks, d.tail_base + run),
                       d.watermarks)
    covered2 = ids[None, None, :] < new_wm[:, :, None]
    return DepSetBatch(new_wm, jnp.where(covered2, jnp.uint8(0), tails),
                       d.tail_base)


@jax.jit
def union_reduce(d: DepSetBatch) -> DepSetBatch:
    """Union of ALL rows as a normalized single-row batch.

    The EPaxos slow path unions the dependency sets of every PreAcceptOk
    in a quorum (epaxos/Replica.scala:795-813); here the whole reply set
    reduces in one device step: max over watermark columns, OR over
    tails, then IntPrefixSet compaction.
    """
    red = DepSetBatch(
        watermarks=d.watermarks.max(axis=0, keepdims=True),
        tails=d.tails.max(axis=0, keepdims=True),
        tail_base=d.tail_base,
    )
    return normalized(red)


@jax.jit
def all_equal(d: DepSetBatch) -> jax.Array:
    """[] bool: do all B rows denote the same set?

    The EPaxos fast path commits when every counted PreAcceptOk carries
    identical dependencies (epaxos/Replica.scala:1291-1420) -- with the
    count threshold equal to the reply count, "k identical" reduces to
    "all equal". Rows are normalized before comparison so representation
    differences (tail bits vs watermark) don't break set equality.
    """
    n = normalized(d)
    return (jnp.all(n.watermarks == n.watermarks[0])
            & jnp.all(n.tails == n.tails[0]))


@jax.jit
def equal(a: DepSetBatch, b: DepSetBatch) -> jax.Array:
    """[B] bool rowwise set equality (EPaxos fast-path identical-deps test).
    Callers must pass normalized batches."""
    return (jnp.all(a.watermarks == b.watermarks, axis=-1)
            & jnp.all(a.tails == b.tails, axis=(-1, -2)))


@jax.jit
def contains(d: DepSetBatch, leader: jax.Array, vid: jax.Array) -> jax.Array:
    """[B] bool: does each row contain vertex (leader[b], vid[b])?"""
    b = d.watermarks.shape[0]
    rows = jnp.arange(b, dtype=jnp.int32)
    in_prefix = vid < d.watermarks[rows, leader]
    off = vid - d.tail_base
    off_c = jnp.clip(off, 0, d.tails.shape[-1] - 1)
    in_tail = (d.tails[rows, leader, off_c] > 0) & (off >= 0) \
        & (off < d.tails.shape[-1])
    return in_prefix | in_tail


@jax.jit
def size(d: DepSetBatch) -> jax.Array:
    """[B] int32 cardinality (assumes normalized rows)."""
    return (d.watermarks.sum(-1)
            + d.tails.astype(jnp.int32).sum(axis=(-1, -2)))
