"""Batched dependency-set algebra for EPaxos/BPaxos.

Reference behavior: epaxos/InstancePrefixSet.scala:12-60 — a dependency
set over vertex ids ``(leader, id)`` stored as one IntPrefixSet per leader
column. On device a batch of dependency sets is:

  * ``watermarks [B, L] int32``: per-leader prefix ("ids < w all present"),
  * ``tails [B, L, W] uint8``: sparse window of ids in
    ``[base, base + W)`` (absolute offsets from a shared GC base).

Union = max of watermarks + OR of tails; equality, containment, and
cardinality are elementwise reductions — the per-command set loops of
epaxos/Replica.scala:1159-1420 become one fused step per drain.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp


def _pow2(n: int) -> int:
    """Smallest power of two >= n (bucket size for cached planes)."""
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


@functools.lru_cache(maxsize=None)
def _index_plane(cap: int) -> jax.Array:
    """Cached ``[cap] int32`` row-index plane at pow2 capacity.

    Built lazily (an import-time device array would initialize the
    backend in every process that merely imports a protocol module, see
    ops/quorum.py) and pinned to int32 regardless of the x64 flag so
    jitted consumers never retrace on index dtype (SHAPE602).
    """
    return jnp.arange(cap, dtype=jnp.int32)


class DepSetBatch(NamedTuple):
    watermarks: jax.Array  # [B, L] int32
    tails: jax.Array       # [B, L, W] uint8, absolute base `tail_base`
    tail_base: jax.Array   # [] int32: id of tail column 0


@jax.jit
def union(a: DepSetBatch, b: DepSetBatch) -> DepSetBatch:
    """Rowwise union (EPaxos slow path unions deps across replies).

    PRECONDITION: ``a.tail_base == b.tail_base``. Tails are OR'd
    bit-for-bit, so both batches must window the same id range; callers
    GC all batches to a shared base before combining (use
    :func:`union_checked` from host code to enforce this).
    """
    return DepSetBatch(
        watermarks=jnp.maximum(a.watermarks, b.watermarks),
        tails=a.tails | b.tails,
        tail_base=a.tail_base,
    )


def union_checked(a: DepSetBatch, b: DepSetBatch) -> DepSetBatch:
    """Host-side union that enforces the shared-tail-base precondition."""
    if int(a.tail_base) != int(b.tail_base):
        raise ValueError(
            f"dep-set unions need a shared tail base: "
            f"{int(a.tail_base)} != {int(b.tail_base)}")
    return union(a, b)


@jax.jit
def normalized(d: DepSetBatch) -> DepSetBatch:
    """Clear tail bits already covered by the watermark, then absorb the
    contiguous run at each watermark (IntPrefixSet compaction)."""
    w = d.tails.shape[-1]
    ids = d.tail_base + jnp.arange(w, dtype=jnp.int32)          # [W]
    covered = ids[None, None, :] < d.watermarks[:, :, None]
    tails = jnp.where(covered, jnp.uint8(0), d.tails)
    # Absorb run: for each (b, l), advance watermark while next id present.
    present_from = jnp.where(ids[None, None, :] >= d.watermarks[:, :, None],
                             tails, jnp.uint8(1))
    run = jnp.cumprod(present_from, axis=-1).sum(axis=-1)       # [B, L]
    # The run from the window start is contiguous with the watermark only
    # when the watermark has reached the window (wm >= tail_base);
    # otherwise ids in [wm, tail_base) are absent and nothing absorbs.
    new_wm = jnp.where(d.watermarks >= d.tail_base,
                       jnp.maximum(d.watermarks, d.tail_base + run),
                       d.watermarks)
    covered2 = ids[None, None, :] < new_wm[:, :, None]
    return DepSetBatch(new_wm, jnp.where(covered2, jnp.uint8(0), tails),
                       d.tail_base)


@jax.jit
def union_reduce(d: DepSetBatch) -> DepSetBatch:
    """Union of ALL rows as a normalized single-row batch.

    The EPaxos slow path unions the dependency sets of every PreAcceptOk
    in a quorum (epaxos/Replica.scala:795-813); here the whole reply set
    reduces in one device step: max over watermark columns, OR over
    tails, then IntPrefixSet compaction.
    """
    red = DepSetBatch(
        watermarks=d.watermarks.max(axis=0, keepdims=True),
        tails=d.tails.max(axis=0, keepdims=True),
        tail_base=d.tail_base,
    )
    return normalized(red)


@jax.jit
def all_equal(d: DepSetBatch) -> jax.Array:
    """[] bool: do all B rows denote the same set?

    The EPaxos fast path commits when every counted PreAcceptOk carries
    identical dependencies (epaxos/Replica.scala:1291-1420) -- with the
    count threshold equal to the reply count, "k identical" reduces to
    "all equal". Rows are normalized before comparison so representation
    differences (tail bits vs watermark) don't break set equality.
    """
    n = normalized(d)
    return (jnp.all(n.watermarks == n.watermarks[0])
            & jnp.all(n.tails == n.tails[0]))


@jax.jit
def equal(a: DepSetBatch, b: DepSetBatch) -> jax.Array:
    """[B] bool rowwise set equality (EPaxos fast-path identical-deps test).
    Callers must pass normalized batches."""
    return (jnp.all(a.watermarks == b.watermarks, axis=-1)
            & jnp.all(a.tails == b.tails, axis=(-1, -2)))


@jax.jit
def _contains_kernel(d: DepSetBatch, leader: jax.Array, vid: jax.Array,
                     plane: jax.Array) -> jax.Array:
    rows = plane[:d.watermarks.shape[0]]
    in_prefix = vid < d.watermarks[rows, leader]
    off = vid - d.tail_base
    off_c = jnp.clip(off, 0, d.tails.shape[-1] - 1)
    in_tail = (d.tails[rows, leader, off_c] > 0) & (off >= 0) \
        & (off < d.tails.shape[-1])
    return in_prefix | in_tail


def contains(d: DepSetBatch, leader: jax.Array, vid: jax.Array) -> jax.Array:
    """[B] bool: does each row contain vertex (leader[b], vid[b])?

    The row-index plane is the cached pow2-padded :func:`_index_plane`
    (sliced inside the kernel), not a per-call ``jnp.arange``: batches
    sharing a pow2 bucket share one device constant, and the plane's
    int32 dtype is pinned against x64 drift (SHAPE602).
    """
    cap = _pow2(int(d.watermarks.shape[0]))
    return _contains_kernel(d, leader, vid, _index_plane(cap))


@jax.jit
def size(d: DepSetBatch) -> jax.Array:
    """[B] int32 cardinality (assumes normalized rows)."""
    return (d.watermarks.sum(-1)
            + d.tails.astype(jnp.int32).sum(axis=(-1, -2)))


@jax.jit
def conflict_max(seqs: jax.Array, d: DepSetBatch
                 ) -> tuple[jax.Array, DepSetBatch]:
    """The EPaxos seq/deps conflict aggregation over a quorum of replies.

    The slow path picks ``seq = max(reply seqs)`` and
    ``deps = union(reply deps)`` (epaxos/Replica.scala:795-813); here the
    whole reply set reduces in one fused step: ``seqs [B]`` -> ``[]``
    max, plus the normalized one-row union of all B dependency rows.
    """
    return jnp.max(seqs), union_reduce(d)


@jax.jit
def intersect(a: DepSetBatch, b: DepSetBatch) -> DepSetBatch:
    """Rowwise set intersection -- the interference-closure step
    (restrict a dependency set to the instances that actually interfere
    with the command under consideration).

    PRECONDITION: shared ``tail_base`` (as for :func:`union`; use
    :func:`intersect_checked` from host code). An id is in the result
    iff it is in both sets: ids below both watermarks stay prefix
    (``min`` of watermarks), everything else lands as tail bits and
    renormalizes. Ids at or past the tail window can only be present
    via both watermarks, which the ``min`` already covers.
    """
    w = a.tails.shape[-1]
    ids = a.tail_base + jnp.arange(w, dtype=jnp.int32)          # [W]
    in_a = (ids[None, None, :] < a.watermarks[:, :, None]) | (a.tails > 0)
    in_b = (ids[None, None, :] < b.watermarks[:, :, None]) | (b.tails > 0)
    new_wm = jnp.minimum(a.watermarks, b.watermarks)
    tails = ((in_a & in_b)
             & (ids[None, None, :] >= new_wm[:, :, None])).astype(jnp.uint8)
    return normalized(DepSetBatch(new_wm, tails, a.tail_base))


def intersect_checked(a: DepSetBatch, b: DepSetBatch) -> DepSetBatch:
    """Host-side intersection enforcing the shared-tail-base precondition."""
    if int(a.tail_base) != int(b.tail_base):
        raise ValueError(
            f"dep-set intersections need a shared tail base: "
            f"{int(a.tail_base)} != {int(b.tail_base)}")
    return intersect(a, b)


@jax.jit
def compact(d: DepSetBatch, executed: jax.Array) -> DepSetBatch:
    """Prefix-compaction against the executed watermark.

    ``executed`` is ``[L]`` or ``[B, L]`` int32 per-column executed
    watermarks: every instance below it has executed, so a dependency on
    it is vacuously satisfied -- absorb those ids into the prefix (raise
    each column's watermark to at least ``executed``, the device twin of
    ``add_all(InstancePrefixSet.from_watermarks(executed))``) and
    renormalize so newly-covered tail bits fold into the run.
    """
    wm = jnp.maximum(d.watermarks, jnp.asarray(executed, dtype=jnp.int32))
    return normalized(DepSetBatch(wm, d.tails, d.tail_base))
