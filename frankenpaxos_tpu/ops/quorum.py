"""The TpuQuorumChecker: batched quorum-vote aggregation on device.

This is the keystone kernel of the framework (BASELINE.json north star).
It replaces the reference's per-message vote-collection loops --
multipaxos/ProxyLeader.scala:217-258 (Phase2b -> Chosen),
multipaxos/Leader.scala:504-576 (Phase1b quorums),
multipaxos/Client.scala:851-933 (MaxSlot read quorums) -- with a
persistent device **vote board** plus one jitted, state-donating step per
event-loop drain.

Layout (TPU-first): the board is ``votes[acceptors, window]`` --
**slot-major along the 128-wide lane dimension**. A ``[window, n_acc]``
layout with a tiny trailing dim wastes >95% of every (8, 128) TPU tile;
transposed, every op runs at full lane utilization (measured ~40x faster
on v5e).

Two update paths:

  * **dense blocks** (the hot path): slots are allocated contiguously, so
    a drain's votes for slot range ``[start, start+B)`` are a dense
    ``[n, B]`` bitmask applied with ``dynamic_update_slice`` -- no
    scatter at all. Measured ~1.5-4G slot-checks/s on one v5e core.
  * **sparse scatter** (stragglers, retries, out-of-order): classic
    ``.at[nodes, slots].max`` scatter; ~40x slower per element but only
    used for the thin out-of-order tail.

The quorum predicate itself is ``counts = masks @ votes_block`` (a
``[G, N] x [N, B]`` matmul) + compare + any/all over groups -- see
quorums/spec.py for how every quorum system factors into this form.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from frankenpaxos_tpu.quorums.spec import ANY, QuorumSpec

# Plain int (promoted inside jit): creating a device array at import
# time would initialize the backend in every process that merely imports
# a protocol module.
_NEG_INF32 = -(2**31) + 1


class VoteBoard(NamedTuple):
    """Per-slot vote-collection state for a window of slots.

    The window is a ring over slot space: column ``slot % window`` holds
    slot ``slot``. Each column carries its current OWNER slot number, so
    wrapping is self-reclaiming: a vote for a newer slot landing on a
    column still holding ``slot - window`` clears the stale state in the
    same kernel pass, and a straggler vote for a slot the ring has moved
    past is dropped. This replaces the host-driven watermark GC the
    reference needs (util/BufferMap.scala:8-66) -- no release() plumbing
    is required for correctness, only ``window`` > max slots in flight.
    """

    votes: jax.Array   # [n, window] uint8: acceptor voted in `rounds[slot]`
    rounds: jax.Array  # [window] int32: highest round seen per slot
    chosen: jax.Array  # [window] bool: quorum already reached
    owner: jax.Array   # [window] int32: slot currently occupying the column


def make_vote_board(window: int, num_nodes: int) -> VoteBoard:
    return VoteBoard(
        votes=jnp.zeros((num_nodes, window), dtype=jnp.uint8),
        rounds=jnp.full((window,), -1, dtype=jnp.int32),
        chosen=jnp.zeros((window,), dtype=jnp.bool_),
        owner=jnp.full((window,), -1, dtype=jnp.int32),
    )


def _quorum_hit(votes_block: jax.Array, masks: jax.Array,
                thresholds: jax.Array, combine_any: bool) -> jax.Array:
    """``[B]`` bool from a ``[N, B]`` vote block: the predicate matmul."""
    counts = masks @ votes_block.astype(jnp.int32)        # [G, B]
    satisfied = counts >= thresholds[:, None]
    return satisfied.any(0) if combine_any else satisfied.all(0)


def grid_layout(masks, thresholds, combine_any: bool):
    """Detect a Grid quorum predicate in factored (masks, thresholds)
    form (quorums/Grid.scala:5-57 via quorums/spec.py).

    Returns ``(kind, rows, cols, perm)`` when the spec is a grid:
    ``kind`` is ``"write"`` ("one vote in every row": thresholds all 1,
    ALL-combine) or ``"read"`` ("some row fully present": thresholds ==
    row sizes, ANY-combine); ``perm`` is a column permutation into
    row-major ``[rows, cols]`` order, or None when the universe is
    already row-major. Returns None for anything else.

    Grids deserve a first-class fast path (Flexible Paxos,
    arXiv:1608.06696): the generic ``[G, N] x [N, B]`` int32 mask
    matmul degenerates, for a grid, to a pure boolean
    reshape-to-``[rows, cols, B]`` col-OR/row-AND (write) or
    col-AND/row-OR (read) reduction -- no dtype widening, no MXU pass,
    and bit-identical booleans (votes are 0/1, so ``count >= 1`` IS
    ``any`` and ``count >= cols`` IS ``all``).
    """
    masks = np.asarray(masks, dtype=np.uint8)
    thresholds = np.asarray(thresholds, dtype=np.int64)
    if masks.ndim != 2:
        return None
    g, n = masks.shape
    if g < 1 or n < 1 or n % g != 0:
        return None
    cols = n // g
    # Rows must partition the universe into equal-size groups.
    if not (masks.sum(axis=0) == 1).all():
        return None
    if not (masks.sum(axis=1) == cols).all():
        return None
    if combine_any:
        if not (thresholds == cols).all():
            return None
        kind = "read"
    else:
        if not (thresholds == 1).all():
            return None
        kind = "write"
    perm = np.concatenate([np.flatnonzero(masks[r]) for r in range(g)])
    if (perm == np.arange(n)).all():
        return kind, g, cols, None
    return kind, g, cols, tuple(int(x) for x in perm)


def _fused_grid_hit(votes_block: jax.Array, grid: tuple) -> jax.Array:
    """``[B]`` bool from a ``[N, B]`` vote block via the fused grid
    reduction (see :func:`grid_layout`).

    The rows/cols reductions are UNROLLED at trace time into a chain of
    elementwise uint8 ``|``/``&`` ops over the block's row vectors (a
    grid has a handful of rows): XLA fuses the whole chain into the
    block's producer pass, where `jnp.any`/`jnp.all` reduce ops over a
    tiny leading axis break fusion and cost ~3x on host XLA. Votes are
    0/1, so ``|`` IS any and ``&`` IS all -- bit-identity preserved.
    """
    kind, rows, cols, perm = grid
    row_of = (lambda i: votes_block[i]) if perm is None \
        else (lambda i: votes_block[perm[i]])
    acc = None
    for r in range(rows):
        row = row_of(r * cols)
        for c in range(1, cols):
            cell = row_of(r * cols + c)
            row = (row | cell) if kind == "write" else (row & cell)
        acc = row if acc is None \
            else ((acc & row) if kind == "write" else (acc | row))
    return acc.astype(jnp.bool_)


def _predicate_hit(votes_block: jax.Array, masks_t: tuple,
                   meta: tuple) -> jax.Array:
    """Trace-time kernel selection: the fused grid reduction when
    ``_spec_statics`` tagged the spec as a grid, else the generic
    factored matmul."""
    thresholds_t, combine_any = meta[0], meta[1]
    grid = meta[2] if len(meta) > 2 else None
    if grid is not None:
        return _fused_grid_hit(votes_block, grid)
    masks = jnp.asarray(np.asarray(masks_t, dtype=np.int32))
    thresholds = jnp.asarray(np.asarray(thresholds_t, dtype=np.int32))
    return _quorum_hit(votes_block, masks, thresholds, combine_any)


def _apply_sparse_votes(board: VoteBoard, slots, true_slots, nodes,
                        vote_rounds, valid):
    """Shared traced body of the sparse scatter kernels: ring
    self-reclaim + round preemption + vote recording, WITHOUT the
    quorum predicate (the single-spec and epoch-segmented kernels each
    attach their own). Returns ``(votes, new_rounds, chosen0, owner,
    mine)``."""
    # Ring self-reclaim: a newer slot claims its column (clearing stale
    # state from `slot - k*window`); votes for slots the column has moved
    # past are dropped. All per-column derived values are identical for
    # duplicate batch entries, so duplicate scatters are deterministic.
    old_owner = board.owner[slots]                              # [B]
    owner = board.owner.at[slots].max(
        jnp.where(valid, true_slots, _NEG_INF32))
    cur_owner = owner[slots]                                    # [B]
    reclaimed = cur_owner > old_owner                           # [B]
    mine = valid & (true_slots == cur_owner)
    cols0 = board.votes[:, slots]                               # [N, B]
    cols0 = jnp.where(reclaimed[None, :], jnp.uint8(0), cols0)
    votes0 = board.votes.at[:, slots].set(cols0)
    rounds0 = board.rounds.at[slots].set(
        jnp.where(reclaimed, jnp.int32(-1), board.rounds[slots]))
    chosen0 = board.chosen.at[slots].set(
        jnp.where(reclaimed, False, board.chosen[slots]))

    old_rounds = rounds0[slots]                                 # [B]
    new_rounds = rounds0.at[slots].max(
        jnp.where(mine, vote_rounds, _NEG_INF32))
    cur = new_rounds[slots]                                     # [B]
    # A newer round preempts: clear the slot's votes (ProxyLeader state is
    # per (slot, round); an old column must not count toward the new
    # round). `preempted` depends only on slot-level values, so duplicate
    # batch entries for one slot all scatter identical columns.
    preempted = cur > old_rounds                                # [B]
    cols = votes0[:, slots]                                     # [N, B]
    cols = jnp.where(preempted[None, :], jnp.uint8(0), cols)
    votes = votes0.at[:, slots].set(cols)
    # Record votes that are for the slot's (possibly new) current round.
    live = mine & (vote_rounds == cur)
    votes = votes.at[nodes, slots].max(live.astype(jnp.uint8))
    return votes, new_rounds, chosen0, owner, mine


@functools.partial(jax.jit, donate_argnums=(0,), static_argnums=(6, 7))
def _record_and_check(
    board: VoteBoard,
    slots: jax.Array,      # [B] int32, already reduced mod window
    true_slots: jax.Array,  # [B] int32 un-modded slot numbers (owner ids)
    nodes: jax.Array,      # [B] int32 acceptor rows
    vote_rounds: jax.Array,  # [B] int32
    valid: jax.Array,      # [B] bool (padding mask for partial batches)
    masks_t: tuple,        # static: ((row, ...), ...) -> rebuilt as [G, N]
    meta: tuple,           # static: (thresholds, combine_any, grid|None)
) -> tuple[VoteBoard, jax.Array]:
    """Sparse path: out-of-order / straggler votes. O(batch) work."""
    votes, new_rounds, chosen0, owner, mine = _apply_sparse_votes(
        board, slots, true_slots, nodes, vote_rounds, valid)
    # Quorum predicate for exactly the touched columns (duplicates are
    # fine: they see identical post-scatter state).
    hit = _predicate_hit(votes[:, slots], masks_t, meta)
    hit = hit & mine
    newly = hit & ~chosen0[slots]
    chosen = chosen0.at[slots].max(hit)
    return VoteBoard(votes, new_rounds, chosen, owner), newly


@functools.partial(jax.jit, donate_argnums=(0,))
def _record_and_check_epochs(
    board: VoteBoard,
    slots: jax.Array,        # [B] int32, reduced mod window
    true_slots: jax.Array,   # [B] int32 un-modded slot numbers
    nodes: jax.Array,        # [B] int32 acceptor rows (union universe)
    vote_rounds: jax.Array,  # [B] int32
    valid: jax.Array,        # [B] bool
    boundaries: jax.Array,   # [K-1] int64: start slots of epochs 1..K-1
    masks: jax.Array,        # [K, G, N] padded per-epoch masks
    thresholds: jax.Array,   # [K, G]
    combine_any: jax.Array,  # [K] bool
) -> tuple[VoteBoard, jax.Array]:
    """The epoch-segmented sparse kernel: identical board update to
    :func:`_record_and_check`, but each vote's quorum predicate is
    selected by its SLOT's epoch (``searchsorted`` over the epoch
    activation boundaries), so one fused drain can span a handover
    boundary -- old-epoch columns keep counting under the old spec
    while new-epoch columns count under the new one."""
    votes, new_rounds, chosen0, owner, mine = _apply_sparse_votes(
        board, slots, true_slots, nodes, vote_rounds, valid)
    config_idx = jnp.searchsorted(boundaries, true_slots, side="right")
    hit = _check_batch_multi(votes[:, slots].T, config_idx, masks,
                             thresholds, combine_any)
    hit = hit & mine
    newly = hit & ~chosen0[slots]
    chosen = chosen0.at[slots].max(hit)
    return VoteBoard(votes, new_rounds, chosen, owner), newly


@functools.partial(jax.jit, donate_argnums=(0,), static_argnums=(5, 6, 7))
def _record_block(
    board: VoteBoard,
    start: jax.Array,        # [] int32 ring offset of the block
    true_start: jax.Array,   # [] int32 slot number of column `start`
    block: jax.Array,        # [N, B] uint8 vote arrivals for these slots
    vote_round: jax.Array,   # [] int32: round all these votes belong to
    block_size: int,         # static
    masks_t: tuple,
    meta: tuple,
) -> tuple[VoteBoard, jax.Array]:
    """Dense path: votes for a contiguous slot block, one round.

    The steady-state Phase2b stream (Leader.scala:331-408 allocates slots
    contiguously; ProxyLeader collects in slot order) maps here: no
    scatter, only slicing. Returns the ``[B]`` newly-chosen mask.

    Columns with no vote in ``block`` (gap slots inside the run, or
    bucket padding) are left untouched -- in particular their rounds are
    NOT bumped, so an older-round slot mid-run keeps collecting its own
    round's votes (matching the per-(slot, round) dict semantics).
    """
    n = board.votes.shape[0]

    touched = block.any(axis=0)                                # [B]
    # Ring self-reclaim (see VoteBoard): claim columns still owned by an
    # older slot; drop votes for slots the column has moved past.
    slot_ids = true_start + jnp.arange(block_size, dtype=jnp.int32)
    old_owner = jax.lax.dynamic_slice(board.owner, (start,), (block_size,))
    claim = touched & (slot_ids > old_owner)
    stale = touched & (slot_ids < old_owner)
    touched = touched & ~stale
    new_owner = jnp.where(claim, slot_ids, old_owner)
    block = block & touched[None, :].astype(jnp.uint8)

    old_rounds = jax.lax.dynamic_slice(board.rounds, (start,), (block_size,))
    old_rounds = jnp.where(claim, jnp.int32(-1), old_rounds)
    new_rounds = jnp.where(touched,
                           jnp.maximum(old_rounds, vote_round), old_rounds)
    preempted = new_rounds > old_rounds
    cols = jax.lax.dynamic_slice(board.votes, (0, start), (n, block_size))
    cols = jnp.where((claim | preempted)[None, :], jnp.uint8(0), cols)
    live = touched & (vote_round == new_rounds)                # [B]
    cols = cols | (block & live[None, :].astype(jnp.uint8))

    hit = _predicate_hit(cols, masks_t, meta)
    old_chosen = jax.lax.dynamic_slice(board.chosen, (start,), (block_size,))
    old_chosen = jnp.where(claim, False, old_chosen)
    newly = hit & ~old_chosen & touched
    return VoteBoard(
        votes=jax.lax.dynamic_update_slice(board.votes, cols, (0, start)),
        rounds=jax.lax.dynamic_update_slice(board.rounds, new_rounds,
                                            (start,)),
        chosen=jax.lax.dynamic_update_slice(board.chosen, hit | old_chosen,
                                            (start,)),
        owner=jax.lax.dynamic_update_slice(board.owner, new_owner,
                                           (start,)),
    ), newly


@functools.partial(jax.jit, donate_argnums=(0,))
def _release(board: VoteBoard, slots: jax.Array, valid: jax.Array) -> VoteBoard:
    """Reset columns for GC'd slots so the ring can wrap
    (BufferMap.scala:55-62)."""
    votes = board.votes.at[:, slots].set(
        jnp.where(valid[None, :], jnp.uint8(0), board.votes[:, slots]))
    rounds = board.rounds.at[slots].set(
        jnp.where(valid, jnp.int32(-1), board.rounds[slots]))
    chosen = board.chosen.at[slots].set(
        jnp.where(valid, False, board.chosen[slots]))
    owner = board.owner.at[slots].set(
        jnp.where(valid, jnp.int32(-1), board.owner[slots]))
    return VoteBoard(votes, rounds, chosen, owner)


@functools.partial(jax.jit, static_argnums=(1, 2))
def _check_batch(present: jax.Array, masks_t: tuple, meta: tuple) -> jax.Array:
    """``[B, N]`` responder rows -> ``[B]`` bool (stateless)."""
    return _predicate_hit(present.T, masks_t, meta)


@functools.partial(jax.jit, static_argnums=(1, 2))
def _check_block(block: jax.Array, masks_t: tuple, meta: tuple) -> jax.Array:
    """``[N, B]`` slot-major vote block -> ``[B]`` bool (stateless).

    The drain-local quorum predicate: one masks @ block matmul and a
    compare, touching NO board state -- no dynamic slices, no ring
    bookkeeping, nothing proportional to the window. Measured ~3x
    cheaper per call than the stateful ``_record_block`` on host XLA
    and flat in B up to MXU-friendly widths."""
    return _predicate_hit(block, masks_t, meta)


@jax.jit
def _check_batch_multi(
    present: jax.Array,       # [B, N]
    config_idx: jax.Array,    # [B] int32
    masks: jax.Array,         # [K, G, N]
    thresholds: jax.Array,    # [K, G]
    combine_any: jax.Array,   # [K] bool
) -> jax.Array:
    """Per-row quorum check under per-row configurations.

    This is the Matchmaker reconfiguration shape (SURVEY.md section 2.3):
    quorum systems change per round, so each checked row selects its own
    padded (masks, thresholds) plane.
    """
    sel_masks = masks[config_idx].astype(jnp.int32)        # [B, G, N]
    counts = jnp.einsum("bn,bgn->bg", present.astype(jnp.int32), sel_masks)
    satisfied = counts >= thresholds[config_idx]
    return jnp.where(combine_any[config_idx],
                     satisfied.any(-1), satisfied.all(-1))


def _shard_board(board: VoteBoard, mesh, window: int) -> VoteBoard:
    """Lay a :class:`VoteBoard` out over ``mesh``: the SLOT axis shards
    over every mesh axis (the slot-partitioning scaling axis, SURVEY.md
    section 2.3 / multipaxos/DistributionScheme) while the acceptor
    axis stays whole per device. Each device holds
    ``window / mesh.size`` columns; XLA's partitioner inserts the
    collectives for cross-shard scatters and block updates, and results
    stay bit-identical to the unsharded board
    (tests/test_multichip_checker.py)."""
    from jax.sharding import NamedSharding, PartitionSpec

    if window % mesh.size != 0:
        raise ValueError(f"window {window} must be a multiple of "
                         f"the mesh size {mesh.size}")
    axes = tuple(mesh.axis_names)
    slot_sharded = NamedSharding(mesh, PartitionSpec(axes))
    return VoteBoard(
        votes=jax.device_put(
            board.votes, NamedSharding(mesh, PartitionSpec(None, axes))),
        rounds=jax.device_put(board.rounds, slot_sharded),
        chosen=jax.device_put(board.chosen, slot_sharded),
        owner=jax.device_put(board.owner, slot_sharded),
    )


def _replicate(x: jax.Array, mesh) -> jax.Array:
    """Place ``x`` fully REPLICATED over ``mesh`` (the epoch-plane
    rule: predicate planes are tiny and every shard checks its own
    slots against all of them, so replication beats any split)."""
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.device_put(x, NamedSharding(mesh, PartitionSpec()))


def _spec_statics(spec: QuorumSpec) -> tuple[tuple, tuple]:
    """Hashable statics for the jitted kernels: ``(masks_t, meta)``
    where ``meta = (thresholds_t, combine_any, grid_or_None)``. Grid
    specs are detected HERE, once per checker, so every kernel built
    from these statics selects the fused grid reduction at trace time
    (see :func:`grid_layout`)."""
    masks_t = tuple(tuple(int(x) for x in row) for row in spec.masks)
    combine_any = spec.combine == ANY
    thresholds_t = tuple(int(t) for t in spec.thresholds)
    meta = (thresholds_t, combine_any,
            grid_layout(spec.masks, spec.thresholds, combine_any))
    return masks_t, meta


def epoch_column_map(old_universe, new_universe) -> np.ndarray:
    """``[N_new]`` int32 gather map for an epoch reshape: new column
    ``i`` draws its votes from old column ``map[i]``, or ``-1`` when
    universe node ``new_universe[i]`` is new to the board (its column
    starts empty). Node ids removed by the new universe simply have no
    image -- their columns are dropped (the shrink half of
    pad/shrink)."""
    old_col = {node: i for i, node in enumerate(old_universe)}
    return np.asarray([old_col.get(node, -1) for node in new_universe],
                      dtype=np.int32)


@jax.jit
def _reshape_columns(block: jax.Array, cmap: jax.Array) -> jax.Array:
    """``[N_old, B] x [N_new] -> [N_new, B]``: the epoch reshape gather
    (column permutation + pad with zero columns + shrink). One fused
    gather+select -- no host round trip for the board's vote matrix."""
    src = jnp.clip(cmap, 0, block.shape[0] - 1)
    return jnp.where((cmap >= 0)[:, None], block[src],
                     jnp.zeros((), dtype=block.dtype))


def reshape_block(block: np.ndarray, old_universe,
                  new_universe) -> np.ndarray:
    """Host wrapper over :func:`_reshape_columns` for a standalone
    ``[N_old, B]`` vote block (drain blocks crossing an epoch
    boundary)."""
    cmap = epoch_column_map(old_universe, new_universe)
    return np.asarray(_reshape_columns(
        jnp.asarray(block), jnp.asarray(cmap)))


class TpuQuorumChecker:
    """Stateful batched quorum checking for one quorum predicate.

    Typical use (ProxyLeader Phase2b path)::

        checker = TpuQuorumChecker(qs.write_spec(), window=1 << 20)
        # hot path: contiguous slot block, dense [n, B] arrival mask
        newly = checker.record_block(start_slot, arrivals, round=3)
        # thin tail: out-of-order votes
        newly = checker.record_and_check(slots, acceptor_cols, rounds)

    One call per event-loop drain, thousands of votes per call.
    """

    def __init__(self, spec: QuorumSpec, window: int, mesh=None):
        """``mesh``: an optional ``jax.sharding.Mesh``. When given, the
        vote board's SLOT axis shards over every mesh axis (the
        slot-partitioning scaling axis, SURVEY.md section 2.3 /
        multipaxos/DistributionScheme): each device holds
        ``window / mesh.size`` columns and XLA's partitioner inserts the
        collectives for cross-shard scatters and block updates. Results
        are bit-identical to the unsharded board (asserted by
        tests/test_multichip_checker.py)."""
        if window <= 0:
            raise ValueError("window must be positive")
        self.spec = spec
        self.window = window
        self.num_nodes = spec.num_nodes
        # Ring-invariant surveillance (the "window > max slots in
        # flight" contract, see VoteBoard): a vote whose slot trails the
        # newest recorded slot by >= window may land on a reclaimed
        # column and be silently dropped on device -- which manifests as
        # a permanently-unchosen slot. Detect it host-side from the slot
        # numbers we already have (no kernel change, no sync): count
        # violations and log the first occurrence loudly.
        self._max_slot_seen = -1
        self.window_violations = 0
        self._masks_t, self._meta = _spec_statics(spec)
        self.board = make_vote_board(window, spec.num_nodes)
        if mesh is not None:
            self.board = _shard_board(self.board, mesh, window)

    def record_block_async(self, start_slot: int, block: np.ndarray,
                           vote_round: int = 0) -> jax.Array:
        """Like :meth:`record_block` but returns the DEVICE newly-chosen
        mask without waiting -- callers overlap the device round-trip
        with host work and fetch later (np.asarray).

        The returned array keeps the PADDED bucket length (entries past
        the input width are padding) -- slicing it on device would
        dispatch a fresh variable-shape executable per width; slice on
        the host after fetching instead."""
        n, b = block.shape
        if n != self.num_nodes:
            raise ValueError(f"block has {n} acceptor rows, spec has "
                             f"{self.num_nodes}")
        start = start_slot % self.window
        if start + b > self.window:
            raise ValueError(
                f"block [{start}, {start + b}) straddles the ring end "
                f"(window {self.window}); split it")
        self._note_slot_span(start_slot, start_slot + b - 1)
        padded = 64
        while padded < b:
            padded *= 2
        if padded != b and start + padded <= self.window:
            block = np.concatenate(
                [np.asarray(block, dtype=np.uint8),
                 np.zeros((n, padded - b), dtype=np.uint8)], axis=1)
        else:
            padded = b
        self.board, newly = _record_block(
            self.board, jnp.int32(start), jnp.int32(start_slot),
            jnp.asarray(block, dtype=jnp.uint8),
            jnp.int32(vote_round), padded, self._masks_t, self._meta)
        return newly

    def check_block_async(self, block: np.ndarray) -> jax.Array:
        """Stateless drain-local quorum over a ``[n, B]`` vote block:
        returns the DEVICE ``[B]`` hit mask (padded to the kernel
        bucket; slice on the host after fetching).

        A slot whose full write quorum arrives within one event-loop
        drain (the steady state: the ProxyLeader fans each Phase2a to
        its whole quorum in one pass and the acks coalesce back into
        one drain) is decided here without touching the vote board at
        all -- no ring constraints, any ``start`` slot, cost flat in B.
        Callers route the non-hit residue through :meth:`record_block`
        for cross-drain accumulation (SURVEY.md section 7's spill
        path, lifted on device)."""
        n, b = block.shape
        if n != self.num_nodes:
            raise ValueError(f"block has {n} acceptor rows, spec has "
                             f"{self.num_nodes}")
        padded = 64
        while padded < b:
            padded *= 2
        if padded != b:
            block = np.concatenate(
                [np.asarray(block, dtype=np.uint8),
                 np.zeros((n, padded - b), dtype=np.uint8)], axis=1)
        return _check_block(jnp.asarray(block, dtype=jnp.uint8),
                            self._masks_t, self._meta)

    def check_block(self, block: np.ndarray) -> np.ndarray:
        """Synchronous :meth:`check_block_async`, sliced to the input
        width."""
        b = block.shape[1]
        # paxlint: disable=TPU203 -- this IS the explicit sync wrapper
        # (prewarm/tests); drain paths use the _async twin and fetch
        # off-loop.
        return np.asarray(self.check_block_async(block))[:b]

    def record_block(self, start_slot: int, block: np.ndarray,
                     vote_round: int = 0) -> np.ndarray:
        """Dense path: record ``block[n, B]`` arrivals for slots
        ``[start_slot, start_slot + B)`` (must not straddle the ring end);
        return the ``[B]`` newly-chosen mask.

        Widths are bucketed to powers of two so variable drain sizes
        compile O(log max_width) kernels, not one per width. Padding
        columns are all-zero, which the kernel leaves untouched.
        """
        b = block.shape[1]
        # paxlint: disable=TPU203 -- explicit sync wrapper; hot paths
        # use record_block_async and fetch off the drain.
        return np.asarray(self.record_block_async(start_slot, block,
                                                  vote_round))[:b]

    def record_and_check_async(
        self,
        slots: Sequence[int] | np.ndarray,
        node_cols: Sequence[int] | np.ndarray,
        rounds: Sequence[int] | np.ndarray | None = None,
        pad_to: int | None = None,
    ) -> jax.Array:
        """Like :meth:`record_and_check` but returns the DEVICE per-vote
        mask without waiting. The returned array keeps the PADDED batch
        length (see :meth:`record_block_async`); slice on the host."""
        slots = np.asarray(slots, dtype=np.int32)
        b = slots.shape[0]
        if b:
            self._note_slot_span(int(slots.min()), int(slots.max()))
        if rounds is None:
            rounds = np.zeros(b, dtype=np.int32)
        if pad_to is None:
            # Bucket to powers of two so variable drain sizes compile
            # O(log max_batch) kernels, not one per size.
            pad_to = 64
            while pad_to < b:
                pad_to *= 2
        size = max(pad_to, b)
        slots_p = np.zeros(size, dtype=np.int32)
        true_p = np.zeros(size, dtype=np.int32)
        nodes_p = np.zeros(size, dtype=np.int32)
        rounds_p = np.zeros(size, dtype=np.int32)
        valid = np.zeros(size, dtype=bool)
        slots_p[:b] = slots % self.window
        true_p[:b] = slots
        nodes_p[:b] = np.asarray(node_cols, dtype=np.int32)
        rounds_p[:b] = np.asarray(rounds, dtype=np.int32)
        valid[:b] = True
        self.board, newly = _record_and_check(
            self.board, jnp.asarray(slots_p), jnp.asarray(true_p),
            jnp.asarray(nodes_p),
            jnp.asarray(rounds_p), jnp.asarray(valid),
            self._masks_t, self._meta)
        return newly

    def record_and_check(
        self,
        slots: Sequence[int] | np.ndarray,
        node_cols: Sequence[int] | np.ndarray,
        rounds: Sequence[int] | np.ndarray | None = None,
        pad_to: int | None = None,
    ) -> np.ndarray:
        """Sparse path: record out-of-order votes; return per-vote "slot
        newly has quorum".

        Duplicate slots in one batch each report quorum; callers dedup
        (the host side keeps the small pending-slot dict, as ProxyLeader
        keeps `states`, ProxyLeader.scala:135).
        """
        b = np.asarray(slots).shape[0]
        # paxlint: disable=TPU203 -- explicit sync wrapper; hot paths
        # use record_and_check_async and fetch off the drain.
        return np.asarray(self.record_and_check_async(
            slots, node_cols, rounds, pad_to))[:b]

    def _note_slot_span(self, lowest: int, highest: int) -> None:
        """Flag votes that trail the frontier by >= window (they may hit
        a self-reclaimed column and be dropped on device). The batch's
        own span counts too: two same-batch slots >= window apart alias
        one column regardless of the prior frontier."""
        if max(self._max_slot_seen, highest) - lowest >= self.window:
            self.window_violations += 1
            if self.window_violations == 1:
                import warnings

                warnings.warn(
                    f"TpuQuorumChecker: vote for slot {lowest} trails the "
                    f"frontier ({self._max_slot_seen}) by >= window "
                    f"({self.window}); straggler votes may be silently "
                    f"dropped -- raise `window` above the max slots in "
                    f"flight (further violations counted in "
                    f"`window_violations` without warning)",
                    RuntimeWarning, stacklevel=3)
        if highest > self._max_slot_seen:
            self._max_slot_seen = highest

    def reshape(self, new_spec: QuorumSpec) -> None:
        """Epoch reshape: remap the live board's ACCEPTOR axis onto
        ``new_spec``'s universe and swap the predicate, in place.

        The ``[acceptors, window]`` vote matrix is re-laid-out by ONE
        on-device gather (:func:`_reshape_columns`): columns permute to
        the new universe order, members new to the universe get empty
        columns (pad), members the new universe drops lose theirs
        (shrink). Slot-axis state (rounds/chosen/owner) is untouched --
        an epoch changes who votes, not which slots exist -- so a board
        mid-collection survives the handover: votes already recorded
        for surviving acceptors keep counting, bit-identical to
        replaying them onto a fresh new-universe board (asserted
        against the two-config ``quorums/systems.py`` oracle in
        tests/test_reconfig.py)."""
        cmap = epoch_column_map(self.spec.universe, new_spec.universe)
        self.board = VoteBoard(
            votes=_reshape_columns(self.board.votes, jnp.asarray(cmap)),
            rounds=self.board.rounds,
            chosen=self.board.chosen,
            owner=self.board.owner,
        )
        self.spec = new_spec
        self.num_nodes = new_spec.num_nodes
        self._masks_t, self._meta = _spec_statics(new_spec)

    def release(self, slots: Sequence[int] | np.ndarray) -> None:
        """GC slot columns below the chosen watermark so the ring can wrap."""
        slots = np.asarray(slots, dtype=np.int32) % self.window
        valid = np.ones(slots.shape[0], dtype=bool)
        self.board = _release(self.board, jnp.asarray(slots),
                              jnp.asarray(valid))

    def check_batch(self, present: np.ndarray) -> np.ndarray:
        """Stateless: evaluate the predicate for ``[B, N]`` responder rows."""
        return np.asarray(_check_batch(jnp.asarray(present), self._masks_t,
                                       self._meta))


class EpochSegmentedChecker:
    """Quorum checking where each SLOT selects its epoch's predicate.

    The reconfiguration (paxepoch) shape: epochs partition slot space
    at activation watermarks (epoch ``k`` governs ``[start_k,
    start_{k+1})``), each with its own acceptor set and QuorumSpec.
    Specs are padded into one ``[K, G, N]`` plane stack over the UNION
    universe (``quorums.spec.pad_specs``), and every kernel selects a
    slot's plane by ``searchsorted`` over the activation boundaries --
    so ONE fused call (stateless ``check_batch`` or the stateful
    scatter ``record_and_check``) spans the handover boundary instead
    of splitting the drain at it.

    ``add_epoch`` grows the stack in place: specs reindex onto the
    widened union universe and the live vote board reshapes by the
    same on-device gather as :meth:`TpuQuorumChecker.reshape` --
    mid-flight votes for surviving acceptors keep counting across the
    handover.

    ``mesh``: an optional ``jax.sharding.Mesh``. The board's SLOT axis
    shards over every mesh axis (:func:`_shard_board`, the same layout
    as the sharded TpuQuorumChecker) while the epoch planes
    (``masks``/``thresholds``/``combine_any``/``boundaries``) are
    REPLICATED: every shard's slots select their own plane by
    searchsorted, so the plane stack must be whole on every device.
    Results stay bit-identical to the unsharded checker
    (tests/test_multichip_epoch.py, vs the two-config systems oracle).
    """

    def __init__(self, specs: Sequence[QuorumSpec],
                 boundaries: Sequence[int], window: int = 4096,
                 mesh=None):
        if len(specs) != len(boundaries):
            raise ValueError(
                f"{len(specs)} specs vs {len(boundaries)} boundaries")
        if list(boundaries) != sorted(boundaries):
            raise ValueError(
                f"epoch boundaries must be nondecreasing: {boundaries}")
        self.window = window
        self.mesh = mesh
        # Per-epoch specs in their OWN universes; the union universe is
        # first-seen order so adding an epoch only APPENDS columns
        # (existing columns keep their indices -- the board gather for
        # a pure-growth reshape is the identity prefix).
        self._own_specs = list(specs)
        self._starts = [int(b) for b in boundaries]
        self.universe: tuple = ()
        self._rebuild_universe()
        self.board = make_vote_board(window, len(self.universe))
        if mesh is not None:
            self.board = _shard_board(self.board, mesh, window)

    def _rebuild_universe(self) -> None:
        seen: dict = {}
        for spec in self._own_specs:
            for node in spec.universe:
                seen.setdefault(node, len(seen))
        self.universe = tuple(seen)
        specs = [s.reindexed(self.universe) for s in self._own_specs]
        from frankenpaxos_tpu.quorums.spec import pad_specs

        masks, thresholds, combine_any = pad_specs(specs)
        self._masks = jnp.asarray(masks)
        self._thresholds = jnp.asarray(thresholds)
        self._combine_any = jnp.asarray(combine_any)
        # boundaries[k-1] = first slot of epoch k (epoch 0 governs
        # everything below boundaries[0]). int32 like the board's slot
        # state: x64 is off in jitted kernels, and no ring outlives
        # 2^31 slots between GCs.
        self._boundaries = jnp.asarray(
            np.asarray(self._starts[1:], dtype=np.int32))
        self._boundaries_np = np.asarray(self._starts[1:],
                                         dtype=np.int64)
        if getattr(self, "mesh", None) is not None:
            # Replicated epoch planes: explicit placement so the drain
            # kernels never re-lay them out (and DEV1203 stays clean).
            self._masks = _replicate(self._masks, self.mesh)
            self._thresholds = _replicate(self._thresholds, self.mesh)
            self._combine_any = _replicate(self._combine_any, self.mesh)
            self._boundaries = _replicate(self._boundaries, self.mesh)

    def column_of(self, node_id: int) -> int:
        return self.universe.index(node_id)

    def add_epoch(self, spec: QuorumSpec, start_slot: int) -> None:
        """Append an epoch: slots >= ``start_slot`` check under
        ``spec``. Reshapes the live board onto the widened union
        universe (the epoch reshape gather)."""
        if start_slot < self._starts[-1]:
            raise ValueError(
                f"epoch start {start_slot} below the newest epoch's "
                f"{self._starts[-1]}")
        self._own_specs.append(spec)
        self._starts.append(int(start_slot))
        old_universe = self.universe
        self._rebuild_universe()
        if self.universe != old_universe:
            cmap = epoch_column_map(old_universe, self.universe)
            self.board = VoteBoard(
                votes=_reshape_columns(self.board.votes,
                                       jnp.asarray(cmap)),
                rounds=self.board.rounds,
                chosen=self.board.chosen,
                owner=self.board.owner,
            )

    def config_indices(self, slots: np.ndarray) -> np.ndarray:
        """Which epoch plane governs each slot."""
        return np.searchsorted(self._boundaries_np,
                               np.asarray(slots, dtype=np.int64),
                               side="right")

    def check_batch(self, present: np.ndarray,
                    slots: np.ndarray) -> np.ndarray:
        """Stateless: ``[B, N]`` union-universe responder rows checked
        under each row's slot's epoch -- one fused kernel across the
        handover boundary."""
        config_idx = self.config_indices(slots)
        return np.asarray(_check_batch_multi(
            jnp.asarray(present, dtype=jnp.uint8),
            jnp.asarray(config_idx, dtype=jnp.int32),
            self._masks, self._thresholds, self._combine_any))

    def check_block(self, start_slot: int,
                    block: np.ndarray) -> np.ndarray:
        """Stateless dense form: ``block[N, B]`` covers contiguous
        slots ``[start_slot, start_slot + B)`` (which may straddle any
        number of epoch boundaries)."""
        b = block.shape[1]
        slots = start_slot + np.arange(b, dtype=np.int64)
        return self.check_batch(np.asarray(block, dtype=np.uint8).T,
                                slots)

    def record_and_check(
        self,
        slots: Sequence[int] | np.ndarray,
        node_cols: Sequence[int] | np.ndarray,
        rounds: Sequence[int] | np.ndarray | None = None,
    ) -> np.ndarray:
        """Stateful sparse path (the TpuQuorumChecker scatter shape):
        record votes on the union-universe board and return the
        per-vote "slot newly has quorum" mask, each slot judged under
        its epoch's spec."""
        slots = np.asarray(slots, dtype=np.int64)
        b = slots.shape[0]
        if rounds is None:
            rounds = np.zeros(b, dtype=np.int32)
        pad = 64
        while pad < b:
            pad *= 2
        slots_p = np.zeros(pad, dtype=np.int32)
        true_p = np.zeros(pad, dtype=np.int32)
        nodes_p = np.zeros(pad, dtype=np.int32)
        rounds_p = np.zeros(pad, dtype=np.int32)
        valid = np.zeros(pad, dtype=bool)
        slots_p[:b] = slots % self.window
        true_p[:b] = slots
        nodes_p[:b] = np.asarray(node_cols, dtype=np.int32)
        rounds_p[:b] = np.asarray(rounds, dtype=np.int32)
        valid[:b] = True
        self.board, newly = _record_and_check_epochs(
            self.board, jnp.asarray(slots_p), jnp.asarray(true_p),
            jnp.asarray(nodes_p), jnp.asarray(rounds_p),
            jnp.asarray(valid), self._boundaries, self._masks,
            self._thresholds, self._combine_any)
        return np.asarray(newly)[:b]

    def release(self, slots: Sequence[int] | np.ndarray) -> None:
        """GC chosen columns below the watermark (ring wrap)."""
        slots = np.asarray(slots, dtype=np.int32) % self.window
        valid = np.ones(slots.shape[0], dtype=bool)
        self.board = _release(self.board, jnp.asarray(slots),
                              jnp.asarray(valid))


class MultiConfigQuorumChecker:
    """Stateless batched checks where each row picks its own quorum system.

    Built from :func:`frankenpaxos_tpu.quorums.spec.pad_specs`; serves
    Matchmaker per-round configurations and mixed acceptor-group grids.
    """

    def __init__(self, specs: Sequence[QuorumSpec]):
        from frankenpaxos_tpu.quorums.spec import pad_specs

        masks, thresholds, combine_any = pad_specs(specs)
        self.universe = specs[0].universe
        self._masks = jnp.asarray(masks)
        self._thresholds = jnp.asarray(thresholds)
        self._combine_any = jnp.asarray(combine_any)

    def check_batch(self, present: np.ndarray,
                    config_idx: np.ndarray) -> np.ndarray:
        return np.asarray(_check_batch_multi(
            jnp.asarray(present), jnp.asarray(config_idx, dtype=jnp.int32),
            self._masks, self._thresholds, self._combine_any))
