"""Batched device kernels for the hot SMR loops.

The reference framework spends its cycles in per-message JVM loops:
Phase2b vote collection (multipaxos/ProxyLeader.scala:217-258), quorum
predicates (quorums/), watermark math (util/QuorumWatermark.scala:31-50),
and dependency-set algebra (epaxos/InstancePrefixSet.scala:12-60). Here
those loops are data: a ``[window_slots x acceptors]`` vote matrix plus
mask matrices, updated by scatters and evaluated by matmul/reductions in
one fused XLA step per event-loop drain.
"""

from frankenpaxos_tpu.ops.quorum import TpuQuorumChecker, VoteBoard
from frankenpaxos_tpu.ops.watermark import (
    quorum_watermark,
    quorum_watermark_vector,
)

__all__ = [
    "TpuQuorumChecker",
    "VoteBoard",
    "quorum_watermark",
    "quorum_watermark_vector",
]
