"""Batched Phase-1 safe-value selection.

Reference behavior: multipaxos/Leader.scala:318-330 (``safeValue``): given
the Phase1b votes for a slot, adopt the value with the highest vote round,
or a Noop if no acceptor voted. The same masked-argmax shape serves Fast
Paxos recovery (any value voted by enough acceptors) and EPaxos fast-path
"k identical replies" tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NO_VOTE = -1


@jax.jit
def safe_values(vote_rounds: jax.Array, value_ids: jax.Array
                ) -> tuple[jax.Array, jax.Array]:
    """Per-slot highest-round vote.

    Args:
      vote_rounds: ``[S, N]`` int32; ``NO_VOTE`` where acceptor didn't vote.
      value_ids: ``[S, N]`` int32 ids naming each acceptor's voted value
        (host keeps the id -> bytes table).

    Returns:
      ``(has_vote [S] bool, value_id [S] int32)``; ``value_id`` is arbitrary
      (first argmax) where ``has_vote`` is False -- callers substitute Noop
      (Leader.scala:318-330).
    """
    best = jnp.argmax(vote_rounds, axis=-1)
    best_round = jnp.take_along_axis(vote_rounds, best[:, None], axis=-1)[:, 0]
    chosen_value = jnp.take_along_axis(value_ids, best[:, None], axis=-1)[:, 0]
    return best_round > NO_VOTE, chosen_value


@jax.jit
def count_matching_replies(reply_value_ids: jax.Array, valid: jax.Array
                           ) -> tuple[jax.Array, jax.Array]:
    """Per-slot modal reply and its multiplicity.

    EPaxos takes the fast path when ``f + (f+1)/2`` PreAcceptOks carry
    identical (seq, deps) (epaxos/Replica.scala:1291-1420); Fast Paxos
    needs "some value voted by >= k acceptors". Both reduce to: for each
    row of reply ids, the most frequent valid id and its count.

    Args:
      reply_value_ids: ``[S, N]`` int32 ids (hash of reply content).
      valid: ``[S, N]`` bool.

    Returns:
      ``(modal_id [S] int32, count [S] int32)``.
    """
    # Pairwise-equality count: O(N^2) per row, tiny N, MXU/VPU friendly.
    eq = (reply_value_ids[:, :, None] == reply_value_ids[:, None, :])
    eq = eq & valid[:, :, None] & valid[:, None, :]
    counts = eq.sum(-1)                      # [S, N]: votes agreeing with col
    best = jnp.argmax(counts, axis=-1)
    modal = jnp.take_along_axis(reply_value_ids, best[:, None], axis=-1)[:, 0]
    count = jnp.take_along_axis(counts, best[:, None], axis=-1)[:, 0]
    return modal, count
