"""Device watermark math.

The reference computes quorum watermarks by sorting small buffers per call
(util/QuorumWatermark.scala:42-49); replicas find executable log prefixes
by walking the log one entry at a time (multipaxos/Replica.scala:394-453).
Here both are batched reductions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def quorum_watermark(watermarks: jax.Array, quorum_size: jax.Array) -> jax.Array:
    """Largest w such that >= quorum_size of ``watermarks[..., n]`` are >= w.

    Sorted ascending, that's element ``n - quorum_size``
    (QuorumWatermark.scala:42-49).
    """
    n = watermarks.shape[-1]
    sorted_w = jnp.sort(watermarks, axis=-1)
    return jnp.take_along_axis(
        sorted_w, jnp.broadcast_to(n - quorum_size, sorted_w.shape[:-1])[..., None],
        axis=-1)[..., 0]


def quorum_watermark_vector(watermarks: np.ndarray, quorum_size: int) -> np.ndarray:
    """Columnwise quorum watermark over ``[n, depth]``
    (QuorumWatermarkVector.scala:20+)."""
    return np.asarray(
        quorum_watermark(jnp.asarray(watermarks).T, jnp.int32(quorum_size)))


@jax.jit
def contiguous_prefix_length(present: jax.Array) -> jax.Array:
    """Length of the all-True prefix of a bool vector.

    The replica's executeLog advances its executed watermark to the end of
    the contiguous chosen prefix (Replica.scala:394-453); on device that's
    ``sum(cumprod(present))``.
    """
    return jnp.cumprod(present.astype(jnp.int32), axis=-1).sum(axis=-1)
