"""Deterministic randomized protocol simulation.

Reference behavior: shared/src/test/scala/frankenpaxos/simulator/
(SimulatedSystem.scala:152-200, Simulator.scala:221-266): a
QuickCheck-for-stateful-systems harness that runs many random executions
of a protocol wired over a SimTransport, checks invariants after every
step, and minimizes failing traces to near-minimal reproducers.
"""

from frankenpaxos_tpu.sim.simulator import (
    BadHistory,
    SimulatedSystem,
    Simulator,
)

__all__ = ["BadHistory", "SimulatedSystem", "Simulator"]
