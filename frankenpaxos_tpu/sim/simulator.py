"""SimulatedSystem / Simulator: property-based testing with minimization.

Reference behavior: simulator/SimulatedSystem.scala:152-200 (define a
system, command generation, command execution, and three invariant
hooks) and simulator/Simulator.scala:221-266 (run ``num_runs`` random
executions of ``run_length`` commands, check invariants after every
step, and on failure shrink the trace to a near-minimal reproducer,
reporting the seed).

Every protocol test wires all roles over one SimTransport in-process and
interleaves protocol commands (e.g. client writes) with transport
commands (deliver any in-flight message, fire any running timer) --
implicitly exploring reordering, duplication-by-resend, and loss.
"""

from __future__ import annotations

import abc
import dataclasses
import random
from typing import Any, Generic, Optional, Sequence, TypeVar

System = TypeVar("System")
Command = TypeVar("Command")


@dataclasses.dataclass
class BadHistory(Generic[Command]):
    """A failing run: the seed that found it, the (minimized) command
    trace, and the invariant violation."""

    seed: int
    history: list
    error: str

    def __str__(self):
        lines = [f"seed: {self.seed}", f"error: {self.error}", "history:"]
        lines.extend(f"  [{i}] {c!r}" for i, c in enumerate(self.history))
        return "\n".join(lines)


class SimulatedSystem(abc.ABC, Generic[System, Command]):
    """A system under randomized test (SimulatedSystem.scala:152-200)."""

    @abc.abstractmethod
    def new_system(self, seed: int) -> System:
        """Fresh system; all nondeterminism seeded from ``seed``."""

    @abc.abstractmethod
    def generate_command(self, system: System,
                         rng: random.Random) -> Optional[Command]:
        """A random next command, or None if nothing can happen."""

    @abc.abstractmethod
    def run_command(self, system: System, command: Command) -> System:
        """Execute a command. Must tolerate commands that no longer apply
        (needed for trace minimization replays)."""

    def state_invariant(self, system: System) -> Optional[str]:
        """Checked after every step; return an error string on violation."""
        return None

    def step_invariant(self, old_state: Any,
                       new_state: Any) -> Optional[str]:
        """Relates consecutive states (e.g. "logs only grow")."""
        return None

    def history_invariant(self, states: Sequence[Any]) -> Optional[str]:
        """Checked over the whole run's state sequence at the end."""
        return None

    def get_state(self, system: System) -> Any:
        """Projection handed to step/history invariants. Must be an
        immutable snapshot if step/history invariants are used."""
        return None


class Simulator(Generic[System, Command]):
    def __init__(self, sim: SimulatedSystem[System, Command],
                 run_length: int = 100, num_runs: int = 100,
                 minimize: bool = True):
        self.sim = sim
        self.run_length = run_length
        self.num_runs = num_runs
        self.minimize = minimize
        #: Commands executed across every run this instance performed,
        #: including minimization replays (they are part of the work a
        #: soak pays for). tests/soak.py divides by wall time to track
        #: sim-core throughput across PRs (bench_results/
        #: soak_summary.json).
        self.commands_run = 0

    def run(self, seed: int = 0) -> Optional[BadHistory]:
        """Run ``num_runs`` random executions; return the first failure
        (minimized), or None if all runs pass
        (Simulator.scala:221-241)."""
        for i in range(self.num_runs):
            run_seed = seed + i
            failure = self._run_once(run_seed)
            if failure is not None:
                if self.minimize:
                    failure = self._minimize(run_seed, failure)
                return failure
        return None

    # --- one run ----------------------------------------------------------
    def _run_once(self, seed: int) -> Optional[BadHistory]:
        rng = random.Random(seed)
        system = self.sim.new_system(seed)
        history: list = []
        return self._check_run(seed, system, history, rng=rng)

    def _replay(self, seed: int, trace: list) -> Optional[BadHistory]:
        system = self.sim.new_system(seed)
        return self._check_run(seed, system, list(trace), rng=None)

    def _check_run(self, seed: int, system, history: list,
                   rng: Optional[random.Random]) -> Optional[BadHistory]:
        executed: list = []
        states = [self.sim.get_state(system)]

        def fail(error: str) -> BadHistory:
            return BadHistory(seed, executed, error)

        error = self.sim.state_invariant(system)
        if error:
            return fail(f"initial state invariant: {error}")

        steps = self.run_length if rng is not None else len(history)
        for step in range(steps):
            if rng is not None:
                command = self.sim.generate_command(system, rng)
                if command is None:
                    break
            else:
                command = history[step]
            executed.append(command)
            self.commands_run += 1
            system = self.sim.run_command(system, command)
            states.append(self.sim.get_state(system))

            error = self.sim.state_invariant(system)
            if error:
                return fail(f"state invariant: {error}")
            error = self.sim.step_invariant(states[-2], states[-1])
            if error:
                return fail(f"step invariant: {error}")

        error = self.sim.history_invariant(states)
        if error:
            return fail(f"history invariant: {error}")
        return None

    # --- shrinking (Simulator.scala:243-266) ------------------------------
    def _minimize(self, seed: int, failure: BadHistory) -> BadHistory:
        """Greedy delta debugging: drop chunks (halving down to single
        commands) while the replayed trace still fails."""
        trace = list(failure.history)
        best = failure
        chunk = max(1, len(trace) // 2)
        while chunk >= 1:
            i = 0
            progress = False
            while i < len(trace):
                candidate = trace[:i] + trace[i + chunk:]
                replayed = self._replay(seed, candidate)
                if replayed is not None:
                    trace = candidate
                    best = replayed
                    progress = True
                else:
                    i += chunk
            if not progress:
                chunk //= 2
        return best
