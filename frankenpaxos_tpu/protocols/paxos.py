"""Single-decree classic Paxos.

Reference behavior: paxos/ (Leader.scala:40-240, Acceptor.scala:30-120,
Client.scala). Leaders run Phase1 (f+1 promises, adopt the highest vote)
then Phase2 (f+1 votes choose); with n leaders, leader i uses rounds
i, i+n, i+2n, ... Acceptors keep (round, vote_round, vote_value).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport


@dataclasses.dataclass(frozen=True)
class PaxosConfig:
    f: int
    leader_addresses: tuple
    acceptor_addresses: tuple

    def check_valid(self) -> None:
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.acceptor_addresses) != 2 * self.f + 1:
            raise ValueError("need exactly 2f+1 acceptors")


@dataclasses.dataclass(frozen=True)
class ProposeRequest:
    v: str


@dataclasses.dataclass(frozen=True)
class ProposeReply:
    chosen: str


@dataclasses.dataclass(frozen=True)
class Phase1a:
    round: int


@dataclasses.dataclass(frozen=True)
class Phase1b:
    round: int
    acceptor_id: int
    vote_round: int
    vote_value: Optional[str]


@dataclasses.dataclass(frozen=True)
class Phase2a:
    round: int
    value: str


@dataclasses.dataclass(frozen=True)
class Phase2b:
    acceptor_id: int
    round: int


class PaxosLeader(Actor):
    """(paxos/Leader.scala:40-240)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: PaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.leader_addresses).index(address)
        self.round = -1
        self.status = "idle"  # idle | phase1 | phase2 | chosen
        self.proposed_value: Optional[str] = None
        self.phase1b_responses: dict[int, Phase1b] = {}
        self.phase2b_responses: dict[int, Phase2b] = {}
        self.chosen_value: Optional[str] = None
        self.waiting_clients: list[Address] = []

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ProposeRequest):
            self._handle_propose_request(src, message)
        elif isinstance(message, Phase1b):
            self._handle_phase1b(src, message)
        elif isinstance(message, Phase2b):
            self._handle_phase2b(src, message)
        else:
            self.logger.fatal(f"unexpected leader message {message!r}")

    def _handle_propose_request(self, src: Address,
                                request: ProposeRequest) -> None:
        if self.chosen_value is not None:
            self.send(src, ProposeReply(self.chosen_value))
            return
        n = len(self.config.leader_addresses)
        self.round = self.index if self.round == -1 else self.round + n
        self.proposed_value = request.v
        self.status = "phase1"
        self.phase1b_responses.clear()
        self.phase2b_responses.clear()
        for acceptor in self.config.acceptor_addresses:
            self.send(acceptor, Phase1a(round=self.round))
        self.waiting_clients.append(src)

    def _handle_phase1b(self, src: Address, response: Phase1b) -> None:
        if self.status != "phase1" or response.round != self.round:
            self.logger.debug(f"ignoring {response}")
            return
        self.phase1b_responses[response.acceptor_id] = response
        if len(self.phase1b_responses) < self.config.f + 1:
            return
        # Adopt the highest-vote-round value, else our own.
        best = max(self.phase1b_responses.values(),
                   key=lambda r: r.vote_round)
        if best.vote_round != -1:
            self.proposed_value = best.vote_value
        for acceptor in self.config.acceptor_addresses:
            self.send(acceptor, Phase2a(round=self.round,
                                        value=self.proposed_value))
        self.status = "phase2"

    def _handle_phase2b(self, src: Address, response: Phase2b) -> None:
        if self.status != "phase2" or response.round != self.round:
            self.logger.debug(f"ignoring {response}")
            return
        self.phase2b_responses[response.acceptor_id] = response
        if len(self.phase2b_responses) < self.config.f + 1:
            return
        chosen = self.proposed_value
        if self.chosen_value is not None:
            self.logger.check_eq(self.chosen_value, chosen)
        self.chosen_value = chosen
        self.status = "chosen"
        for client in self.waiting_clients:
            self.send(client, ProposeReply(chosen=chosen))
        self.waiting_clients.clear()


class PaxosAcceptor(Actor):
    """(paxos/Acceptor.scala:30-120)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: PaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.acceptor_addresses).index(address)
        self.round = -1
        self.vote_round = -1
        self.vote_value: Optional[str] = None

    def receive(self, src: Address, message) -> None:
        if isinstance(message, Phase1a):
            self._handle_phase1a(src, message)
        elif isinstance(message, Phase2a):
            self._handle_phase2a(src, message)
        else:
            self.logger.fatal(f"unexpected acceptor message {message!r}")

    def _handle_phase1a(self, src: Address, phase1a: Phase1a) -> None:
        if phase1a.round <= self.round:
            return
        self.round = phase1a.round
        self.send(src, Phase1b(round=self.round, acceptor_id=self.index,
                               vote_round=self.vote_round,
                               vote_value=self.vote_value))

    def _handle_phase2a(self, src: Address, phase2a: Phase2a) -> None:
        if phase2a.round < self.round:
            return
        if phase2a.round == self.round and phase2a.round == self.vote_round:
            return  # already voted this round
        self.round = phase2a.round
        self.vote_round = phase2a.round
        self.vote_value = phase2a.value
        self.send(src, Phase2b(acceptor_id=self.index, round=self.round))


class PaxosClient(Actor):
    """(paxos/Client.scala): propose to a leader with a re-propose timer."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: PaxosConfig,
                 repropose_period_s: float = 10.0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.proposed_value: Optional[str] = None
        self.chosen_value: Optional[str] = None
        self.callbacks: list[Callable[[str], None]] = []
        self.repropose_timer = self.timer(
            "repropose", repropose_period_s, self._repropose)

    def propose(self, v: str,
                callback: Optional[Callable[[str], None]] = None) -> None:
        if callback is not None:
            self.callbacks.append(callback)
        if self.chosen_value is not None:
            for cb in self.callbacks:
                cb(self.chosen_value)
            self.callbacks.clear()
            return
        if self.proposed_value is not None:
            return  # already proposing; callback queued
        self.proposed_value = v
        self._send_proposal()
        self.repropose_timer.start()

    def _send_proposal(self) -> None:
        for leader in self.config.leader_addresses:
            self.send(leader, ProposeRequest(v=self.proposed_value))

    def _repropose(self) -> None:
        if self.chosen_value is None and self.proposed_value is not None:
            self._send_proposal()
            self.repropose_timer.start()

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ProposeReply):
            self.logger.fatal(f"unexpected client message {message!r}")
        if self.chosen_value is not None:
            self.logger.check_eq(self.chosen_value, message.chosen)
            return
        self.chosen_value = message.chosen
        self.repropose_timer.stop()
        for cb in self.callbacks:
            cb(message.chosen)
        self.callbacks.clear()


# Importing for side effect: registers this protocol's binary wire
# codecs with the default serializer (see baseline_wire.py).
from frankenpaxos_tpu.protocols import baseline_wire  # noqa: E402,F401
