"""Single-decree Fast Paxos.

Reference behavior: fastpaxos/ (Leader.scala:32-260, Acceptor.scala:30-150,
Client.scala:40-200, Config.scala). Round 0 is the fast round: leader 0
pre-runs Phase1 and issues the distinguished "any" value; clients then
propose directly to acceptors, who vote and reply straight to the client.
A fast quorum (f + floor((f+1)/2) + 1 ... here ``f + majority-of-quorum``)
of matching votes chooses. On conflict or recovery, classic rounds > 0
run through leaders with fast-round vote recovery (the
popular-items/majority-of-quorum rule, Leader.scala:150-190).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from frankenpaxos_tpu.runs.quorums import fast_flexible_specs, SpecChecker
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport


@dataclasses.dataclass(frozen=True)
class FastPaxosConfig:
    f: int
    leader_addresses: tuple
    acceptor_addresses: tuple

    @property
    def n(self) -> int:
        return 2 * self.f + 1

    @property
    def classic_quorum_size(self) -> int:
        return self.f + 1

    @property
    def quorum_majority_size(self) -> int:
        return (self.f + 1) // 2 + 1

    @property
    def fast_quorum_size(self) -> int:
        return self.f + self.quorum_majority_size

    def check_valid(self) -> None:
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.acceptor_addresses) != self.n:
            raise ValueError("need exactly 2f+1 acceptors")


@dataclasses.dataclass(frozen=True)
class ProposeRequest:
    v: str


@dataclasses.dataclass(frozen=True)
class ProposeReply:
    chosen: str


@dataclasses.dataclass(frozen=True)
class Phase1a:
    round: int


@dataclasses.dataclass(frozen=True)
class Phase1b:
    round: int
    acceptor_id: int
    vote_round: int
    vote_value: Optional[str]


@dataclasses.dataclass(frozen=True)
class Phase2a:
    round: int
    # None is the distinguished "any" value (fast round only).
    value: Optional[str]


@dataclasses.dataclass(frozen=True)
class Phase2b:
    acceptor_id: int
    round: int


class FastPaxosLeader(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: FastPaxosConfig,
                 quorum_backend: str = "host"):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        # Quorum predicates in matrix form, sized from the LIVE config
        # (runs/quorums.py): recovery adopts a fast-round value exactly
        # when fast-quorum intersection demands it (>= q1 + qf - n
        # votes among the phase-1 replies).
        specs = fast_flexible_specs(config.n, config.classic_quorum_size,
                                    config.fast_quorum_size)
        self.classic_quorum = SpecChecker(
            specs.classic, quorum_backend,
            metrics=lambda: transport.runtime_metrics)
        self.recovery_quorum = SpecChecker(
            specs.recovery, quorum_backend,
            metrics=lambda: transport.runtime_metrics)
        self.index = list(config.leader_addresses).index(address)
        self.round = self.index
        self.status = "idle"
        self.proposed_value: Optional[str] = None
        self.phase1b_responses: dict[int, Phase1b] = {}
        self.phase2b_responses: dict[int, Phase2b] = {}
        self.chosen_value: Optional[str] = None
        self.waiting_clients: list[Address] = []
        # Leader of the fast round starts Phase1 immediately
        # (Leader.scala:77-84).
        if self.round == 0:
            for acceptor in config.acceptor_addresses:
                self.send(acceptor, Phase1a(round=self.round))
            self.status = "phase1"

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ProposeRequest):
            self._handle_propose_request(src, message)
        elif isinstance(message, Phase1b):
            self._handle_phase1b(src, message)
        elif isinstance(message, Phase2b):
            self._handle_phase2b(src, message)
        else:
            self.logger.fatal(f"unexpected leader message {message!r}")

    def _handle_propose_request(self, src: Address,
                                request: ProposeRequest) -> None:
        if self.chosen_value is not None:
            self.send(src, ProposeReply(self.chosen_value))
            return
        if self.status == "idle":
            n = len(self.config.leader_addresses)
            self.round += n
            self.proposed_value = request.v
            self.status = "phase1"
            self.phase1b_responses.clear()
            self.phase2b_responses.clear()
            for acceptor in self.config.acceptor_addresses:
                self.send(acceptor, Phase1a(round=self.round))
        self.waiting_clients.append(src)

    def _handle_phase1b(self, src: Address, response: Phase1b) -> None:
        if self.status != "phase1" or response.round != self.round:
            return
        self.phase1b_responses[response.acceptor_id] = response
        if not self.classic_quorum.check(self.phase1b_responses):
            return
        k = max(r.vote_round for r in self.phase1b_responses.values())
        if k == -1:
            value = self.proposed_value  # may be None -> "any"
        elif k > 0:
            # Classic round: a single vote value.
            values = {r.vote_value for r in self.phase1b_responses.values()
                      if r.vote_round == k}
            self.logger.check_eq(len(values), 1)
            value = next(iter(values))
            self.proposed_value = value
        else:
            # Fast round: a value the fast quorum may have chosen is one
            # whose voters intersect every fast quorum -- the recovery
            # spec (Leader.scala:168-185; runs/quorums.py). Under a
            # valid configuration at most one value can be popular; an
            # ambiguity means the config violates the fast intersection
            # condition, and adoption is not forced, so the leader keeps
            # its own value (the divergence stays observable to sims).
            voters: dict[Optional[str], list[int]] = {}
            for r in self.phase1b_responses.values():
                if r.vote_round == 0:
                    voters.setdefault(r.vote_value, []).append(
                        r.acceptor_id)
            popular = [v for v, ids in voters.items()
                       if self.recovery_quorum.check(ids)]
            if len(popular) == 1:
                value = popular[0]
                self.proposed_value = value
            else:
                value = self.proposed_value
        for acceptor in self.config.acceptor_addresses:
            self.send(acceptor, Phase2a(round=self.round, value=value))
        self.status = "phase2"

    def _handle_phase2b(self, src: Address, response: Phase2b) -> None:
        self.logger.check_gt(response.round, 0)
        if self.status != "phase2" or response.round != self.round:
            return
        self.phase2b_responses[response.acceptor_id] = response
        if not self.classic_quorum.check(self.phase2b_responses):
            return
        self.logger.check(self.proposed_value is not None)
        chosen = self.proposed_value
        if self.chosen_value is not None:
            self.logger.check_eq(self.chosen_value, chosen)
        self.chosen_value = chosen
        self.status = "chosen"
        for client in self.waiting_clients:
            self.send(client, ProposeReply(chosen=chosen))
        self.waiting_clients.clear()


class FastPaxosAcceptor(Actor):
    """(fastpaxos/Acceptor.scala:30-150). ``any_round`` records receipt of
    the distinguished any value: the next client proposal is voted for
    directly, with the Phase2b going to the *client*."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: FastPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.acceptor_addresses).index(address)
        self.round = -1
        self.vote_round = -1
        self.vote_value: Optional[str] = None
        self.any_round: Optional[int] = None

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ProposeRequest):
            self._handle_propose_request(src, message)
        elif isinstance(message, Phase1a):
            self._handle_phase1a(src, message)
        elif isinstance(message, Phase2a):
            self._handle_phase2a(src, message)
        else:
            self.logger.fatal(f"unexpected acceptor message {message!r}")

    def _handle_propose_request(self, src: Address,
                                request: ProposeRequest) -> None:
        if self.any_round is None:
            return
        r = self.any_round
        if self.round <= r and self.vote_round < r:
            self.round = r
            self.vote_round = r
            self.vote_value = request.v
            self.any_round = None
            self.send(src, Phase2b(acceptor_id=self.index, round=r))

    def _handle_phase1a(self, src: Address, phase1a: Phase1a) -> None:
        if phase1a.round <= self.round:
            return
        self.round = phase1a.round
        self.send(src, Phase1b(round=self.round, acceptor_id=self.index,
                               vote_round=self.vote_round,
                               vote_value=self.vote_value))

    def _handle_phase2a(self, src: Address, phase2a: Phase2a) -> None:
        if phase2a.round < self.round:
            return
        if phase2a.round == self.round and phase2a.round == self.vote_round:
            return
        if phase2a.value is not None:
            self.round = phase2a.round
            self.vote_round = phase2a.round
            self.vote_value = phase2a.value
            self.any_round = None
            self.send(src, Phase2b(acceptor_id=self.index, round=self.round))
        else:
            # The distinguished any value (fast round 0 only).
            if phase2a.round == 0:
                self.any_round = 0


class FastPaxosClient(Actor):
    """(fastpaxos/Client.scala:40-200): proposes straight to acceptors;
    collects fast-quorum Phase2bs itself; falls back to leaders via a
    repropose timer."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: FastPaxosConfig,
                 repropose_period_s: float = 10.0,
                 quorum_backend: str = "host"):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.fast_quorum = SpecChecker(
            fast_flexible_specs(config.n, config.classic_quorum_size,
                                config.fast_quorum_size).fast,
            quorum_backend,
            metrics=lambda: transport.runtime_metrics)
        self.proposed_value: Optional[str] = None
        self.chosen_value: Optional[str] = None
        self.phase2b_responses: dict[int, Phase2b] = {}
        self.callbacks: list[Callable[[str], None]] = []
        self.repropose_timer = self.timer(
            "repropose", repropose_period_s, self._repropose)

    def propose(self, v: str,
                callback: Optional[Callable[[str], None]] = None) -> None:
        if callback is not None:
            self.callbacks.append(callback)
        if self.chosen_value is not None:
            self._deliver()
            return
        if self.proposed_value is not None:
            return
        self.proposed_value = v
        for acceptor in self.config.acceptor_addresses:
            self.send(acceptor, ProposeRequest(v=v))
        self.repropose_timer.start()

    def _repropose(self) -> None:
        if self.chosen_value is not None or self.proposed_value is None:
            return
        # Fall back to the classic path through the leaders.
        for leader in self.config.leader_addresses:
            self.send(leader, ProposeRequest(v=self.proposed_value))
        self.repropose_timer.start()

    def _deliver(self) -> None:
        for cb in self.callbacks:
            cb(self.chosen_value)
        self.callbacks.clear()

    def _choose(self, chosen: str) -> None:
        if self.chosen_value is not None:
            self.logger.check_eq(self.chosen_value, chosen)
            return
        self.chosen_value = chosen
        self.repropose_timer.stop()
        self._deliver()

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ProposeReply):
            self._choose(message.chosen)
        elif isinstance(message, Phase2b):
            self.logger.check_eq(message.round, 0)
            self.phase2b_responses[message.acceptor_id] = message
            if not self.fast_quorum.check(self.phase2b_responses):
                return
            self.logger.check(self.proposed_value is not None)
            self._choose(self.proposed_value)
        else:
            self.logger.fatal(f"unexpected client message {message!r}")


# Importing for side effect: registers this protocol's binary wire
# codecs with the default serializer (see baseline_wire.py).
from frankenpaxos_tpu.protocols import baseline_wire  # noqa: E402,F401
