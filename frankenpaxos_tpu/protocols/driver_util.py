"""Shared scheduling helper for driver-based chaos workloads
(jvm/.../horizontal/Driver.scala:98-129 and
jvm/.../matchmakermultipaxos/Driver.scala:127-160 use the same
delayedTimer shape)."""

from __future__ import annotations

from typing import Callable, Optional


def delayed_repeating(actor, name: str, delay_s: float, period_s: float,
                      n: int, fire: Callable[[], None],
                      on_last: Optional[Callable[[], None]] = None) -> list:
    """After ``delay_s``, fire ``n`` times at ``period_s`` intervals:
    ``fire`` for the first ``n - 1`` firings, then ``on_last`` (or
    ``fire``) for the final one. Returns the created timers."""
    remaining = {"n": n}

    def tick():
        if remaining["n"] > 1:
            remaining["n"] -= 1
            fire()
            repeat.start()
        elif remaining["n"] == 1:
            remaining["n"] = 0
            (on_last or fire)()

    repeat = actor.timer(f"{name}Repeat", period_s, tick)
    delay = actor.timer(f"{name}Delay", delay_s, repeat.start)
    delay.start()
    return [delay, repeat]


def repeating(actor, name: str, delay_s: float, period_s: float,
              fire: Callable[[], None]) -> list:
    """After ``delay_s``, fire every ``period_s`` forever. Returns the
    created timers."""
    def tick():
        fire()
        repeat.start()

    repeat = actor.timer(f"{name}Repeat", period_s, tick)
    delay = actor.timer(f"{name}Delay", delay_s, repeat.start)
    delay.start()
    return [delay, repeat]
