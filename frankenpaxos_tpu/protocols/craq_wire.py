"""Binary codecs for the CRAQ steady-state path."""

from __future__ import annotations

import struct

from frankenpaxos_tpu.protocols import craq as cq
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_address,
    _put_bytes,
    _take_address,
    _take_bytes,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")
_QQQ = struct.Struct("<qqq")

# --- CRAQ -------------------------------------------------------------------


def _cq_put_cid(out: bytearray, cid: cq.CommandId) -> None:
    _put_address(out, cid.client_address)
    out += _I64I64.pack(cid.client_pseudonym, cid.client_id)


def _cq_take_cid(buf: bytes, at: int):
    address, at = _take_address(buf, at)
    pseudonym, id = _I64I64.unpack_from(buf, at)
    return cq.CommandId(address, pseudonym, id), at + 16


def _cq_put_write_batch(out: bytearray, batch: cq.WriteBatch) -> None:
    out += _I64I64.pack(batch.seq, batch.version)
    out += _I32.pack(len(batch.writes))
    for write in batch.writes:
        _cq_put_cid(out, write.command_id)
        _put_bytes(out, write.key.encode())
        _put_bytes(out, write.value.encode())


def _cq_take_write_batch(buf: bytes, at: int):
    seq, version = _I64I64.unpack_from(buf, at)
    (n,) = _I32.unpack_from(buf, at + 16)
    at += 20
    writes = []
    for _ in range(n):
        cid, at = _cq_take_cid(buf, at)
        key, at = _take_bytes(buf, at)
        value, at = _take_bytes(buf, at)
        writes.append(cq.Write(cid, key.decode(), value.decode()))
    return cq.WriteBatch(tuple(writes), seq=seq, version=version), at


def _cq_put_read_batch(out: bytearray, batch: cq.ReadBatch) -> None:
    out += _I32.pack(len(batch.reads))
    for read in batch.reads:
        _cq_put_cid(out, read.command_id)
        _put_bytes(out, read.key.encode())


def _cq_take_read_batch(buf: bytes, at: int):
    (n,) = _I32.unpack_from(buf, at)
    at += 4
    reads = []
    for _ in range(n):
        cid, at = _cq_take_cid(buf, at)
        key, at = _take_bytes(buf, at)
        reads.append(cq.Read(cid, key.decode()))
    return cq.ReadBatch(tuple(reads)), at


class CraqWriteBatchCodec(MessageCodec):
    message_type = cq.WriteBatch
    tag = 64

    def encode(self, out, message):
        _cq_put_write_batch(out, message)

    def decode(self, buf, at):
        return _cq_take_write_batch(buf, at)


class CraqReadBatchCodec(MessageCodec):
    message_type = cq.ReadBatch
    tag = 65

    def encode(self, out, message):
        _cq_put_read_batch(out, message)

    def decode(self, buf, at):
        return _cq_take_read_batch(buf, at)


class CraqTailReadCodec(MessageCodec):
    message_type = cq.TailRead
    tag = 66

    def encode(self, out, message):
        _cq_put_read_batch(out, message.read_batch)

    def decode(self, buf, at):
        batch, at = _cq_take_read_batch(buf, at)
        return cq.TailRead(batch), at


class CraqAckCodec(MessageCodec):
    message_type = cq.Ack
    tag = 67

    def encode(self, out, message):
        _cq_put_write_batch(out, message.write_batch)

    def decode(self, buf, at):
        batch, at = _cq_take_write_batch(buf, at)
        return cq.Ack(batch), at


class CraqClientReplyCodec(MessageCodec):
    message_type = cq.ClientReply
    tag = 68

    def encode(self, out, message):
        _cq_put_cid(out, message.command_id)

    def decode(self, buf, at):
        cid, at = _cq_take_cid(buf, at)
        return cq.ClientReply(cid), at


class CraqReadReplyCodec(MessageCodec):
    message_type = cq.ReadReply
    tag = 69

    def encode(self, out, message):
        _cq_put_cid(out, message.command_id)
        _put_bytes(out, message.value.encode())

    def decode(self, buf, at):
        cid, at = _cq_take_cid(buf, at)
        value, at = _take_bytes(buf, at)
        return cq.ReadReply(cid, value.decode()), at


# The bare client-edge shapes (paxworld, extended tag page): what a
# CraqClient actually puts on the wire is Write/Read, not the chain's
# batch envelopes -- without their own tags these frames pickled, so
# the frame-layer lane classifier (serve/lanes.py) was BLIND to them
# and a bounded inbox could never shed CRAQ client traffic (the
# FLOW405a class paxflow caught on the multipaxos read batchers).


class CraqWriteCodec(MessageCodec):
    message_type = cq.Write
    tag = 201

    def encode(self, out, message):
        _cq_put_cid(out, message.command_id)
        _put_bytes(out, message.key.encode())
        _put_bytes(out, message.value.encode())

    def decode(self, buf, at):
        cid, at = _cq_take_cid(buf, at)
        key, at = _take_bytes(buf, at)
        value, at = _take_bytes(buf, at)
        return cq.Write(cid, key.decode(), value.decode()), at


class CraqReadCodec(MessageCodec):
    message_type = cq.Read
    tag = 202

    def encode(self, out, message):
        _cq_put_cid(out, message.command_id)
        _put_bytes(out, message.key.encode())

    def decode(self, buf, at):
        cid, at = _cq_take_cid(buf, at)
        key, at = _take_bytes(buf, at)
        return cq.Read(cid, key.decode()), at


class CraqChainReconfigureCodec(MessageCodec):
    """paxchaos chain re-link (control lane by construction: the tag
    is outside the client-lane set, so a bounded inbox can never shed
    the repair that unwedges the chain)."""

    message_type = cq.ChainReconfigure
    tag = 203

    def encode(self, out, message):
        out += _I64.pack(message.version)
        out += _I32.pack(len(message.chain))
        for address in message.chain:
            _put_address(out, address)

    def decode(self, buf, at):
        (version,) = _I64.unpack_from(buf, at)
        (n,) = _I32.unpack_from(buf, at + 8)
        if not 0 <= n <= 1024:
            raise ValueError(f"malformed chain length {n}")
        at += 12
        chain = []
        for _ in range(n):
            address, at = _take_address(buf, at)
            chain.append(address)
        return cq.ChainReconfigure(version=version,
                                   chain=tuple(chain)), at


for _codec in (CraqWriteBatchCodec(), CraqReadBatchCodec(),
               CraqTailReadCodec(), CraqAckCodec(),
               CraqClientReplyCodec(), CraqReadReplyCodec(),
               CraqWriteCodec(), CraqReadCodec(),
               CraqChainReconfigureCodec()):
    register_codec(_codec)
