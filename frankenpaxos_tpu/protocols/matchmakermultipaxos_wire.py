"""Binary codecs for the MatchmakerMultiPaxos steady-state write path.

Only the per-command hot loop (ClientRequest -> Phase2a -> Phase2b ->
Chosen -> ClientReply, Matchmaker.proto's MultiPaxos core); the
matchmaking/reconfiguration traffic (MatchRequest/Stop/Bootstrap/...)
is per-epoch, not per-command, and stays pickled.
"""

from __future__ import annotations

import struct

from frankenpaxos_tpu.protocols import matchmakermultipaxos as m
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_address,
    _put_bytes,
    _take_address,
    _take_bytes,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")
_QQQ = struct.Struct("<qqq")


def _put_command(out: bytearray, command: m.Command) -> None:
    cid = command.command_id
    _put_address(out, cid.client_address)
    out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
    _put_bytes(out, command.command)


def _take_command(buf: bytes, at: int):
    address, at = _take_address(buf, at)
    pseudonym, id = _I64I64.unpack_from(buf, at)
    payload, at = _take_bytes(buf, at + 16)
    return m.Command(m.CommandId(address, pseudonym, id), payload), at


def _put_value(out: bytearray, value) -> None:
    if isinstance(value, m.Noop):
        out.append(0)
    else:
        out.append(1)
        _put_command(out, value)


def _take_value(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    if kind == 0:
        return m.NOOP, at
    return _take_command(buf, at)


class MMPClientRequestCodec(MessageCodec):
    message_type = m.ClientRequest
    tag = 48

    def encode(self, out, message):
        _put_command(out, message.command)

    def decode(self, buf, at):
        command, at = _take_command(buf, at)
        return m.ClientRequest(command), at


class MMPPhase2aCodec(MessageCodec):
    message_type = m.Phase2a
    tag = 49

    def encode(self, out, message):
        out += _I64I64.pack(message.slot, message.round)
        _put_value(out, message.value)

    def decode(self, buf, at):
        slot, round = _I64I64.unpack_from(buf, at)
        value, at = _take_value(buf, at + 16)
        return m.Phase2a(slot=slot, round=round, value=value), at


class MMPPhase2bCodec(MessageCodec):
    message_type = m.Phase2b
    tag = 50

    def encode(self, out, message):
        out += _QQQ.pack(message.slot, message.round,
                         message.acceptor_index)

    def decode(self, buf, at):
        slot, round, acceptor = _QQQ.unpack_from(buf, at)
        return m.Phase2b(slot=slot, round=round,
                         acceptor_index=acceptor), at + _QQQ.size


class MMPChosenCodec(MessageCodec):
    message_type = m.Chosen
    tag = 51

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        _put_value(out, message.value)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        value, at = _take_value(buf, at + 8)
        return m.Chosen(slot=slot, value=value), at


class MMPClientReplyCodec(MessageCodec):
    message_type = m.ClientReply
    tag = 52

    def encode(self, out, message):
        cid = message.command_id
        _put_address(out, cid.client_address)
        out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
        _put_bytes(out, message.result)

    def decode(self, buf, at):
        address, at = _take_address(buf, at)
        pseudonym, id = _I64I64.unpack_from(buf, at)
        result, at = _take_bytes(buf, at + 16)
        return m.ClientReply(m.CommandId(address, pseudonym, id),
                             result), at


for _codec in (MMPClientRequestCodec(), MMPPhase2aCodec(),
               MMPPhase2bCodec(), MMPChosenCodec(),
               MMPClientReplyCodec()):
    register_codec(_codec)
