"""Binary codecs for the MatchmakerMultiPaxos steady-state write path
and the matchmaker-epoch-change cold path.

The per-command hot loop (ClientRequest -> Phase2a -> Phase2b ->
Chosen -> ClientReply, Matchmaker.proto's MultiPaxos core) rides tags
48-52. The matchmaker self-reconfiguration single-decree Paxos
(MatchPhase1a/1b/2a/2b/MatchChosen/MatchNack), the Stopped bounce and
the GC pair ride extended tags 181-189 (paxsafe COD301 burn-down):
per-epoch traffic, but it is exactly what is on the wire during a
matchmaker failover, and pickled frames are refused under
``set_pickle_fallback(False)``. The whole-log transfer messages
(Stop/StopAck/Bootstrap/BootstrapAck/ReconfigureMatchmakers, tags
195-199, paxsim COD301 burn-down) carry round -> quorum-system DICT
logs; their wire form encodes the four structured quorum-system
shapes (`quorums.systems.quorum_system_to_dict`: simple_majority /
unanimous_writes member sets, grid / zone_grid int matrices)
fixed-layout, with a guarded-pickle escape hatch for exotic dicts so
``set_pickle_fallback(False)`` still covers the hatch.
"""

from __future__ import annotations

import struct

from frankenpaxos_tpu.protocols import matchmakermultipaxos as m
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_address,
    _put_bytes,
    _take_address,
    _take_bytes,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I32 = struct.Struct("<i")
_I32I32 = struct.Struct("<ii")
_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")
_QQQ = struct.Struct("<qqq")


def _put_command(out: bytearray, command: m.Command) -> None:
    cid = command.command_id
    _put_address(out, cid.client_address)
    out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
    _put_bytes(out, command.command)


def _take_command(buf: bytes, at: int):
    address, at = _take_address(buf, at)
    pseudonym, id = _I64I64.unpack_from(buf, at)
    payload, at = _take_bytes(buf, at + 16)
    return m.Command(m.CommandId(address, pseudonym, id), payload), at


def _put_value(out: bytearray, value) -> None:
    if isinstance(value, m.Noop):
        out.append(0)
    else:
        out.append(1)
        _put_command(out, value)


def _take_value(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    if kind == 0:
        return m.NOOP, at
    return _take_command(buf, at)


class MMPClientRequestCodec(MessageCodec):
    message_type = m.ClientRequest
    tag = 48

    def encode(self, out, message):
        _put_command(out, message.command)

    def decode(self, buf, at):
        command, at = _take_command(buf, at)
        return m.ClientRequest(command), at


class MMPPhase2aCodec(MessageCodec):
    message_type = m.Phase2a
    tag = 49

    def encode(self, out, message):
        out += _I64I64.pack(message.slot, message.round)
        _put_value(out, message.value)

    def decode(self, buf, at):
        slot, round = _I64I64.unpack_from(buf, at)
        value, at = _take_value(buf, at + 16)
        return m.Phase2a(slot=slot, round=round, value=value), at


class MMPPhase2bCodec(MessageCodec):
    message_type = m.Phase2b
    tag = 50

    def encode(self, out, message):
        out += _QQQ.pack(message.slot, message.round,
                         message.acceptor_index)

    def decode(self, buf, at):
        slot, round, acceptor = _QQQ.unpack_from(buf, at)
        return m.Phase2b(slot=slot, round=round,
                         acceptor_index=acceptor), at + _QQQ.size


class MMPChosenCodec(MessageCodec):
    message_type = m.Chosen
    tag = 51

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        _put_value(out, message.value)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        value, at = _take_value(buf, at + 8)
        return m.Chosen(slot=slot, value=value), at


class MMPClientReplyCodec(MessageCodec):
    message_type = m.ClientReply
    tag = 52

    def encode(self, out, message):
        cid = message.command_id
        _put_address(out, cid.client_address)
        out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
        _put_bytes(out, message.result)

    def decode(self, buf, at):
        address, at = _take_address(buf, at)
        pseudonym, id = _I64I64.unpack_from(buf, at)
        result, at = _take_bytes(buf, at + 16)
        return m.ClientReply(m.CommandId(address, pseudonym, id),
                             result), at


def _put_mc(out: bytearray, mc: m.MatchmakerConfiguration) -> None:
    out += _I64.pack(mc.epoch)
    out += _I32.pack(mc.reconfigurer_index)
    out += _I32.pack(len(mc.matchmaker_indices))
    for index in mc.matchmaker_indices:
        out += _I32.pack(index)


def _take_mc(buf: bytes, at: int):
    (epoch,) = _I64.unpack_from(buf, at)
    reconfigurer, n = _I32I32.unpack_from(buf, at + 8)
    if n < 0 or n > (len(buf) - at - 16) // 4:
        raise ValueError(f"hostile matchmaker-index count {n}")
    at += 16
    indices = []
    for _ in range(n):
        (index,) = _I32.unpack_from(buf, at)
        if not 0 <= index < (1 << 20):
            # Value validation at the trust boundary (see
            # fasterpaxos_wire._take_delegates): out-of-range indices
            # must die as corrupt frames, not as IndexErrors (or
            # silent negative-index wraps) inside the matchmaker.
            raise ValueError(f"hostile matchmaker index {index}")
        indices.append(index)
        at += 4
    return m.MatchmakerConfiguration(epoch, reconfigurer,
                                     tuple(indices)), at


class MMPStoppedCodec(MessageCodec):
    message_type = m.Stopped
    tag = 181

    def encode(self, out, message):
        out += _I64.pack(message.epoch)

    def decode(self, buf, at):
        (epoch,) = _I64.unpack_from(buf, at)
        return m.Stopped(epoch=epoch), at + 8


class MMPGarbageCollectCodec(MessageCodec):
    message_type = m.GarbageCollect
    tag = 182

    def encode(self, out, message):
        _put_mc(out, message.matchmaker_configuration)
        out += _I64.pack(message.gc_watermark)

    def decode(self, buf, at):
        mc, at = _take_mc(buf, at)
        (watermark,) = _I64.unpack_from(buf, at)
        return m.GarbageCollect(mc, watermark), at + 8


class MMPGarbageCollectAckCodec(MessageCodec):
    message_type = m.GarbageCollectAck
    tag = 183

    def encode(self, out, message):
        out += _I64.pack(message.epoch)
        out += _I32.pack(message.matchmaker_index)
        out += _I64.pack(message.gc_watermark)

    def decode(self, buf, at):
        (epoch,) = _I64.unpack_from(buf, at)
        (index,) = _I32.unpack_from(buf, at + 8)
        (watermark,) = _I64.unpack_from(buf, at + 12)
        return m.GarbageCollectAck(epoch, index, watermark), at + 20


class MMPMatchPhase1aCodec(MessageCodec):
    message_type = m.MatchPhase1a
    tag = 184

    def encode(self, out, message):
        _put_mc(out, message.matchmaker_configuration)
        out += _I64.pack(message.round)

    def decode(self, buf, at):
        mc, at = _take_mc(buf, at)
        (round,) = _I64.unpack_from(buf, at)
        return m.MatchPhase1a(mc, round), at + 8


class MMPMatchPhase1bCodec(MessageCodec):
    message_type = m.MatchPhase1b
    tag = 185

    def encode(self, out, message):
        out += _I64I64.pack(message.epoch, message.round)
        out += _I32.pack(message.matchmaker_index)
        out += _I64.pack(message.vote_round)
        if message.vote_value is None:
            out.append(0)
        else:
            out.append(1)
            _put_mc(out, message.vote_value)

    def decode(self, buf, at):
        epoch, round = _I64I64.unpack_from(buf, at)
        (index,) = _I32.unpack_from(buf, at + 16)
        (vote_round,) = _I64.unpack_from(buf, at + 20)
        at += 28
        kind = buf[at]
        at += 1
        vote_value = None
        if kind == 1:
            vote_value, at = _take_mc(buf, at)
        elif kind != 0:
            raise ValueError(f"bad MatchPhase1b vote flag {kind}")
        return m.MatchPhase1b(epoch=epoch, round=round,
                              matchmaker_index=index,
                              vote_round=vote_round,
                              vote_value=vote_value), at


class MMPMatchPhase2aCodec(MessageCodec):
    message_type = m.MatchPhase2a
    tag = 186

    def encode(self, out, message):
        _put_mc(out, message.matchmaker_configuration)
        out += _I64.pack(message.round)
        _put_mc(out, message.value)

    def decode(self, buf, at):
        mc, at = _take_mc(buf, at)
        (round,) = _I64.unpack_from(buf, at)
        value, at = _take_mc(buf, at + 8)
        return m.MatchPhase2a(mc, round, value), at


class MMPMatchPhase2bCodec(MessageCodec):
    message_type = m.MatchPhase2b
    tag = 187

    def encode(self, out, message):
        out += _I64I64.pack(message.epoch, message.round)
        out += _I32.pack(message.matchmaker_index)

    def decode(self, buf, at):
        epoch, round = _I64I64.unpack_from(buf, at)
        (index,) = _I32.unpack_from(buf, at + 16)
        return m.MatchPhase2b(epoch=epoch, round=round,
                              matchmaker_index=index), at + 20


class MMPMatchChosenCodec(MessageCodec):
    message_type = m.MatchChosen
    tag = 188

    def encode(self, out, message):
        _put_mc(out, message.value)

    def decode(self, buf, at):
        value, at = _take_mc(buf, at)
        return m.MatchChosen(value), at


class MMPMatchNackCodec(MessageCodec):
    message_type = m.MatchNack
    tag = 189

    def encode(self, out, message):
        out += _I64I64.pack(message.epoch, message.round)

    def decode(self, buf, at):
        epoch, round = _I64I64.unpack_from(buf, at)
        return m.MatchNack(epoch=epoch, round=round), at + 16


# --- whole-log transfers: round -> quorum-system dict logs ----------------

_QS_KINDS = {"simple_majority": 0, "unanimous_writes": 1,
             "grid": 2, "zone_grid": 3}
_QS_KIND_NAMES = {v: k for k, v in _QS_KINDS.items()}
_QS_PICKLED = 255
_MAX_QS_INT = 1 << 20


def _put_qs_dict(out: bytearray, d) -> None:
    """One quorum-system dict (quorums.systems.quorum_system_to_dict).
    The four structured shapes encode fixed-layout; anything else --
    unknown kind, non-int members -- rides the guarded pickle hatch,
    so exotic payloads still honor ``set_pickle_fallback``."""
    from frankenpaxos_tpu.runtime import serializer

    kind = _QS_KINDS.get(d.get("kind")) if isinstance(d, dict) else None
    if kind in (0, 1):
        members = d.get("members")
        if (isinstance(members, list)
                and all(type(x) is int and 0 <= x < _MAX_QS_INT
                        for x in members)):
            out.append(kind)
            out += _I32.pack(len(members))
            for x in members:
                out += _I32.pack(x)
            return
    elif kind in (2, 3):
        grid = d.get("grid")
        if (isinstance(grid, list)
                and all(isinstance(row, list)
                        and all(type(x) is int and 0 <= x < _MAX_QS_INT
                                for x in row)
                        for row in grid)):
            out.append(kind)
            out += _I32.pack(len(grid))
            for row in grid:
                out += _I32.pack(len(row))
                for x in row:
                    out += _I32.pack(x)
            return
    out.append(_QS_PICKLED)
    _put_bytes(out, serializer.guarded_pickle_dumps(
        d, "quorum-system dict"))


def _take_qs_dict(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    if kind == _QS_PICKLED:
        from frankenpaxos_tpu.runtime import serializer

        raw, at = _take_bytes(buf, at)
        return serializer.guarded_pickle_loads(
            raw, "quorum-system dict"), at
    if kind in (0, 1):
        (n,) = _I32.unpack_from(buf, at)
        at += 4
        if n < 0 or n > (len(buf) - at) // 4:
            raise ValueError(f"hostile quorum-member count {n}")
        members = []
        for _ in range(n):
            (x,) = _I32.unpack_from(buf, at)
            if not 0 <= x < _MAX_QS_INT:
                raise ValueError(f"hostile quorum member {x}")
            members.append(x)
            at += 4
        return {"kind": _QS_KIND_NAMES[kind], "members": members}, at
    if kind in (2, 3):
        (rows,) = _I32.unpack_from(buf, at)
        at += 4
        if rows < 0 or rows > (len(buf) - at) // 4:
            raise ValueError(f"hostile quorum-grid row count {rows}")
        grid = []
        for _ in range(rows):
            (cols,) = _I32.unpack_from(buf, at)
            at += 4
            if cols < 0 or cols > (len(buf) - at) // 4:
                raise ValueError(
                    f"hostile quorum-grid column count {cols}")
            row = []
            for _ in range(cols):
                (x,) = _I32.unpack_from(buf, at)
                if not 0 <= x < _MAX_QS_INT:
                    raise ValueError(f"hostile quorum-grid entry {x}")
                row.append(x)
                at += 4
            grid.append(row)
        return {"kind": _QS_KIND_NAMES[kind], "grid": grid}, at
    raise ValueError(f"bad quorum-system kind byte {kind}")


def _put_configurations(out: bytearray, configurations) -> None:
    out += _I32.pack(len(configurations))
    for round, qs_dict in configurations:
        out += _I64.pack(round)
        _put_qs_dict(out, qs_dict)


def _take_configurations(buf: bytes, at: int):
    (n,) = _I32.unpack_from(buf, at)
    at += 4
    # Each entry is at least round (8) + kind byte (1).
    if n < 0 or n > (len(buf) - at) // 9:
        raise ValueError(f"hostile configuration count {n}")
    configurations = []
    for _ in range(n):
        (round,) = _I64.unpack_from(buf, at)
        qs_dict, at = _take_qs_dict(buf, at + 8)
        configurations.append((round, qs_dict))
    return tuple(configurations), at


class MMPStopCodec(MessageCodec):
    message_type = m.Stop
    tag = 195

    def encode(self, out, message):
        _put_mc(out, message.matchmaker_configuration)

    def decode(self, buf, at):
        mc, at = _take_mc(buf, at)
        return m.Stop(mc), at


class MMPStopAckCodec(MessageCodec):
    message_type = m.StopAck
    tag = 196

    def encode(self, out, message):
        out += _I32.pack(message.matchmaker_index)
        out += _I64I64.pack(message.epoch, message.gc_watermark)
        _put_configurations(out, message.configurations)

    def decode(self, buf, at):
        (index,) = _I32.unpack_from(buf, at)
        epoch, watermark = _I64I64.unpack_from(buf, at + 4)
        configurations, at = _take_configurations(buf, at + 20)
        return m.StopAck(matchmaker_index=index, epoch=epoch,
                         gc_watermark=watermark,
                         configurations=configurations), at


class MMPBootstrapCodec(MessageCodec):
    message_type = m.Bootstrap
    tag = 197

    def encode(self, out, message):
        out += _I64.pack(message.epoch)
        out += _I32.pack(message.reconfigurer_index)
        out += _I64.pack(message.gc_watermark)
        _put_configurations(out, message.configurations)

    def decode(self, buf, at):
        (epoch,) = _I64.unpack_from(buf, at)
        (index,) = _I32.unpack_from(buf, at + 8)
        (watermark,) = _I64.unpack_from(buf, at + 12)
        configurations, at = _take_configurations(buf, at + 20)
        return m.Bootstrap(epoch=epoch, reconfigurer_index=index,
                           gc_watermark=watermark,
                           configurations=configurations), at


class MMPBootstrapAckCodec(MessageCodec):
    message_type = m.BootstrapAck
    tag = 198

    def encode(self, out, message):
        out += _I32.pack(message.matchmaker_index)
        out += _I64.pack(message.epoch)

    def decode(self, buf, at):
        (index,) = _I32.unpack_from(buf, at)
        (epoch,) = _I64.unpack_from(buf, at + 4)
        return m.BootstrapAck(matchmaker_index=index,
                              epoch=epoch), at + 12


class MMPReconfigureMatchmakersCodec(MessageCodec):
    message_type = m.ReconfigureMatchmakers
    tag = 199

    def encode(self, out, message):
        _put_mc(out, message.matchmaker_configuration)
        out += _I32.pack(len(message.new_matchmaker_indices))
        for index in message.new_matchmaker_indices:
            out += _I32.pack(index)

    def decode(self, buf, at):
        mc, at = _take_mc(buf, at)
        (n,) = _I32.unpack_from(buf, at)
        at += 4
        if n < 0 or n > (len(buf) - at) // 4:
            raise ValueError(f"hostile matchmaker-index count {n}")
        indices = []
        for _ in range(n):
            (index,) = _I32.unpack_from(buf, at)
            if not 0 <= index < _MAX_QS_INT:
                raise ValueError(f"hostile matchmaker index {index}")
            indices.append(index)
            at += 4
        return m.ReconfigureMatchmakers(
            matchmaker_configuration=mc,
            new_matchmaker_indices=tuple(indices)), at


for _codec in (MMPClientRequestCodec(), MMPPhase2aCodec(),
               MMPPhase2bCodec(), MMPChosenCodec(),
               MMPClientReplyCodec(), MMPStoppedCodec(),
               MMPGarbageCollectCodec(), MMPGarbageCollectAckCodec(),
               MMPMatchPhase1aCodec(), MMPMatchPhase1bCodec(),
               MMPMatchPhase2aCodec(), MMPMatchPhase2bCodec(),
               MMPMatchChosenCodec(), MMPMatchNackCodec(),
               MMPStopCodec(), MMPStopAckCodec(), MMPBootstrapCodec(),
               MMPBootstrapAckCodec(),
               MMPReconfigureMatchmakersCodec()):
    register_codec(_codec)
