"""Binary codecs for the MatchmakerMultiPaxos steady-state write path
and the matchmaker-epoch-change cold path.

The per-command hot loop (ClientRequest -> Phase2a -> Phase2b ->
Chosen -> ClientReply, Matchmaker.proto's MultiPaxos core) rides tags
48-52. The matchmaker self-reconfiguration single-decree Paxos
(MatchPhase1a/1b/2a/2b/MatchChosen/MatchNack), the Stopped bounce and
the GC pair ride extended tags 181-189 (paxsafe COD301 burn-down):
per-epoch traffic, but it is exactly what is on the wire during a
matchmaker failover, and pickled frames are refused under
``set_pickle_fallback(False)``. Only Stop/StopAck/Bootstrap/
BootstrapAck/ReconfigureMatchmakers (whole-log transfers carrying
round -> quorum-system DICTS) stay pickled.
"""

from __future__ import annotations

import struct

from frankenpaxos_tpu.protocols import matchmakermultipaxos as m
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_address,
    _put_bytes,
    _take_address,
    _take_bytes,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I32 = struct.Struct("<i")
_I32I32 = struct.Struct("<ii")
_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")
_QQQ = struct.Struct("<qqq")


def _put_command(out: bytearray, command: m.Command) -> None:
    cid = command.command_id
    _put_address(out, cid.client_address)
    out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
    _put_bytes(out, command.command)


def _take_command(buf: bytes, at: int):
    address, at = _take_address(buf, at)
    pseudonym, id = _I64I64.unpack_from(buf, at)
    payload, at = _take_bytes(buf, at + 16)
    return m.Command(m.CommandId(address, pseudonym, id), payload), at


def _put_value(out: bytearray, value) -> None:
    if isinstance(value, m.Noop):
        out.append(0)
    else:
        out.append(1)
        _put_command(out, value)


def _take_value(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    if kind == 0:
        return m.NOOP, at
    return _take_command(buf, at)


class MMPClientRequestCodec(MessageCodec):
    message_type = m.ClientRequest
    tag = 48

    def encode(self, out, message):
        _put_command(out, message.command)

    def decode(self, buf, at):
        command, at = _take_command(buf, at)
        return m.ClientRequest(command), at


class MMPPhase2aCodec(MessageCodec):
    message_type = m.Phase2a
    tag = 49

    def encode(self, out, message):
        out += _I64I64.pack(message.slot, message.round)
        _put_value(out, message.value)

    def decode(self, buf, at):
        slot, round = _I64I64.unpack_from(buf, at)
        value, at = _take_value(buf, at + 16)
        return m.Phase2a(slot=slot, round=round, value=value), at


class MMPPhase2bCodec(MessageCodec):
    message_type = m.Phase2b
    tag = 50

    def encode(self, out, message):
        out += _QQQ.pack(message.slot, message.round,
                         message.acceptor_index)

    def decode(self, buf, at):
        slot, round, acceptor = _QQQ.unpack_from(buf, at)
        return m.Phase2b(slot=slot, round=round,
                         acceptor_index=acceptor), at + _QQQ.size


class MMPChosenCodec(MessageCodec):
    message_type = m.Chosen
    tag = 51

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        _put_value(out, message.value)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        value, at = _take_value(buf, at + 8)
        return m.Chosen(slot=slot, value=value), at


class MMPClientReplyCodec(MessageCodec):
    message_type = m.ClientReply
    tag = 52

    def encode(self, out, message):
        cid = message.command_id
        _put_address(out, cid.client_address)
        out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
        _put_bytes(out, message.result)

    def decode(self, buf, at):
        address, at = _take_address(buf, at)
        pseudonym, id = _I64I64.unpack_from(buf, at)
        result, at = _take_bytes(buf, at + 16)
        return m.ClientReply(m.CommandId(address, pseudonym, id),
                             result), at


def _put_mc(out: bytearray, mc: m.MatchmakerConfiguration) -> None:
    out += _I64.pack(mc.epoch)
    out += _I32.pack(mc.reconfigurer_index)
    out += _I32.pack(len(mc.matchmaker_indices))
    for index in mc.matchmaker_indices:
        out += _I32.pack(index)


def _take_mc(buf: bytes, at: int):
    (epoch,) = _I64.unpack_from(buf, at)
    reconfigurer, n = _I32I32.unpack_from(buf, at + 8)
    if n < 0 or n > (len(buf) - at - 16) // 4:
        raise ValueError(f"hostile matchmaker-index count {n}")
    at += 16
    indices = []
    for _ in range(n):
        (index,) = _I32.unpack_from(buf, at)
        if not 0 <= index < (1 << 20):
            # Value validation at the trust boundary (see
            # fasterpaxos_wire._take_delegates): out-of-range indices
            # must die as corrupt frames, not as IndexErrors (or
            # silent negative-index wraps) inside the matchmaker.
            raise ValueError(f"hostile matchmaker index {index}")
        indices.append(index)
        at += 4
    return m.MatchmakerConfiguration(epoch, reconfigurer,
                                     tuple(indices)), at


class MMPStoppedCodec(MessageCodec):
    message_type = m.Stopped
    tag = 181

    def encode(self, out, message):
        out += _I64.pack(message.epoch)

    def decode(self, buf, at):
        (epoch,) = _I64.unpack_from(buf, at)
        return m.Stopped(epoch=epoch), at + 8


class MMPGarbageCollectCodec(MessageCodec):
    message_type = m.GarbageCollect
    tag = 182

    def encode(self, out, message):
        _put_mc(out, message.matchmaker_configuration)
        out += _I64.pack(message.gc_watermark)

    def decode(self, buf, at):
        mc, at = _take_mc(buf, at)
        (watermark,) = _I64.unpack_from(buf, at)
        return m.GarbageCollect(mc, watermark), at + 8


class MMPGarbageCollectAckCodec(MessageCodec):
    message_type = m.GarbageCollectAck
    tag = 183

    def encode(self, out, message):
        out += _I64.pack(message.epoch)
        out += _I32.pack(message.matchmaker_index)
        out += _I64.pack(message.gc_watermark)

    def decode(self, buf, at):
        (epoch,) = _I64.unpack_from(buf, at)
        (index,) = _I32.unpack_from(buf, at + 8)
        (watermark,) = _I64.unpack_from(buf, at + 12)
        return m.GarbageCollectAck(epoch, index, watermark), at + 20


class MMPMatchPhase1aCodec(MessageCodec):
    message_type = m.MatchPhase1a
    tag = 184

    def encode(self, out, message):
        _put_mc(out, message.matchmaker_configuration)
        out += _I64.pack(message.round)

    def decode(self, buf, at):
        mc, at = _take_mc(buf, at)
        (round,) = _I64.unpack_from(buf, at)
        return m.MatchPhase1a(mc, round), at + 8


class MMPMatchPhase1bCodec(MessageCodec):
    message_type = m.MatchPhase1b
    tag = 185

    def encode(self, out, message):
        out += _I64I64.pack(message.epoch, message.round)
        out += _I32.pack(message.matchmaker_index)
        out += _I64.pack(message.vote_round)
        if message.vote_value is None:
            out.append(0)
        else:
            out.append(1)
            _put_mc(out, message.vote_value)

    def decode(self, buf, at):
        epoch, round = _I64I64.unpack_from(buf, at)
        (index,) = _I32.unpack_from(buf, at + 16)
        (vote_round,) = _I64.unpack_from(buf, at + 20)
        at += 28
        kind = buf[at]
        at += 1
        vote_value = None
        if kind == 1:
            vote_value, at = _take_mc(buf, at)
        elif kind != 0:
            raise ValueError(f"bad MatchPhase1b vote flag {kind}")
        return m.MatchPhase1b(epoch=epoch, round=round,
                              matchmaker_index=index,
                              vote_round=vote_round,
                              vote_value=vote_value), at


class MMPMatchPhase2aCodec(MessageCodec):
    message_type = m.MatchPhase2a
    tag = 186

    def encode(self, out, message):
        _put_mc(out, message.matchmaker_configuration)
        out += _I64.pack(message.round)
        _put_mc(out, message.value)

    def decode(self, buf, at):
        mc, at = _take_mc(buf, at)
        (round,) = _I64.unpack_from(buf, at)
        value, at = _take_mc(buf, at + 8)
        return m.MatchPhase2a(mc, round, value), at


class MMPMatchPhase2bCodec(MessageCodec):
    message_type = m.MatchPhase2b
    tag = 187

    def encode(self, out, message):
        out += _I64I64.pack(message.epoch, message.round)
        out += _I32.pack(message.matchmaker_index)

    def decode(self, buf, at):
        epoch, round = _I64I64.unpack_from(buf, at)
        (index,) = _I32.unpack_from(buf, at + 16)
        return m.MatchPhase2b(epoch=epoch, round=round,
                              matchmaker_index=index), at + 20


class MMPMatchChosenCodec(MessageCodec):
    message_type = m.MatchChosen
    tag = 188

    def encode(self, out, message):
        _put_mc(out, message.value)

    def decode(self, buf, at):
        value, at = _take_mc(buf, at)
        return m.MatchChosen(value), at


class MMPMatchNackCodec(MessageCodec):
    message_type = m.MatchNack
    tag = 189

    def encode(self, out, message):
        out += _I64I64.pack(message.epoch, message.round)

    def decode(self, buf, at):
        epoch, round = _I64I64.unpack_from(buf, at)
        return m.MatchNack(epoch=epoch, round=round), at + 16


for _codec in (MMPClientRequestCodec(), MMPPhase2aCodec(),
               MMPPhase2bCodec(), MMPChosenCodec(),
               MMPClientReplyCodec(), MMPStoppedCodec(),
               MMPGarbageCollectCodec(), MMPGarbageCollectAckCodec(),
               MMPMatchPhase1aCodec(), MMPMatchPhase1bCodec(),
               MMPMatchPhase2aCodec(), MMPMatchPhase2bCodec(),
               MMPMatchChosenCodec(), MMPMatchNackCodec()):
    register_codec(_codec)
