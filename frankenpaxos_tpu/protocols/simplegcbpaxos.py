"""Simple GC BPaxos: SimpleBPaxos plus vertex garbage collection.

Reference behavior: simplegcbpaxos/ (GarbageCollector.scala:56-180,
Proposer.scala:599-626, Acceptor.scala:269-287, Replica.scala:500-600,
DepServiceNode GC). Replicas gossip their executed frontier (a
per-leader watermark vector) to GarbageCollector nodes every N
executions; collectors relay GarbageCollect to proposers, acceptors, and
dep service nodes, which fold the frontiers into an f+1
QuorumWatermarkVector and prune all per-vertex state below the quorum
watermark -- once f+1 replicas have executed a vertex, its consensus
state is unrecoverable-needed and reclaimable.

Replicas that fall behind the GC watermark catch up from snapshots
(Replica.scala:195-214, 496-560, 743-880): every
``snapshot_every_n * num_replicas`` executed commands a replica asks a
leader to propose a *snapshot vertex* (SnapshotRequest,
Leader.scala:246-251). The dep service makes it depend on everything it
has seen and makes later commands depend on it
(DepServiceNode.scala:269-300 putSnapshot). Executing the snapshot
vertex captures (state machine bytes, client table, executed-vertex
watermark); a replica whose Recover hits a peer that already garbage
collected the vertex receives the whole snapshot as a CommitSnapshot
and re-executes only its unsnapshotted history on top.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from frankenpaxos_tpu.clienttable import ClientTable
from frankenpaxos_tpu.protocols.simplebpaxos.messages import (
    Commit,
    Recover,
    SimpleBPaxosConfig,
    VertexId,
    VertexIdPrefixSet,
)
from frankenpaxos_tpu.protocols.simplebpaxos.replica import BPaxosReplica
from frankenpaxos_tpu.protocols.simplebpaxos.roles import (
    BPaxosAcceptor,
    BPaxosDepServiceNode,
    BPaxosLeader,
    BPaxosProposer,
)
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.utils.watermark import QuorumWatermarkVector


@dataclasses.dataclass(frozen=True)
class GcBPaxosConfig(SimpleBPaxosConfig):
    garbage_collector_addresses: tuple = ()

    def check_valid(self) -> None:
        super().check_valid()
        if len(self.garbage_collector_addresses) \
                != len(self.replica_addresses):
            raise ValueError("collectors must mirror replicas")


@dataclasses.dataclass(frozen=True)
class GarbageCollect:
    replica_index: int
    frontier: tuple[int, ...]  # per-leader executed watermark vector


@dataclasses.dataclass(frozen=True)
class SnapshotMarker:
    """A proposal value meaning 'snapshot here' (the reference's
    CommandOrSnapshot Snapshot arm, SimpleGcBPaxos.proto:91-122)."""


SNAPSHOT = SnapshotMarker()


@dataclasses.dataclass(frozen=True)
class SnapshotRequest:
    """Replica -> leader: please get a snapshot vertex chosen
    (Replica.scala:595-604, Leader.scala:246-251)."""


@dataclasses.dataclass(frozen=True)
class CommitSnapshot:
    """A full snapshot, sent to a replica whose Recover hit a vertex we
    already garbage collected (Replica.scala:743-756)."""

    id: int
    watermark: dict  # VertexIdPrefixSet wire form
    state_machine: bytes
    client_table: dict  # ClientTable wire form


@dataclasses.dataclass
class _Snapshot:
    id: int
    watermark: VertexIdPrefixSet
    state_machine: bytes
    client_table: dict


class GarbageCollector(Actor):
    """Relays GarbageCollect to proposers, acceptors, and dep nodes
    (GarbageCollector.scala:56-180)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: GcBPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, GarbageCollect):
            self.logger.fatal(f"unexpected collector message {message!r}")
        for dst in (tuple(self.config.proposer_addresses)
                    + tuple(self.config.acceptor_addresses)
                    + tuple(self.config.dep_service_node_addresses)):
            self.send(dst, message)


class _GcWatermarkMixin:
    """Fold GarbageCollect frontiers into an f+1 quorum watermark vector
    and prune per-vertex state below it.

    ``gc_backend="tpu"`` evaluates the quorum-watermark reduction on
    device (ops/watermark.py: sort + index over the [replicas x leaders]
    frontier matrix -- the QuorumWatermark.scala:31-50 math as one
    batched kernel); ``"host"`` is the numpy oracle.
    """

    def _init_gc(self, config: GcBPaxosConfig,
                 gc_backend: str = "host") -> None:
        if gc_backend not in ("host", "tpu"):
            raise ValueError(f"unknown gc backend {gc_backend!r}")
        self._gc_backend = gc_backend
        self._gc_vector = QuorumWatermarkVector(
            n=len(config.replica_addresses),
            depth=len(config.leader_addresses))
        self.gc_watermark = [0] * len(config.leader_addresses)

    def _handle_garbage_collect(self, message: GarbageCollect) -> None:
        self._gc_vector.update(message.replica_index, message.frontier)
        self.gc_watermark = self._gc_vector.watermark(
            quorum_size=self.config.f + 1, backend=self._gc_backend)
        self._prune()

    def _collectable(self, vertex_id: VertexId) -> bool:
        return vertex_id.instance_number \
            < self.gc_watermark[vertex_id.replica_index]

    def _prune(self) -> None:
        for vertex_id in [v for v in self.states if self._collectable(v)]:
            state = self.states.pop(vertex_id)
            resend = getattr(state, "resend", None)
            if resend is not None:
                resend.stop()


class GcBPaxosLeader(BPaxosLeader):
    """BPaxosLeader that can also get snapshot vertices chosen
    (Leader.scala:246-251): a SnapshotRequest is handled exactly like a
    client request whose 'command' is the snapshot marker."""

    def receive(self, src: Address, message) -> None:
        if isinstance(message, SnapshotRequest):
            self._start_vertex(SNAPSHOT)
            return
        super().receive(src, message)


class GcBPaxosProposer(_GcWatermarkMixin, BPaxosProposer):
    def __init__(self, *args, gc_backend: str = "host", **kwargs):
        super().__init__(*args, **kwargs)
        self._init_gc(self.config, gc_backend)

    def receive(self, src: Address, message) -> None:
        if isinstance(message, GarbageCollect):
            self._handle_garbage_collect(message)
            return
        if isinstance(message, Recover) \
                and self._collectable(message.vertex_id):
            # The vertex was garbage collected: f+1 replicas executed
            # it, so the recovering replica will get it from a peer's
            # snapshot instead. Proposing a fresh noop here would run
            # consensus against acceptors that pruned their votes.
            return
        super().receive(src, message)


class GcBPaxosAcceptor(_GcWatermarkMixin, BPaxosAcceptor):
    def __init__(self, *args, gc_backend: str = "host", **kwargs):
        super().__init__(*args, **kwargs)
        self._init_gc(self.config, gc_backend)

    def receive(self, src: Address, message) -> None:
        if isinstance(message, GarbageCollect):
            self._handle_garbage_collect(message)
            return
        super().receive(src, message)


class GcBPaxosDepServiceNode(_GcWatermarkMixin, BPaxosDepServiceNode):
    def __init__(self, *args, gc_backend: str = "host", **kwargs):
        super().__init__(*args, **kwargs)
        self._init_gc(self.config, gc_backend)
        # Highest vertex id + 1 seen per leader column, and the latest
        # snapshot vertex: a snapshot depends on everything seen before
        # it, and everything after depends on the snapshot
        # (DepServiceNode.scala:269-300 putSnapshot/highWatermark).
        self._high_watermark = [0] * len(self.config.leader_addresses)
        self._last_snapshot: Optional[VertexId] = None

    def _prune(self) -> None:
        # Dep nodes prune the dependency cache, not per-vertex consensus
        # state. Top-k conflict indexes don't support removal; stale
        # entries only add extra dependencies, which is safe
        # (DepServiceNode "fast conflict indexes don't remove").
        for vertex_id in [v for v in self.dependencies_cache
                          if self._collectable(v)]:
            del self.dependencies_cache[vertex_id]

    def receive(self, src: Address, message) -> None:
        if isinstance(message, GarbageCollect):
            self._handle_garbage_collect(message)
            return
        super().receive(src, message)

    def _compute_dependencies(self, vertex_id: VertexId,
                              command) -> VertexIdPrefixSet:
        """Snapshot vertices depend on everything seen; later commands
        depend on the latest snapshot (DepServiceNode.scala:269-300).
        Both are computed before the first reply is cached, keeping deps
        deterministic across re-asks."""
        if isinstance(command, SnapshotMarker):
            dependencies = VertexIdPrefixSet.from_watermarks(
                self._high_watermark)
            if self._last_snapshot is not None:
                dependencies.add(self._last_snapshot)
            dependencies.subtract_one(vertex_id)
            self._last_snapshot = vertex_id
        else:
            dependencies = super()._compute_dependencies(vertex_id, command)
            if self._last_snapshot is not None:
                dependencies.add(self._last_snapshot)
        column = vertex_id.replica_index
        self._high_watermark[column] = max(self._high_watermark[column],
                                           vertex_id.instance_number + 1)
        return dependencies


class GcBPaxosReplica(BPaxosReplica):
    """Gossips its executed frontier every N executions
    (Replica.scala:575-600), periodically requests snapshot vertices,
    answers peer Recovers from its snapshot, and catches up from
    CommitSnapshots (Replica.scala:496-560, 743-880)."""

    def __init__(self, *args, send_gc_every_n: int = 10,
                 snapshot_every_n: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        self.send_gc_every_n = send_gc_every_n
        self.snapshot_every_n = snapshot_every_n
        self._since_gc_send = 0
        # Staggered so replicas request snapshots at different times
        # (Replica.scala:274-279).
        self._since_snapshot_request = snapshot_every_n * self.index
        num_leaders = len(self.config.leader_addresses)
        # Contiguous executed prefix per leader column.
        self._frontier = [0] * num_leaders
        # Every executed vertex (incl. noops and snapshots), the
        # snapshot watermark source (Replica.scala:353-365).
        self.executed_vertices = VertexIdPrefixSet(num_leaders)
        self.snapshot: Optional[_Snapshot] = None
        # Command vertices actually run since the last snapshot, in
        # execution order (Replica.scala:368-374).
        self.history: list[VertexId] = []

    # --- execution hooks --------------------------------------------------
    def _unexecuted_dependencies(self, dependencies) -> set:
        # Snapshot vertices depend on the entire seen history; only the
        # unexecuted remainder constrains execution order, and only it
        # is worth materializing.
        return dependencies.materialized_diff(self.executed_vertices)

    def _execute(self, vertex_id: VertexId, value) -> None:
        self.executed_vertices.add(vertex_id)
        if isinstance(value, SnapshotMarker):
            self._take_snapshot()
        else:
            before = self.executed_count
            super()._execute(vertex_id, value)
            if self.executed_count > before:
                self.history.append(vertex_id)
        self._after_execute(vertex_id)

    def _after_execute(self, vertex_id: VertexId) -> None:
        # Advance the contiguous frontier for the vertex's column.
        column = vertex_id.replica_index
        executed = self.dependency_graph.executed
        while VertexId(column, self._frontier[column]) in executed:
            self._frontier[column] += 1
        self._since_gc_send += 1
        if self._since_gc_send >= self.send_gc_every_n:
            self._since_gc_send = 0
            self.send(self.config.garbage_collector_addresses[self.index],
                      GarbageCollect(replica_index=self.index,
                                     frontier=tuple(self._frontier)))
        if self.snapshot_every_n > 0:
            self._since_snapshot_request += 1
            n = self.snapshot_every_n * len(self.config.replica_addresses)
            if self._since_snapshot_request % n == 0:
                self._since_snapshot_request = 0
                leader = self.rng.choice(self.config.leader_addresses)
                self.send(leader, SnapshotRequest())

    def _take_snapshot(self) -> None:
        """Capture (sm bytes, client table, executed watermark) and drop
        snapshotted per-vertex state (Replica.scala:508-531)."""
        self.snapshot = _Snapshot(
            id=self.snapshot.id + 1 if self.snapshot else 0,
            watermark=self.executed_vertices.copy(),
            state_machine=self.state_machine.to_bytes(),
            client_table=self.client_table.to_dict())
        self.history.clear()
        self._prune_commands_below(self.executed_vertices.watermarks())

    def _prune_commands_below(self, watermarks: list[int]) -> None:
        for vertex_id in [v for v in self.commands
                          if v.instance_number
                          < watermarks[v.replica_index]]:
            del self.commands[vertex_id]

    # --- recovery ---------------------------------------------------------
    def _make_recover_timer(self, vertex_id: VertexId) -> object:
        attempt = [0]

        def fire():
            # Ask the vertex's proposer (noop if nothing was proposed)
            # AND one peer replica, rotating per attempt: if proposers
            # already garbage collected the vertex, only a peer's
            # snapshot has it (Replica.scala:607-650) -- but asking
            # every peer at once would pull one snapshot-sized reply
            # per peer when the first suffices.
            self.send(self.config.proposer_addresses[
                vertex_id.replica_index % len(
                    self.config.proposer_addresses)],
                Recover(vertex_id=vertex_id))
            peers = [i for i in range(len(self.config.replica_addresses))
                     if i != self.index]
            if peers:
                peer = peers[attempt[0] % len(peers)]
                attempt[0] += 1
                self.send(self.config.replica_addresses[peer],
                          Recover(vertex_id=vertex_id))
            timer.start()

        timer = self.timer(f"recoverVertex {vertex_id}",
                           self.rng.uniform(self.recover_min,
                                            self.recover_max), fire)
        timer.start()
        return timer

    def receive(self, src: Address, message) -> None:
        if isinstance(message, Recover):
            self._handle_peer_recover(src, message)
            return
        if isinstance(message, CommitSnapshot):
            self._handle_commit_snapshot(src, message)
            return
        super().receive(src, message)

    def _handle_peer_recover(self, src: Address, recover: Recover) -> None:
        """A peer is missing a vertex: send our snapshot if it swallowed
        the vertex, else the Commit if we still have it
        (Replica.scala:743-786)."""
        vertex_id = recover.vertex_id
        committed = self.commands.get(vertex_id)
        if committed is not None:
            # A single Commit is a much cheaper answer than the whole
            # snapshot; prefer it whenever we still have the vertex.
            self.send(src, Commit(
                vertex_id=vertex_id,
                command_or_noop=committed.command_or_noop,
                dependencies=committed.dependencies.copy()))
            return
        if self.snapshot is not None \
                and self.snapshot.watermark.contains(vertex_id):
            self.send(src, CommitSnapshot(
                id=self.snapshot.id,
                watermark=self.snapshot.watermark.to_dict(),
                state_machine=self.snapshot.state_machine,
                client_table=self.snapshot.client_table))

    def _handle_commit_snapshot(self, src: Address,
                                commit: CommitSnapshot) -> None:
        """Adopt a newer snapshot wholesale, then re-execute our
        unsnapshotted suffix on top (Replica.scala:788-880)."""
        if self.snapshot is not None and commit.id <= self.snapshot.id:
            return
        watermark = VertexIdPrefixSet.from_dict(commit.watermark)
        # Only vertices the snapshot newly marks executed need to reach
        # the dependency graph (bounds the materialization). The diff is
        # lazy -- force it before add_all mutates executed_vertices.
        newly_executed = list(
            watermark.materialized_diff(self.executed_vertices))
        self.state_machine.from_bytes(commit.state_machine)
        self.client_table = ClientTable.from_dict(commit.client_table)
        self.executed_vertices.add_all(watermark)
        self.snapshot = _Snapshot(commit.id, watermark.copy(),
                                  commit.state_machine, commit.client_table)
        # Recovery timers for snapshotted vertices are moot.
        for vertex_id in [v for v in self.recover_vertex_timers
                          if watermark.contains(v)]:
            self.recover_vertex_timers.pop(vertex_id).stop()
        # Drop per-vertex state the snapshot covers.
        watermarks = watermark.watermarks()
        self._prune_commands_below(watermarks)
        for column, mark in enumerate(watermarks):
            self._frontier[column] = max(self._frontier[column], mark)
        # Re-execute executed-but-unsnapshotted commands: their effects
        # were wiped when we replaced the state machine.
        old_history, self.history = self.history, []
        for vertex_id in old_history:
            if watermark.contains(vertex_id):
                continue
            committed = self.commands.get(vertex_id)
            if committed is None:
                self.logger.fatal(
                    f"unsnapshotted history vertex {vertex_id} has no "
                    f"Committed entry")
            self._execute(vertex_id, committed.command_or_noop)
        # Tell the graph, then see what became eligible.
        self.dependency_graph.update_executed(newly_executed)
        self._execute_graph()


# Register the snapshot cold-path codecs (tags 206-207). At the bottom
# to dodge the import cycle: the codec module imports our dataclasses.
from frankenpaxos_tpu.protocols import simplegcbpaxos_wire  # noqa: E402,F401
