"""Simple GC BPaxos: SimpleBPaxos plus vertex garbage collection.

Reference behavior: simplegcbpaxos/ (GarbageCollector.scala:56-180,
Proposer.scala:599-626, Acceptor.scala:269-287, Replica.scala:500-600,
DepServiceNode GC). Replicas gossip their executed frontier (a
per-leader watermark vector) to GarbageCollector nodes every N
executions; collectors relay GarbageCollect to proposers, acceptors, and
dep service nodes, which fold the frontiers into an f+1
QuorumWatermarkVector and prune all per-vertex state below the quorum
watermark -- once f+1 replicas have executed a vertex, its consensus
state is unrecoverable-needed and reclaimable.

(The reference also supports snapshot commands, CommitSnapshot, for
replicas that fall far behind; here recovery below the GC watermark is
handled by the noop-recovery path instead. Snapshot-command parity is a
round-2 item.)
"""

from __future__ import annotations

import dataclasses

from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.utils.watermark import QuorumWatermarkVector
from frankenpaxos_tpu.protocols.simplebpaxos.messages import (
    SimpleBPaxosConfig,
    VertexId,
)
from frankenpaxos_tpu.protocols.simplebpaxos.replica import BPaxosReplica
from frankenpaxos_tpu.protocols.simplebpaxos.roles import (
    BPaxosAcceptor,
    BPaxosDepServiceNode,
    BPaxosProposer,
)


@dataclasses.dataclass(frozen=True)
class GcBPaxosConfig(SimpleBPaxosConfig):
    garbage_collector_addresses: tuple = ()

    def check_valid(self) -> None:
        super().check_valid()
        if len(self.garbage_collector_addresses) \
                != len(self.replica_addresses):
            raise ValueError("collectors must mirror replicas")


@dataclasses.dataclass(frozen=True)
class GarbageCollect:
    replica_index: int
    frontier: tuple[int, ...]  # per-leader executed watermark vector


class GarbageCollector(Actor):
    """Relays GarbageCollect to proposers, acceptors, and dep nodes
    (GarbageCollector.scala:56-180)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: GcBPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, GarbageCollect):
            self.logger.fatal(f"unexpected collector message {message!r}")
        for dst in (tuple(self.config.proposer_addresses)
                    + tuple(self.config.acceptor_addresses)
                    + tuple(self.config.dep_service_node_addresses)):
            self.send(dst, message)


class _GcWatermarkMixin:
    """Fold GarbageCollect frontiers into an f+1 quorum watermark vector
    and prune per-vertex state below it."""

    def _init_gc(self, config: GcBPaxosConfig) -> None:
        self._gc_vector = QuorumWatermarkVector(
            n=len(config.replica_addresses),
            depth=len(config.leader_addresses))
        self.gc_watermark = [0] * len(config.leader_addresses)

    def _handle_garbage_collect(self, message: GarbageCollect) -> None:
        self._gc_vector.update(message.replica_index, message.frontier)
        self.gc_watermark = self._gc_vector.watermark(
            quorum_size=self.config.f + 1)
        self._prune()

    def _collectable(self, vertex_id: VertexId) -> bool:
        return vertex_id.instance_number \
            < self.gc_watermark[vertex_id.replica_index]

    def _prune(self) -> None:
        for vertex_id in [v for v in self.states if self._collectable(v)]:
            state = self.states.pop(vertex_id)
            resend = getattr(state, "resend", None)
            if resend is not None:
                resend.stop()


class GcBPaxosProposer(_GcWatermarkMixin, BPaxosProposer):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._init_gc(self.config)

    def receive(self, src: Address, message) -> None:
        if isinstance(message, GarbageCollect):
            self._handle_garbage_collect(message)
            return
        super().receive(src, message)


class GcBPaxosAcceptor(_GcWatermarkMixin, BPaxosAcceptor):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._init_gc(self.config)

    def receive(self, src: Address, message) -> None:
        if isinstance(message, GarbageCollect):
            self._handle_garbage_collect(message)
            return
        super().receive(src, message)


class GcBPaxosDepServiceNode(BPaxosDepServiceNode):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._gc_vector = QuorumWatermarkVector(
            n=len(self.config.replica_addresses),
            depth=len(self.config.leader_addresses))
        self.gc_watermark = [0] * len(self.config.leader_addresses)

    def receive(self, src: Address, message) -> None:
        if isinstance(message, GarbageCollect):
            self._gc_vector.update(message.replica_index, message.frontier)
            self.gc_watermark = self._gc_vector.watermark(
                quorum_size=self.config.f + 1)
            for vertex_id in [
                    v for v in self.dependencies_cache
                    if v.instance_number
                    < self.gc_watermark[v.replica_index]]:
                del self.dependencies_cache[vertex_id]
                # Top-k conflict indexes don't support removal; stale
                # entries only add extra dependencies, which is safe
                # (DepServiceNode "fast conflict indexes don't remove").
            return
        super().receive(src, message)


class GcBPaxosReplica(BPaxosReplica):
    """Gossips its executed frontier every N executions
    (Replica.scala:575-600)."""

    def __init__(self, *args, send_gc_every_n: int = 10, **kwargs):
        super().__init__(*args, **kwargs)
        self.send_gc_every_n = send_gc_every_n
        self._since_gc_send = 0
        num_leaders = len(self.config.leader_addresses)
        # Contiguous executed prefix per leader column.
        self._frontier = [0] * num_leaders

    def _execute(self, vertex_id: VertexId, value) -> None:
        super()._execute(vertex_id, value)
        # Advance the contiguous frontier for the vertex's column.
        column = vertex_id.replica_index
        executed = self.dependency_graph.executed
        while VertexId(column, self._frontier[column]) in executed:
            self._frontier[column] += 1
        self._since_gc_send += 1
        if self._since_gc_send >= self.send_gc_every_n:
            self._since_gc_send = 0
            self.send(self.config.garbage_collector_addresses[self.index],
                      GarbageCollect(replica_index=self.index,
                                     frontier=tuple(self._frontier)))
