"""Binary codecs for the Horizontal MultiPaxos hot path.

The steady-state write path (ClientRequest -> Phase2a -> Phase2b ->
Chosen -> ClientReply, horizontal/Horizontal.proto). A Value is a
Command, Noop, or Configuration; configurations (rare: one per
reconfiguration) ride a pickled escape hatch inside the value slot.
"""

from __future__ import annotations

import pickle
import struct

from frankenpaxos_tpu.protocols import horizontal as m
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_address,
    _put_bytes,
    _take_address,
    _take_bytes,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")
_QQQ = struct.Struct("<qqq")


def _put_command(out: bytearray, command: m.Command) -> None:
    cid = command.command_id
    _put_address(out, cid.client_address)
    out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
    _put_bytes(out, command.command)


def _take_command(buf: bytes, at: int):
    address, at = _take_address(buf, at)
    pseudonym, id = _I64I64.unpack_from(buf, at)
    payload, at = _take_bytes(buf, at + 16)
    return m.Command(m.CommandId(address, pseudonym, id), payload), at


def _put_value(out: bytearray, value) -> None:
    if isinstance(value, m.Noop):
        out.append(0)
    elif isinstance(value, m.Command):
        out.append(1)
        _put_command(out, value)
    else:  # Configuration (one per reconfiguration -- cold)
        from frankenpaxos_tpu.runtime import serializer

        out.append(2)
        _put_bytes(out, serializer.guarded_pickle_dumps(value, "value"))


def _take_value(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    if kind == 0:
        return m.NOOP, at
    if kind == 1:
        return _take_command(buf, at)
    from frankenpaxos_tpu.runtime import serializer

    raw, at = _take_bytes(buf, at)
    return serializer.guarded_pickle_loads(raw, "value"), at


class HClientRequestCodec(MessageCodec):
    message_type = m.ClientRequest
    tag = 43

    def encode(self, out, message):
        _put_command(out, message.command)

    def decode(self, buf, at):
        command, at = _take_command(buf, at)
        return m.ClientRequest(command), at


class HPhase2aCodec(MessageCodec):
    message_type = m.Phase2a
    tag = 44

    def encode(self, out, message):
        out += _QQQ.pack(message.slot, message.round, message.first_slot)
        _put_value(out, message.value)

    def decode(self, buf, at):
        slot, round, first_slot = _QQQ.unpack_from(buf, at)
        value, at = _take_value(buf, at + _QQQ.size)
        return m.Phase2a(slot=slot, round=round, first_slot=first_slot,
                         value=value), at


class HPhase2bCodec(MessageCodec):
    message_type = m.Phase2b
    tag = 45

    def encode(self, out, message):
        out += _QQQ.pack(message.slot, message.round,
                         message.acceptor_index)

    def decode(self, buf, at):
        slot, round, acceptor = _QQQ.unpack_from(buf, at)
        return m.Phase2b(slot=slot, round=round,
                         acceptor_index=acceptor), at + _QQQ.size


class HChosenCodec(MessageCodec):
    message_type = m.Chosen
    tag = 46

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        _put_value(out, message.value)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        value, at = _take_value(buf, at + 8)
        return m.Chosen(slot=slot, value=value), at


class HClientReplyCodec(MessageCodec):
    message_type = m.ClientReply
    tag = 47

    def encode(self, out, message):
        cid = message.command_id
        _put_address(out, cid.client_address)
        out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
        _put_bytes(out, message.result)

    def decode(self, buf, at):
        address, at = _take_address(buf, at)
        pseudonym, id = _I64I64.unpack_from(buf, at)
        result, at = _take_bytes(buf, at + 16)
        return m.ClientReply(m.CommandId(address, pseudonym, id),
                             result), at


for _codec in (HClientRequestCodec(), HPhase2aCodec(), HPhase2bCodec(),
               HChosenCodec(), HClientReplyCodec()):
    register_codec(_codec)
