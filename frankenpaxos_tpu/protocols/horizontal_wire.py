"""Binary codecs for the Horizontal MultiPaxos hot path.

The steady-state write path (ClientRequest -> Phase2a -> Phase2b ->
Chosen -> ClientReply, horizontal/Horizontal.proto). A Value is a
Command, Noop, or Configuration; configurations (rare: one per
reconfiguration) ride a pickled escape hatch inside the value slot.
"""

from __future__ import annotations

import pickle
import struct

from frankenpaxos_tpu.protocols import horizontal as m
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_address,
    _put_bytes,
    _take_address,
    _take_bytes,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")
_QQQ = struct.Struct("<qqq")


def _put_command(out: bytearray, command: m.Command) -> None:
    cid = command.command_id
    _put_address(out, cid.client_address)
    out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
    _put_bytes(out, command.command)


def _take_command(buf: bytes, at: int):
    address, at = _take_address(buf, at)
    pseudonym, id = _I64I64.unpack_from(buf, at)
    payload, at = _take_bytes(buf, at + 16)
    return m.Command(m.CommandId(address, pseudonym, id), payload), at


def _put_value(out: bytearray, value) -> None:
    if isinstance(value, m.Noop):
        out.append(0)
    elif isinstance(value, m.Command):
        out.append(1)
        _put_command(out, value)
    else:  # Configuration (one per reconfiguration -- cold)
        from frankenpaxos_tpu.runtime import serializer

        out.append(2)
        _put_bytes(out, serializer.guarded_pickle_dumps(value, "value"))


def _take_value(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    if kind == 0:
        return m.NOOP, at
    if kind == 1:
        return _take_command(buf, at)
    from frankenpaxos_tpu.runtime import serializer

    raw, at = _take_bytes(buf, at)
    return serializer.guarded_pickle_loads(raw, "value"), at


class HClientRequestCodec(MessageCodec):
    message_type = m.ClientRequest
    tag = 43

    def encode(self, out, message):
        _put_command(out, message.command)

    def decode(self, buf, at):
        command, at = _take_command(buf, at)
        return m.ClientRequest(command), at


class HPhase2aCodec(MessageCodec):
    message_type = m.Phase2a
    tag = 44

    def encode(self, out, message):
        out += _QQQ.pack(message.slot, message.round, message.first_slot)
        _put_value(out, message.value)

    def decode(self, buf, at):
        slot, round, first_slot = _QQQ.unpack_from(buf, at)
        value, at = _take_value(buf, at + _QQQ.size)
        return m.Phase2a(slot=slot, round=round, first_slot=first_slot,
                         value=value), at


class HPhase2bCodec(MessageCodec):
    message_type = m.Phase2b
    tag = 45

    def encode(self, out, message):
        out += _QQQ.pack(message.slot, message.round,
                         message.acceptor_index)

    def decode(self, buf, at):
        slot, round, acceptor = _QQQ.unpack_from(buf, at)
        return m.Phase2b(slot=slot, round=round,
                         acceptor_index=acceptor), at + _QQQ.size


class HChosenCodec(MessageCodec):
    message_type = m.Chosen
    tag = 46

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        _put_value(out, message.value)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        value, at = _take_value(buf, at + 8)
        return m.Chosen(slot=slot, value=value), at


class HClientReplyCodec(MessageCodec):
    message_type = m.ClientReply
    tag = 47

    def encode(self, out, message):
        cid = message.command_id
        _put_address(out, cid.client_address)
        out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
        _put_bytes(out, message.result)

    def decode(self, buf, at):
        address, at = _take_address(buf, at)
        pseudonym, id = _I64I64.unpack_from(buf, at)
        result, at = _take_bytes(buf, at + 16)
        return m.ClientReply(m.CommandId(address, pseudonym, id),
                             result), at


# --- the reconfiguration/chaos cold path (COD301 burn-down, 179-180) --------

_I32 = struct.Struct("<i")
_QS_KINDS = {"simple_majority": 0, "unanimous_writes": 1, "grid": 2,
             "zone_grid": 3}
_QS_BY_CODE = {v: k for k, v in _QS_KINDS.items()}
_MAX_NODES = 4096


def _take_node_list(buf: bytes, at: int):
    (n,) = _I32.unpack_from(buf, at)
    at += 4
    if not 0 <= n <= _MAX_NODES:
        raise ValueError(f"malformed node list: count {n}")
    nodes = []
    for _ in range(n):
        (node,) = _I64.unpack_from(buf, at)
        nodes.append(node)
        at += 8
    return nodes, at


class HReconfigureCodec(MessageCodec):
    """The wire form of ``quorums.quorum_system_to_dict``: a kind
    byte plus the member list (flat kinds) or the row-major grid."""

    message_type = m.Reconfigure
    tag = 179

    def encode(self, out, message):
        d = message.quorum_system
        code = _QS_KINDS.get(d.get("kind"))
        if code is None:
            raise ValueError(f"unknown quorum system {d!r}")
        out.append(code)
        if code >= 2:
            grid = d["grid"]
            out += _I32.pack(len(grid))
            out += _I32.pack(len(grid[0]) if grid else 0)
            for row in grid:
                for node in row:
                    out += _I64.pack(node)
        else:
            out += _I32.pack(len(d["members"]))
            for node in d["members"]:
                out += _I64.pack(node)

    def decode(self, buf, at):
        kind = _QS_BY_CODE.get(buf[at])
        if kind is None:
            raise ValueError(f"unknown quorum system code {buf[at]}")
        at += 1
        if kind in ("grid", "zone_grid"):
            (rows,) = _I32.unpack_from(buf, at)
            (cols,) = _I32.unpack_from(buf, at + 4)
            at += 8
            if not (0 <= rows <= _MAX_NODES
                    and 0 <= cols <= _MAX_NODES):
                raise ValueError(f"malformed grid {rows}x{cols}")
            grid = []
            for _ in range(rows):
                row = []
                for _ in range(cols):
                    (node,) = _I64.unpack_from(buf, at)
                    row.append(node)
                    at += 8
                grid.append(row)
            return m.Reconfigure({"kind": kind, "grid": grid}), at
        members, at = _take_node_list(buf, at)
        return m.Reconfigure({"kind": kind, "members": members}), at


class HDieCodec(MessageCodec):
    message_type = m.Die
    tag = 180

    def encode(self, out, message):
        pass

    def decode(self, buf, at):
        return m.Die(), at


for _codec in (HClientRequestCodec(), HPhase2aCodec(), HPhase2bCodec(),
               HReconfigureCodec(), HDieCodec(),
               HChosenCodec(), HClientReplyCodec()):
    register_codec(_codec)
