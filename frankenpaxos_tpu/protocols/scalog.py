"""Scalog: a replicated shared log via per-shard logs + cut ordering.

Reference behavior: scalog/ (Server.scala:60-530, Aggregator.scala:69-470,
Leader/Acceptor = Paxos on cuts, Replica, ProxyReplica; Config.scala).

  * Servers (>= f+1 per shard): every server is primary of its own local
    log and backs up its shard-mates'. Client commands append locally and
    replicate to the shard (Backup). Servers periodically push their
    watermark vectors (ShardInfo) to the aggregator.
  * Aggregator: folds shard infos into pairwise-max shard cuts; every N
    infos proposes the flattened global cut to the Paxos leader; chosen
    raw cuts are pruned to a monotone sequence and redistributed to
    servers as CutChosen.
  * Leader/Acceptors: MultiPaxos on the log of cuts (f+1 leaders, 2f+1
    acceptors).
  * On CutChosen, each server projects the cut difference onto its local
    log (Server.projectCut, Server.scala:82-116) and sends the global
    slot range's commands to the replicas, which execute in order.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional, Union

from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.utils import BufferMap


@dataclasses.dataclass(frozen=True)
class ScalogConfig:
    f: int
    server_addresses: tuple   # [shard][server]
    aggregator_address: Address
    leader_addresses: tuple
    acceptor_addresses: tuple
    replica_addresses: tuple
    # Optional reply fan-out stage (scalog/ProxyReplica.scala): replicas
    # batch client replies to a proxy replica, which forwards them with
    # write coalescing. Empty = replicas reply directly.
    proxy_replica_addresses: tuple = ()

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if not self.server_addresses:
            raise ValueError("need at least one shard")
        for shard in self.server_addresses:
            if len(shard) < self.f + 1:
                raise ValueError("each shard needs >= f+1 servers")
            if len(shard) != len(self.server_addresses[0]):
                raise ValueError("shards must be equal-sized")
        if len(self.leader_addresses) != self.f + 1:
            raise ValueError("need exactly f+1 leaders")
        if len(self.acceptor_addresses) != 2 * self.f + 1:
            raise ValueError("need exactly 2f+1 acceptors")
        if len(self.replica_addresses) < self.f + 1:
            raise ValueError("need >= f+1 replicas")

    def all_servers(self) -> list[Address]:
        return [a for shard in self.server_addresses for a in shard]


@dataclasses.dataclass(frozen=True)
class CommandId:
    client_address: Address
    client_id: int


@dataclasses.dataclass(frozen=True)
class Command:
    command_id: CommandId
    command: bytes


@dataclasses.dataclass(frozen=True)
class ClientRequest:
    command: Command


@dataclasses.dataclass(frozen=True)
class Backup:
    server_index: int
    slot: int
    command: Command


@dataclasses.dataclass(frozen=True)
class ShardInfo:
    shard_index: int
    server_index: int
    watermark: tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class GlobalCut:
    watermark: tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class Noop:
    pass


GlobalCutOrNoop = Union[GlobalCut, Noop]


@dataclasses.dataclass(frozen=True)
class ProposeCut:
    cut: GlobalCut


@dataclasses.dataclass(frozen=True)
class Phase2a:
    slot: int
    round: int
    value: GlobalCutOrNoop


@dataclasses.dataclass(frozen=True)
class Phase2b:
    acceptor_index: int
    slot: int
    round: int


@dataclasses.dataclass(frozen=True)
class RawCutChosen:
    slot: int
    raw_cut_or_noop: GlobalCutOrNoop


@dataclasses.dataclass(frozen=True)
class CutChosen:
    slot: int
    cut: GlobalCut


@dataclasses.dataclass(frozen=True)
class Chosen:
    slot: int
    commands: tuple[Command, ...]


@dataclasses.dataclass(frozen=True)
class ClientReply:
    command_id: CommandId
    slot: int
    result: bytes


@dataclasses.dataclass(frozen=True)
class ClientReplyBatch:
    """A replica's replies from one Chosen batch, routed through a
    ProxyReplica (scalog/ProxyReplica.scala:130-147)."""

    batch: tuple[ClientReply, ...]


class ScalogServer(Actor):
    """(scalog/Server.scala:60-530)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: ScalogConfig, push_size: int = 1):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.push_size = push_size
        self.shard_index = next(
            s for s, shard in enumerate(config.server_addresses)
            if address in shard)
        shard = list(config.server_addresses[self.shard_index])
        self.index = shard.index(address)
        self.num_servers_per_shard = len(shard)
        # Global server index across all shards (column in global cuts).
        self.global_index = (self.shard_index * self.num_servers_per_shard
                             + self.index)
        self.num_servers = len(config.all_servers())
        # logs[i] = local log of shard-mate i (we're primary of ours).
        self.logs: list[BufferMap] = [BufferMap()
                                      for _ in range(len(shard))]
        self.watermarks = [0] * len(shard)
        self.cuts: BufferMap = BufferMap()
        self.last_watermark_pushed = 0

    def _push(self) -> None:
        self.send(self.config.aggregator_address,
                  ShardInfo(shard_index=self.shard_index,
                            server_index=self.index,
                            watermark=tuple(self.watermarks)))
        self.last_watermark_pushed = self.watermarks[self.index]

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ClientRequest):
            self._handle_client_request(src, message)
        elif isinstance(message, Backup):
            self._put(message.server_index, message.slot, message.command)
        elif isinstance(message, CutChosen):
            self._handle_cut_chosen(src, message)
        else:
            self.logger.fatal(f"unexpected server message {message!r}")

    def _put(self, server_index: int, slot: int, command: Command) -> None:
        self.logs[server_index].put(slot, command)
        while self.logs[server_index].get(
                self.watermarks[server_index]) is not None:
            self.watermarks[server_index] += 1

    def _handle_client_request(self, src: Address,
                               request: ClientRequest) -> None:
        slot = self.watermarks[self.index]
        self._put(self.index, slot, request.command)
        for i, server in enumerate(
                self.config.server_addresses[self.shard_index]):
            if i != self.index:
                self.send(server, Backup(server_index=self.index, slot=slot,
                                         command=request.command))
        if (self.watermarks[self.index] - self.last_watermark_pushed
                >= self.push_size):
            self._push()

    def _project_cut(self, slot: int) -> Optional[tuple[int, list[Command]]]:
        """(Server.projectCut, Server.scala:82-116)."""
        cut = self.cuts.get(slot)
        if cut is None:
            return None
        if slot == 0:
            previous = [0] * self.num_servers
        else:
            previous = self.cuts.get(slot - 1)
            if previous is None:
                return None
        diffs = [c - p for p, c in zip(previous, cut)]
        global_start = sum(previous) + sum(diffs[:self.global_index])
        local_start = previous[self.global_index]
        local_end = cut[self.global_index]
        commands = []
        for i in range(local_start, local_end):
            command = self.logs[self.index].get(i)
            if command is None:
                self.logger.fatal(
                    f"server {self.index} missing log entry {i} chosen in "
                    f"a cut")
            commands.append(command)
        return global_start, commands

    def _handle_cut_chosen(self, src: Address, message: CutChosen) -> None:
        already = self.cuts.contains(message.slot)
        self.cuts.put(message.slot, list(message.cut.watermark))
        slots = [message.slot] if already else [message.slot,
                                               message.slot + 1]
        for s in slots:
            projection = self._project_cut(s)
            if projection is None:
                continue
            global_start, commands = projection
            if commands:
                for replica in self.config.replica_addresses:
                    self.send(replica, Chosen(slot=global_start,
                                              commands=tuple(commands)))


class ScalogAggregator(Actor):
    """(scalog/Aggregator.scala:69-470)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: ScalogConfig,
                 num_shard_cuts_per_proposal: int = 2):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.num_shard_cuts_per_proposal = num_shard_cuts_per_proposal
        self.round_system = ClassicRoundRobin(len(config.leader_addresses))
        self.round = 0
        per_shard = len(config.server_addresses[0])
        self.shard_cuts = [
            [[0] * per_shard for _ in shard]
            for shard in config.server_addresses]
        self.num_infos_since_proposal = 0
        self.raw_cuts: BufferMap = BufferMap()
        self.cuts: list[tuple[int, ...]] = []
        self.raw_cuts_watermark = 0

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ShardInfo):
            self._handle_shard_info(src, message)
        elif isinstance(message, RawCutChosen):
            self._handle_raw_cut_chosen(src, message)
        else:
            self.logger.fatal(f"unexpected aggregator message {message!r}")

    def _handle_shard_info(self, src: Address, info: ShardInfo) -> None:
        current = self.shard_cuts[info.shard_index][info.server_index]
        self.shard_cuts[info.shard_index][info.server_index] = [
            max(a, b) for a, b in zip(current, info.watermark)]
        self.num_infos_since_proposal += 1
        if self.num_infos_since_proposal < self.num_shard_cuts_per_proposal:
            return
        self.num_infos_since_proposal = 0
        global_cut = []
        for shard in self.shard_cuts:
            merged = [max(col) for col in zip(*shard)]
            global_cut.extend(merged)
        leader = self.config.leader_addresses[
            self.round_system.leader(self.round)]
        self.send(leader, ProposeCut(GlobalCut(tuple(global_cut))))

    def _handle_raw_cut_chosen(self, src: Address,
                               message: RawCutChosen) -> None:
        if self.raw_cuts.get(message.slot) is not None:
            return
        self.raw_cuts.put(message.slot, message.raw_cut_or_noop)
        while self.raw_cuts.get(self.raw_cuts_watermark) is not None:
            value = self.raw_cuts.get(self.raw_cuts_watermark)
            if isinstance(value, GlobalCut):
                cut = value.watermark
                # Prune non-monotone cuts (Aggregator.scala:219-231).
                if not self.cuts or (
                        cut != self.cuts[-1]
                        and all(a <= b
                                for a, b in zip(self.cuts[-1], cut))):
                    slot = len(self.cuts)
                    self.cuts.append(cut)
                    for server in self.config.all_servers():
                        self.send(server, CutChosen(slot=slot,
                                                    cut=GlobalCut(cut)))
            self.raw_cuts_watermark += 1


class ScalogLeader(Actor):
    """MultiPaxos on the cut log (scalog/Leader.scala). Leader 0 is
    initially active in round 0; nacks promote higher rounds."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: ScalogConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.leader_addresses).index(address)
        self.round = 0 if self.index == 0 else -1
        self.active = self.index == 0
        self.next_slot = 0
        # (slot, round) -> [value, {acceptor votes}]; None once chosen.
        self.pending: dict[tuple[int, int], object] = {}

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ProposeCut):
            if not self.active:
                return
            phase2a = Phase2a(slot=self.next_slot, round=self.round,
                              value=message.cut)
            self.next_slot += 1
            self.pending[(phase2a.slot, phase2a.round)] = [message.cut, set()]
            for acceptor in self.config.acceptor_addresses:
                self.send(acceptor, phase2a)
        elif isinstance(message, Phase2b):
            key = (message.slot, message.round)
            state = self.pending.get(key)
            if state is None:
                return
            state[1].add(message.acceptor_index)
            if len(state[1]) < self.config.f + 1:
                return
            self.pending[key] = None
            chosen = RawCutChosen(slot=message.slot,
                                  raw_cut_or_noop=state[0])
            self.send(self.config.aggregator_address, chosen)
        else:
            self.logger.fatal(f"unexpected leader message {message!r}")


class ScalogAcceptor(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: ScalogConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.acceptor_addresses).index(address)
        self.round = -1
        self.votes: dict[int, tuple[int, object]] = {}

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, Phase2a):
            self.logger.fatal(f"unexpected acceptor message {message!r}")
        if message.round < self.round:
            return
        self.round = message.round
        self.votes[message.slot] = (message.round, message.value)
        self.send(src, Phase2b(acceptor_index=self.index,
                               slot=message.slot, round=message.round))


class ScalogReplica(Actor):
    """Executes the globally ordered log (scalog/Replica.scala)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: ScalogConfig,
                 state_machine: StateMachine):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.state_machine = state_machine
        self.index = list(config.replica_addresses).index(address)
        self.log: BufferMap = BufferMap()
        self.executed_watermark = 0
        self.client_table: dict[Address, tuple[int, bytes]] = {}

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, Chosen):
            self.logger.fatal(f"unexpected replica message {message!r}")
        for offset, command in enumerate(message.commands):
            self.log.put(message.slot + offset, command)
        replies: list[ClientReply] = []
        while True:
            command = self.log.get(self.executed_watermark)
            if command is None:
                break
            slot = self.executed_watermark
            self.executed_watermark += 1
            cid = command.command_id
            cached = self.client_table.get(cid.client_address)
            if cached is not None and cid.client_id < cached[0]:
                continue
            if cached is not None and cid.client_id == cached[0]:
                result = cached[1]
            else:
                result = self.state_machine.run(command.command)
                self.client_table[cid.client_address] = (cid.client_id,
                                                         result)
            if slot % len(self.config.replica_addresses) == self.index:
                replies.append(ClientReply(command_id=cid, slot=slot,
                                           result=result))
        if not replies:
            return
        proxies = self.config.proxy_replica_addresses
        if proxies:
            # Route each replica's replies to "its" proxy (the Hash
            # scheme of ProxyReplica fan-out).
            self.send(proxies[self.index % len(proxies)],
                      ClientReplyBatch(batch=tuple(replies)))
        else:
            for reply in replies:
                self.send(reply.command_id.client_address, reply)


class ScalogProxyReplica(Actor):
    """Reply fan-out stage (scalog/ProxyReplica.scala:64-148): forwards
    a replica's ClientReplyBatch to the clients, coalescing writes per
    batch."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: ScalogConfig,
                 batch_flush: bool = True):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.batch_flush = batch_flush

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ClientReplyBatch):
            self.logger.fatal(
                f"unexpected proxy replica message {message!r}")
        if not self.batch_flush:
            for reply in message.batch:
                self.send(reply.command_id.client_address, reply)
            return
        clients = set()
        for reply in message.batch:
            client = reply.command_id.client_address
            clients.add(client)
            self.send_no_flush(client, reply)
        for client in clients:
            self.flush(client)


@dataclasses.dataclass
class _Pending:
    id: int
    callback: Callable[[bytes], None]
    resend: object


class ScalogClient(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: ScalogConfig,
                 resend_period_s: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.next_id = 0
        self.pending: dict[int, _Pending] = {}

    def propose(self, command: bytes,
                callback: Optional[Callable[[bytes], None]] = None) -> None:
        id = self.next_id
        self.next_id += 1
        request = ClientRequest(Command(CommandId(self.address, id),
                                        command))
        servers = self.config.all_servers()
        self.send(servers[self.rng.randrange(len(servers))], request)

        def resend():
            self.send(servers[self.rng.randrange(len(servers))], request)
            timer.start()

        timer = self.timer(f"resend-{id}", self.resend_period_s, resend)
        timer.start()
        self.pending[id] = _Pending(id, callback or (lambda _: None), timer)

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ClientReply):
            self.logger.fatal(f"unexpected client message {message!r}")
        pending = self.pending.pop(message.command_id.client_id, None)
        if pending is None:
            return
        pending.resend.stop()
        pending.callback(message.result)

# Importing registers the Scalog binary codecs with the hybrid
# serializer (see scalog_wire.py).
from frankenpaxos_tpu.protocols import scalog_wire  # noqa: E402,F401
