"""CRAQ: chain replication with apportioned queries.

Reference behavior: craq/ (ChainNode.scala:59-340, Client.scala, Config).
Writes enter at the head and propagate down the chain as pending; the
tail applies, replies to the client, and acks back up the chain, at
which point each node applies the write and clears it from pending.
Reads hit any node: clean keys (no pending write) are served locally;
dirty keys are forwarded to the tail (the apportioned-queries rule,
ChainNode.scala:163-197).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional

from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport


@dataclasses.dataclass(frozen=True)
class CraqConfig:
    chain_node_addresses: tuple

    def check_valid(self) -> None:
        if not self.chain_node_addresses:
            raise ValueError("need at least one chain node")


@dataclasses.dataclass(frozen=True)
class CommandId:
    client_address: Address
    client_pseudonym: int
    client_id: int


@dataclasses.dataclass(frozen=True)
class Write:
    command_id: CommandId
    key: str
    value: str


@dataclasses.dataclass(frozen=True)
class WriteBatch:
    writes: tuple[Write, ...]
    # Head-assigned sequence number. CRAQ's consistency argument assumes
    # FIFO links (the reference rides Netty TCP's ordering); explicit
    # sequencing keeps the chain consistent under ANY delivery order --
    # the randomized sim reorders chain hops and caught value regression
    # without it.
    seq: int = 0
    # paxchaos chain-configuration fence: the chain version this batch
    # belongs to. A reconfigured chain bumps the version and re-stamps
    # its dirty (pending) batches, so delayed frames from the old era
    # -- including a dead head's in-flight sequence numbers that would
    # otherwise COLLIDE with the new head's -- drop at receive instead
    # of corrupting the order (docs/DURABILITY.md).
    version: int = 0


@dataclasses.dataclass(frozen=True)
class Read:
    command_id: CommandId
    key: str


@dataclasses.dataclass(frozen=True)
class ReadBatch:
    reads: tuple[Read, ...]


@dataclasses.dataclass(frozen=True)
class TailRead:
    read_batch: ReadBatch


@dataclasses.dataclass(frozen=True)
class Ack:
    write_batch: WriteBatch


@dataclasses.dataclass(frozen=True)
class ClientReply:
    command_id: CommandId


@dataclasses.dataclass(frozen=True)
class ReadReply:
    command_id: CommandId
    value: str


@dataclasses.dataclass(frozen=True)
class ChainReconfigure:
    """Chain re-link (paxchaos): adopt ``chain`` (surviving nodes, in
    order) as configuration ``version``. Controller-driven, sent to
    every surviving node AND every client after a node kill; nodes
    perform the dirty-version handoff on adoption (a node that becomes
    tail applies + acks + replies its whole pending backlog -- those
    writes include everything the dead tail acked, so no acked write
    is lost; a node with a new successor re-propagates its pending
    under the new version, deduped downstream by seq)."""

    version: int
    chain: tuple


class ChainNode(Actor):
    """``admission`` (a serve.admission.AdmissionOptions, or None)
    arms paxload admission control on this node's CLIENT edge: bare
    ``Write``/``Read`` arrivals -- the only client-sent shapes -- are
    admitted or answered with an explicit ``Rejected``, while the
    chain's own replication traffic (``WriteBatch`` hops, ``Ack``,
    ``TailRead``) is control plane and never touches the controller.
    That puts CRAQ's read path under the same admission/client-lane/
    Rejected-backoff discipline the Paxos write paths already have
    (docs/SERVING.md), which is what lets the scenario matrix gate
    zone-local chain reads on the same SLO clauses as writes."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: CraqConfig,
                 resend_period_s: float = 1.0, admission=None):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.chain_node_addresses).index(address)
        if admission is not None and admission.any_enabled():
            from frankenpaxos_tpu.serve.admission import (
                AdmissionController,
            )

            self.admission = AdmissionController(
                admission, role=f"craq_node_{self.index}",
                metrics=transport.runtime_metrics)
            transport.note_admission(address, self)
        self.is_head = self.index == 0
        self.is_tail = self.index == len(config.chain_node_addresses) - 1
        #: paxchaos: the chain-configuration fence. Batches/acks from
        #: another version drop at receive; ChainReconfigure bumps it.
        self.chain_version = 0
        #: Set when a reconfiguration removes THIS node: a fenced
        #: node serves nothing (a partitioned-but-alive old tail
        #: answering a delayed pinned read from its frozen state
        #: would violate the read guarantee the re-link preserves).
        self.fenced_out = False
        self.pending_writes: list[WriteBatch] = []
        self.state_machine: dict[str, str] = {}
        self.versions = 0
        # Head-side sequencer + per-node in-order apply state: batches
        # propagate down (and acks back up) in ``seq`` order regardless
        # of per-hop delivery order. Duplicate deliveries re-ack, and an
        # unacked-head resend timer retransmits, so lost hop messages
        # heal rather than wedging the chain.
        self._next_seq = 0               # head: next seq to assign
        self._next_in = 0                # next batch seq to accept
        self._in_buffer: dict[int, WriteBatch] = {}
        self._next_ack = 0               # next ack seq to apply
        self._ack_buffer: dict[int, Ack] = {}
        # Head-side at-most-once: (client, pseudonym) -> (largest client
        # id sequenced, its chain seq). A late duplicate of an old
        # client Write must NOT be re-sequenced -- it would resurrect a
        # stale value over a newer committed one. Retries of the LATEST
        # write re-reply once it has committed (a lost ClientReply must
        # not wedge the client stream).
        self._sequenced: dict[tuple, tuple[int, int]] = {}
        self._resend_timer = None
        if not self.is_tail:
            def resend():
                # Reads config/index/is_tail dynamically: a re-linked
                # chain (ChainReconfigure) retargets the resend to the
                # NEW successor; a node that became tail has nothing
                # pending to push.
                if self.pending_writes and not self.is_tail:
                    self.send(
                        self.config.chain_node_addresses[self.index + 1],
                        self.pending_writes[0])
                self._resend_timer.start()

            self._resend_timer = self.timer("resendChain",
                                            resend_period_s, resend)
            self._resend_timer.start()

    # --- write path (ChainNode.scala:135-161) -----------------------------
    def _process_write_batch(self, batch: WriteBatch) -> None:
        if self.is_head:
            fresh = []
            for write in batch.writes:
                key = (write.command_id.client_address,
                       write.command_id.client_pseudonym)
                last_id, last_seq = self._sequenced.get(key, (-1, -1))
                if write.command_id.client_id < last_id:
                    continue  # stale duplicate
                if write.command_id.client_id == last_id:
                    # Retry of the latest write: if it already committed
                    # (fully acked, or applied directly on a single-node
                    # chain), the client's reply was lost -- re-reply.
                    if self.is_tail or last_seq < self._next_ack:
                        self.send(write.command_id.client_address,
                                  ClientReply(write.command_id))
                    continue
                self._sequenced[key] = (write.command_id.client_id,
                                        self._next_seq)
                fresh.append(write)
            if not fresh:
                return
            batch = WriteBatch(writes=tuple(fresh), seq=self._next_seq,
                               version=self.chain_version)
            self._next_seq += 1
            self._accept_in_order(batch)
            return
        if batch.seq < self._next_in:
            # Already accepted: a duplicate means the sender may have
            # missed our Ack -- re-ack anything we've already acked.
            if batch.seq < self._next_ack or self.is_tail:
                self.send(self.config.chain_node_addresses[self.index - 1],
                          Ack(batch))
            return
        if batch.seq in self._in_buffer:
            return
        self._in_buffer[batch.seq] = batch
        while self._next_in in self._in_buffer:
            self._accept_in_order(self._in_buffer.pop(self._next_in))

    def _accept_in_order(self, batch: WriteBatch) -> None:
        self._next_in = batch.seq + 1
        # Passive at-most-once maintenance on EVERY node (not just the
        # head): each node sees every write flow past, so a node
        # promoted to head by a chain re-link inherits a live
        # duplicate-suppression map instead of an empty one -- a late
        # client duplicate can never be re-sequenced over a newer
        # committed value just because the original head died.
        for write in batch.writes:
            key = (write.command_id.client_address,
                   write.command_id.client_pseudonym)
            last_id, _ = self._sequenced.get(key, (-1, -1))
            if write.command_id.client_id >= last_id:
                self._sequenced[key] = (write.command_id.client_id,
                                        batch.seq)
        if not self.is_tail:
            self.pending_writes.append(batch)
            self.send(self.config.chain_node_addresses[self.index + 1],
                      batch)
            return
        # Tail: apply, reply, ack upstream.
        for write in batch.writes:
            self.state_machine[write.key] = write.value
            self.send(write.command_id.client_address,
                      ClientReply(write.command_id))
            self.versions += 1
        if not self.is_head:
            self.send(self.config.chain_node_addresses[self.index - 1],
                      Ack(batch))

    def _handle_ack(self, ack: Ack) -> None:
        seq = ack.write_batch.seq
        if seq < self._next_ack or seq in self._ack_buffer:
            return
        self._ack_buffer[seq] = ack
        while self._next_ack in self._ack_buffer:
            self._apply_ack(self._ack_buffer.pop(self._next_ack))

    def _apply_ack(self, ack: Ack) -> None:
        self._next_ack = ack.write_batch.seq + 1
        for write in ack.write_batch.writes:
            self.state_machine[write.key] = write.value
        # In-order accept + in-order ack application make the acked
        # batch the oldest pending one.
        if self.pending_writes \
                and self.pending_writes[0].seq == ack.write_batch.seq:
            self.pending_writes.pop(0)
        if not self.is_head:
            self.send(self.config.chain_node_addresses[self.index - 1], ack)

    # --- read path (ChainNode.scala:163-197) ------------------------------
    def _process_read_batch(self, batch: ReadBatch) -> None:
        dirty_keys = {write.key
                      for pending in self.pending_writes
                      for write in pending.writes}
        dirty_reads = []
        for read in batch.reads:
            if read.key in dirty_keys:
                dirty_reads.append(read)
            else:
                value = self.state_machine.get(read.key, "default")
                self.send(read.command_id.client_address,
                          ReadReply(read.command_id, value))
                self.versions += 1
        if dirty_reads:
            self.send(self.config.chain_node_addresses[-1],
                      TailRead(ReadBatch(tuple(dirty_reads))))

    def _handle_tail_read(self, tail_read: TailRead) -> None:
        for read in tail_read.read_batch.reads:
            value = self.state_machine.get(read.key, "default")
            self.send(read.command_id.client_address,
                      ReadReply(read.command_id, value))
            self.versions += 1

    # --- chain reconfiguration (paxchaos) ---------------------------------
    def _handle_reconfigure(self, m: ChainReconfigure) -> None:
        """Adopt a re-linked chain with the dirty-version handoff.

        The controller removed dead node(s) from the chain; survivors
        keep their sequence state (``_next_in``/``_next_ack`` carry
        over -- the surviving prefix saw a superset of what any
        successor saw, so re-propagation + seq dedup heals every gap).
        Three role transitions matter:

        * became TAIL (old tail died): every pending batch is, by the
          chain invariant, a superset of everything the dead tail
          acked -- apply them all in order, reply, and ack upstream
          (duplicate replies/applies are absorbed by client dedup and
          last-write-wins per key). Zero acked writes lost.
        * new SUCCESSOR (mid node died): re-propagate the whole
          pending backlog under the new version; downstream dedupes by
          seq and re-acks what it already acked.
        * became HEAD (old head died): continue the sequence space at
          ``max(_next_seq, _next_in)`` -- old-era in-flight seqs that
          could collide are fenced off by the version bump -- with the
          passively-maintained at-most-once map intact.
        """
        if m.version <= self.chain_version:
            return
        if self.address not in m.chain:
            # Reconfigured OUT (we were presumed dead): stop serving
            # the chain ENTIRELY -- a zombie tail answering stale
            # reads is the failure mode the fence exists for, and the
            # read path has no version field of its own, so the fence
            # is a node-level flag checked at receive.
            self.chain_version = m.version
            self.fenced_out = True
            self.pending_writes.clear()
            self._in_buffer.clear()
            self._ack_buffer.clear()
            return
        self.chain_version = m.version
        self.fenced_out = False
        self.config = CraqConfig(chain_node_addresses=tuple(m.chain))
        was_tail = self.is_tail
        self.index = list(m.chain).index(self.address)
        self.is_head = self.index == 0
        self.is_tail = self.index == len(m.chain) - 1
        # Cross-era reorder buffers die with the old era: upstream
        # re-propagation re-delivers anything that mattered.
        self._in_buffer.clear()
        self._ack_buffer.clear()
        # Re-stamp the dirty backlog into the new era (the periodic
        # resend timer then speaks the current version too).
        self.pending_writes = [
            dataclasses.replace(batch, version=m.version)
            for batch in self.pending_writes]
        if self.is_head:
            self._next_seq = max(self._next_seq, self._next_in)
        if self.is_tail and not was_tail:
            # Dirty-version handoff: drain the pending backlog as the
            # new tail -- apply, reply, ack upstream, in seq order.
            backlog, self.pending_writes = self.pending_writes, []
            for batch in backlog:
                for write in batch.writes:
                    self.state_machine[write.key] = write.value
                    self.send(write.command_id.client_address,
                              ClientReply(write.command_id))
                    self.versions += 1
                self._next_ack = max(self._next_ack, batch.seq + 1)
                if not self.is_head:
                    self.send(
                        self.config.chain_node_addresses[self.index - 1],
                        Ack(batch))
        elif not self.is_tail:
            # Possibly-new successor: push the whole backlog at it
            # (dedup by seq downstream); its own acks flow back.
            successor = self.config.chain_node_addresses[self.index + 1]
            for batch in self.pending_writes:
                self.send(successor, batch)

    # --- dispatch ---------------------------------------------------------
    def _admit_client(self, message) -> bool:
        """Admit one client-edge command, or answer ``Rejected`` (the
        client backs off and retries -- backoff.py discipline; reads
        and writes share the controller)."""
        if self.admission is None or self.admission.admit():
            return True
        from frankenpaxos_tpu.serve.messages import Rejected

        cid = message.command_id
        self.send(cid.client_address, Rejected(
            entries=((cid.client_pseudonym, cid.client_id),),
            retry_after_ms=self.admission.retry_after_ms(),
            reason=self.admission.last_reason))
        return False

    def on_drain(self) -> None:
        # Resync the admission in-flight measure where it changes
        # (the wpaxos-leader discipline): reads complete inside their
        # handler and writes complete on ack-apply, so the live span
        # is the un-acked sequenced write backlog. Without this, an
        # armed inflight_limit saturates after `limit` admits and the
        # node rejects forever.
        if self.admission is not None \
                and self.admission.options.inflight_limit:
            self.admission.set_inflight(
                sum(len(batch.writes)
                    for batch in self.pending_writes))

    def receive(self, src: Address, message) -> None:
        if self.fenced_out:
            # Reconfigured out of the chain: drop EVERYTHING (reads
            # included -- they carry no version to fence on). Clients
            # conclude via their own resend-to-current-chain path.
            if isinstance(message, ChainReconfigure):
                self._handle_reconfigure(message)
            return
        if isinstance(message, Write):
            if not self.is_head:
                # A client racing a chain re-link (its config updated
                # before ours, or a stale frame to a demoted head):
                # drop -- the client's resend lands once the
                # configuration settles.
                return
            if not self._admit_client(message):
                return
            self._process_write_batch(
                WriteBatch((message,), version=self.chain_version))
        elif isinstance(message, WriteBatch):
            if message.version != self.chain_version:
                return  # old-era frame fenced off (see WriteBatch)
            self._process_write_batch(message)
        elif isinstance(message, Read):
            if not self._admit_client(message):
                return
            self._process_read_batch(ReadBatch((message,)))
        elif isinstance(message, ReadBatch):
            self._process_read_batch(message)
        elif isinstance(message, Ack):
            if message.write_batch.version != self.chain_version:
                return
            self._handle_ack(message)
        elif isinstance(message, TailRead):
            self._handle_tail_read(message)
        elif isinstance(message, ChainReconfigure):
            self._handle_reconfigure(message)
        else:
            self.logger.fatal(f"unexpected chain node message {message!r}")


@dataclasses.dataclass
class _Pending:
    id: int
    callback: Callable
    resend_timer: object
    request: object = None
    dst: object = None
    is_read: bool = False
    attempts: int = 0
    # A Rejected already rescheduled the timer: a duplicate refusal
    # (original + resend both refused) must not double-consume the
    # retry budget or re-widen the backoff.
    backoff_pending: bool = False


class CraqClient(Actor):
    """Writes go to the head; reads go to a random node -- or, with
    ``read_node`` pinned, to THAT node (the paxworld zone-local read
    lane: a geo scenario pins each zone's client to its zone's chain
    node). ``retry_budget``/``backoff`` arm the paxload retry
    discipline (serve/backoff.py): a ``Rejected`` backs off with
    jitter (honoring the server's retry_after hint) and retries the
    same node, timeouts resend on the resend period, and both consume
    the per-op budget -- exhaustion concludes the op with
    RETRY_EXHAUSTED instead of retrying forever. A budget of 0 (the
    default) preserves the pre-paxworld behavior exactly; when one is
    armed, WRITE callbacks must accept the sentinel argument."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: CraqConfig,
                 resend_period_s: float = 10.0, seed: int = 0,
                 retry_budget: int = 0, backoff=None,
                 read_node: Optional[int] = None):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.chain_version = 0
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.retry_budget = retry_budget
        self.backoff = backoff
        self.read_node = read_node
        self.giveups = 0
        # String-seeded: only the Rejected-backoff jitter draws here.
        self._backoff_rng = random.Random(f"craq-client|{address}|{seed}")
        self.ids: dict[int, int] = {}
        self.pending: dict[int, _Pending] = {}

    def _start(self, pseudonym: int, make_request, dst: Address,
               callback, is_read: bool) -> None:
        if pseudonym in self.pending:
            raise RuntimeError(f"pseudonym {pseudonym} has a pending op")
        id = self.ids.get(pseudonym, 0)
        self.ids[pseudonym] = id + 1
        request = make_request(CommandId(self.address, pseudonym, id))
        self.send(dst, request)
        timer = self.timer(f"resend-{pseudonym}", self.resend_period_s,
                           lambda p=pseudonym: self._resend(p))
        timer.start()
        self.pending[pseudonym] = _Pending(
            id, callback or (lambda *_: None), timer,
            request=request, dst=dst, is_read=is_read)

    def _read_target(self) -> Address:
        if self.read_node is not None:
            # Clamp: a re-linked (shorter) chain keeps the pin valid;
            # zone affinity is best-effort after a reconfiguration.
            index = min(self.read_node,
                        len(self.config.chain_node_addresses) - 1)
            return self.config.chain_node_addresses[index]
        return self.config.chain_node_addresses[self.rng.randrange(
            len(self.config.chain_node_addresses))]

    def _resend(self, pseudonym: int) -> None:
        pending = self.pending.get(pseudonym)
        if pending is None:
            return
        pending.backoff_pending = False
        if self.retry_budget and pending.attempts >= self.retry_budget:
            self._giveup(pseudonym)
            return
        pending.attempts += 1
        # Re-derive the destination from the CURRENT chain (paxchaos:
        # a ChainReconfigure may have removed the node this op was
        # pinned to -- writes re-target the head, reads the clamped
        # read pin), so in-flight ops survive a re-link on their own
        # resend schedule.
        pending.dst = (self._read_target() if pending.is_read
                       else self.config.chain_node_addresses[0])
        self.send(pending.dst, pending.request)
        timer = pending.resend_timer
        timer.set_delay(self.resend_period_s)
        timer.start()

    def _giveup(self, pseudonym: int) -> None:
        from frankenpaxos_tpu.serve.backoff import RETRY_EXHAUSTED

        pending = self.pending.pop(pseudonym)
        pending.resend_timer.stop()
        self.giveups += 1
        pending.callback(RETRY_EXHAUSTED)

    def _handle_rejected(self, src: Address, m) -> None:
        """Admission refusal from a chain node: alive but saturated.
        Back off (jittered, server hint as the floor) and retry the
        SAME node on the rescheduled resend timer.

        (Known accepted duplication: this budget/backoff_pending/
        RETRY_EXHAUSTED state machine mirrors
        protocols/wpaxos/client.py and the multipaxos/mencius retry
        discipline, pending the protocol-neutral client-layer
        refactor on the ROADMAP -- change one, check the others.)"""
        for pseudonym, client_id in m.entries:
            pending = self.pending.get(pseudonym)
            if pending is None or pending.id != client_id \
                    or pending.backoff_pending:
                continue
            pending.attempts += 1
            if self.retry_budget \
                    and pending.attempts >= self.retry_budget:
                self._giveup(pseudonym)
                continue
            delay = self.resend_period_s
            if self.backoff is not None:
                delay = self.backoff.delay_s(
                    pending.attempts - 1, self._backoff_rng,
                    floor_s=getattr(m, "retry_after_ms", 0) / 1000.0)
            pending.backoff_pending = True
            timer = pending.resend_timer
            timer.stop()
            timer.set_delay(delay)
            timer.start()

    def write(self, pseudonym: int, key: str, value: str,
              callback: Optional[Callable[[], None]] = None) -> None:
        self._start(pseudonym, lambda cid: Write(cid, key, value),
                    self.config.chain_node_addresses[0], callback,
                    is_read=False)

    def read(self, pseudonym: int, key: str,
             callback: Optional[Callable[[str], None]] = None) -> None:
        self._start(pseudonym, lambda cid: Read(cid, key),
                    self._read_target(), callback, is_read=True)

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ClientReply):
            pseudonym = message.command_id.client_pseudonym
            result = None
        elif isinstance(message, ReadReply):
            pseudonym = message.command_id.client_pseudonym
            result = message.value
        elif isinstance(message, ChainReconfigure):
            if message.version > self.chain_version:
                self.chain_version = message.version
                self.config = CraqConfig(
                    chain_node_addresses=tuple(message.chain))
            return
        elif type(message).__name__ == "Rejected":
            self._handle_rejected(src, message)
            return
        else:
            self.logger.fatal(f"unexpected client message {message!r}")
        pending = self.pending.get(pseudonym)
        if pending is None or pending.id != message.command_id.client_id:
            self.logger.debug(f"stale reply {message}")
            return
        pending.resend_timer.stop()
        del self.pending[pseudonym]
        if result is None:
            pending.callback()
        else:
            pending.callback(result)

# Importing registers this protocol's binary codecs with the hybrid
# serializer (see craq_wire.py).
from frankenpaxos_tpu.protocols import craq_wire  # noqa: E402,F401
