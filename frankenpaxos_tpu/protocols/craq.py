"""CRAQ: chain replication with apportioned queries.

Reference behavior: craq/ (ChainNode.scala:59-340, Client.scala, Config).
Writes enter at the head and propagate down the chain as pending; the
tail applies, replies to the client, and acks back up the chain, at
which point each node applies the write and clears it from pending.
Reads hit any node: clean keys (no pending write) are served locally;
dirty keys are forwarded to the tail (the apportioned-queries rule,
ChainNode.scala:163-197).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional

from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport


@dataclasses.dataclass(frozen=True)
class CraqConfig:
    chain_node_addresses: tuple

    def check_valid(self) -> None:
        if not self.chain_node_addresses:
            raise ValueError("need at least one chain node")


@dataclasses.dataclass(frozen=True)
class CommandId:
    client_address: Address
    client_pseudonym: int
    client_id: int


@dataclasses.dataclass(frozen=True)
class Write:
    command_id: CommandId
    key: str
    value: str


@dataclasses.dataclass(frozen=True)
class WriteBatch:
    writes: tuple[Write, ...]
    # Head-assigned sequence number. CRAQ's consistency argument assumes
    # FIFO links (the reference rides Netty TCP's ordering); explicit
    # sequencing keeps the chain consistent under ANY delivery order --
    # the randomized sim reorders chain hops and caught value regression
    # without it.
    seq: int = 0


@dataclasses.dataclass(frozen=True)
class Read:
    command_id: CommandId
    key: str


@dataclasses.dataclass(frozen=True)
class ReadBatch:
    reads: tuple[Read, ...]


@dataclasses.dataclass(frozen=True)
class TailRead:
    read_batch: ReadBatch


@dataclasses.dataclass(frozen=True)
class Ack:
    write_batch: WriteBatch


@dataclasses.dataclass(frozen=True)
class ClientReply:
    command_id: CommandId


@dataclasses.dataclass(frozen=True)
class ReadReply:
    command_id: CommandId
    value: str


class ChainNode(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: CraqConfig,
                 resend_period_s: float = 1.0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.chain_node_addresses).index(address)
        self.is_head = self.index == 0
        self.is_tail = self.index == len(config.chain_node_addresses) - 1
        self.pending_writes: list[WriteBatch] = []
        self.state_machine: dict[str, str] = {}
        self.versions = 0
        # Head-side sequencer + per-node in-order apply state: batches
        # propagate down (and acks back up) in ``seq`` order regardless
        # of per-hop delivery order. Duplicate deliveries re-ack, and an
        # unacked-head resend timer retransmits, so lost hop messages
        # heal rather than wedging the chain.
        self._next_seq = 0               # head: next seq to assign
        self._next_in = 0                # next batch seq to accept
        self._in_buffer: dict[int, WriteBatch] = {}
        self._next_ack = 0               # next ack seq to apply
        self._ack_buffer: dict[int, Ack] = {}
        # Head-side at-most-once: (client, pseudonym) -> (largest client
        # id sequenced, its chain seq). A late duplicate of an old
        # client Write must NOT be re-sequenced -- it would resurrect a
        # stale value over a newer committed one. Retries of the LATEST
        # write re-reply once it has committed (a lost ClientReply must
        # not wedge the client stream).
        self._sequenced: dict[tuple, tuple[int, int]] = {}
        self._resend_timer = None
        if not self.is_tail:
            def resend():
                if self.pending_writes:
                    self.send(
                        self.config.chain_node_addresses[self.index + 1],
                        self.pending_writes[0])
                self._resend_timer.start()

            self._resend_timer = self.timer("resendChain",
                                            resend_period_s, resend)
            self._resend_timer.start()

    # --- write path (ChainNode.scala:135-161) -----------------------------
    def _process_write_batch(self, batch: WriteBatch) -> None:
        if self.is_head:
            fresh = []
            for write in batch.writes:
                key = (write.command_id.client_address,
                       write.command_id.client_pseudonym)
                last_id, last_seq = self._sequenced.get(key, (-1, -1))
                if write.command_id.client_id < last_id:
                    continue  # stale duplicate
                if write.command_id.client_id == last_id:
                    # Retry of the latest write: if it already committed
                    # (fully acked, or applied directly on a single-node
                    # chain), the client's reply was lost -- re-reply.
                    if self.is_tail or last_seq < self._next_ack:
                        self.send(write.command_id.client_address,
                                  ClientReply(write.command_id))
                    continue
                self._sequenced[key] = (write.command_id.client_id,
                                        self._next_seq)
                fresh.append(write)
            if not fresh:
                return
            batch = WriteBatch(writes=tuple(fresh), seq=self._next_seq)
            self._next_seq += 1
            self._accept_in_order(batch)
            return
        if batch.seq < self._next_in:
            # Already accepted: a duplicate means the sender may have
            # missed our Ack -- re-ack anything we've already acked.
            if batch.seq < self._next_ack or self.is_tail:
                self.send(self.config.chain_node_addresses[self.index - 1],
                          Ack(batch))
            return
        if batch.seq in self._in_buffer:
            return
        self._in_buffer[batch.seq] = batch
        while self._next_in in self._in_buffer:
            self._accept_in_order(self._in_buffer.pop(self._next_in))

    def _accept_in_order(self, batch: WriteBatch) -> None:
        self._next_in = batch.seq + 1
        if not self.is_tail:
            self.pending_writes.append(batch)
            self.send(self.config.chain_node_addresses[self.index + 1],
                      batch)
            return
        # Tail: apply, reply, ack upstream.
        for write in batch.writes:
            self.state_machine[write.key] = write.value
            self.send(write.command_id.client_address,
                      ClientReply(write.command_id))
            self.versions += 1
        if not self.is_head:
            self.send(self.config.chain_node_addresses[self.index - 1],
                      Ack(batch))

    def _handle_ack(self, ack: Ack) -> None:
        seq = ack.write_batch.seq
        if seq < self._next_ack or seq in self._ack_buffer:
            return
        self._ack_buffer[seq] = ack
        while self._next_ack in self._ack_buffer:
            self._apply_ack(self._ack_buffer.pop(self._next_ack))

    def _apply_ack(self, ack: Ack) -> None:
        self._next_ack = ack.write_batch.seq + 1
        for write in ack.write_batch.writes:
            self.state_machine[write.key] = write.value
        # In-order accept + in-order ack application make the acked
        # batch the oldest pending one.
        if self.pending_writes \
                and self.pending_writes[0].seq == ack.write_batch.seq:
            self.pending_writes.pop(0)
        if not self.is_head:
            self.send(self.config.chain_node_addresses[self.index - 1], ack)

    # --- read path (ChainNode.scala:163-197) ------------------------------
    def _process_read_batch(self, batch: ReadBatch) -> None:
        dirty_keys = {write.key
                      for pending in self.pending_writes
                      for write in pending.writes}
        dirty_reads = []
        for read in batch.reads:
            if read.key in dirty_keys:
                dirty_reads.append(read)
            else:
                value = self.state_machine.get(read.key, "default")
                self.send(read.command_id.client_address,
                          ReadReply(read.command_id, value))
                self.versions += 1
        if dirty_reads:
            self.send(self.config.chain_node_addresses[-1],
                      TailRead(ReadBatch(tuple(dirty_reads))))

    def _handle_tail_read(self, tail_read: TailRead) -> None:
        for read in tail_read.read_batch.reads:
            value = self.state_machine.get(read.key, "default")
            self.send(read.command_id.client_address,
                      ReadReply(read.command_id, value))
            self.versions += 1

    # --- dispatch ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        if isinstance(message, Write):
            self._process_write_batch(WriteBatch((message,)))
        elif isinstance(message, WriteBatch):
            self._process_write_batch(message)
        elif isinstance(message, Read):
            self._process_read_batch(ReadBatch((message,)))
        elif isinstance(message, ReadBatch):
            self._process_read_batch(message)
        elif isinstance(message, Ack):
            self._handle_ack(message)
        elif isinstance(message, TailRead):
            self._handle_tail_read(message)
        else:
            self.logger.fatal(f"unexpected chain node message {message!r}")


@dataclasses.dataclass
class _Pending:
    id: int
    callback: Callable
    resend_timer: object


class CraqClient(Actor):
    """Writes go to the head; reads go to a random node
    (craq/Client.scala)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: CraqConfig,
                 resend_period_s: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.ids: dict[int, int] = {}
        self.pending: dict[int, _Pending] = {}

    def _start(self, pseudonym: int, make_request, dst: Address,
               callback) -> None:
        if pseudonym in self.pending:
            raise RuntimeError(f"pseudonym {pseudonym} has a pending op")
        id = self.ids.get(pseudonym, 0)
        self.ids[pseudonym] = id + 1
        request = make_request(CommandId(self.address, pseudonym, id))

        def resend():
            self.send(dst, request)
            timer.start()

        self.send(dst, request)
        timer = self.timer(f"resend-{pseudonym}", self.resend_period_s,
                           resend)
        timer.start()
        self.pending[pseudonym] = _Pending(id, callback or (lambda *_: None),
                                           timer)

    def write(self, pseudonym: int, key: str, value: str,
              callback: Optional[Callable[[], None]] = None) -> None:
        self._start(pseudonym, lambda cid: Write(cid, key, value),
                    self.config.chain_node_addresses[0], callback)

    def read(self, pseudonym: int, key: str,
             callback: Optional[Callable[[str], None]] = None) -> None:
        node = self.config.chain_node_addresses[
            self.rng.randrange(len(self.config.chain_node_addresses))]
        self._start(pseudonym, lambda cid: Read(cid, key), node, callback)

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ClientReply):
            pseudonym = message.command_id.client_pseudonym
            result = None
        elif isinstance(message, ReadReply):
            pseudonym = message.command_id.client_pseudonym
            result = message.value
        else:
            self.logger.fatal(f"unexpected client message {message!r}")
        pending = self.pending.get(pseudonym)
        if pending is None or pending.id != message.command_id.client_id:
            self.logger.debug(f"stale reply {message}")
            return
        pending.resend_timer.stop()
        del self.pending[pseudonym]
        if result is None:
            pending.callback()
        else:
            pending.callback(result)

# Importing registers this protocol's binary codecs with the hybrid
# serializer (see craq_wire.py).
from frankenpaxos_tpu.protocols import craq_wire  # noqa: E402,F401
