"""Matchmaker MultiPaxos: MultiPaxos with live acceptor reconfiguration.

Reference behavior: matchmakermultipaxos/ (~4,900 LoC Scala: Leader,
Matchmaker.scala:79-700, Reconfigurer.scala:98-500, Acceptor, Replica;
SURVEY.md section 2.2). Every round has its own quorum system over an
arbitrary acceptor set, registered with 2f+1 matchmakers:

  * to start round r, the leader matchmakes: MatchRequest(r, config) to
    the matchmakers; f+1 MatchReplies return all prior-round
    configurations; phase 1 reads a read quorum of every prior
    configuration (for the whole log suffix); phase 2 writes through the
    new round's own configuration -- the per-round quorum-systems shape
    that ops/quorum.py's MultiConfigQuorumChecker batches on device;
  * a Reconfigurer drives acceptor-set changes mid-stream by handing the
    leader a new configuration, which the leader adopts in its next
    round (the reference's Stop/Bootstrap/Phase1/Phase2 matchmaker
    self-reconfiguration and GarbageCollect pruning are simplified to
    this leader-driven path here);
  * Die messages support chaos testing of matchmakers
    (Matchmaker.scala:664).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional, Union

from frankenpaxos_tpu.quorums import (
    QuorumSystem,
    SimpleMajority,
    quorum_system_from_dict,
    quorum_system_to_dict,
)
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.utils import BufferMap


@dataclasses.dataclass(frozen=True)
class MatchmakerMultiPaxosConfig:
    f: int
    leader_addresses: tuple
    matchmaker_addresses: tuple
    reconfigurer_addresses: tuple
    acceptor_addresses: tuple
    replica_addresses: tuple

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.matchmaker_addresses) != 2 * self.f + 1:
            raise ValueError("need exactly 2f+1 matchmakers")
        if len(self.reconfigurer_addresses) < 1:
            raise ValueError("need >= 1 reconfigurer")
        if len(self.acceptor_addresses) < 2 * self.f + 1:
            raise ValueError("need >= 2f+1 acceptors")
        if len(self.replica_addresses) < self.f + 1:
            raise ValueError("need >= f+1 replicas")


@dataclasses.dataclass(frozen=True)
class CommandId:
    client_address: Address
    client_pseudonym: int
    client_id: int


@dataclasses.dataclass(frozen=True)
class Command:
    command_id: CommandId
    command: bytes


@dataclasses.dataclass(frozen=True)
class Noop:
    pass


NOOP = Noop()
Value = Union[Command, Noop]


@dataclasses.dataclass(frozen=True)
class ClientRequest:
    command: Command


@dataclasses.dataclass(frozen=True)
class ClientReply:
    command_id: CommandId
    result: bytes


@dataclasses.dataclass(frozen=True)
class MatchRequest:
    round: int
    quorum_system: dict


@dataclasses.dataclass(frozen=True)
class MatchReply:
    round: int
    matchmaker_index: int
    configurations: tuple[tuple[int, dict], ...]  # (round, quorum system)


@dataclasses.dataclass(frozen=True)
class MatchmakerNack:
    round: int


@dataclasses.dataclass(frozen=True)
class GarbageCollect:
    """Prune matchmaker configurations below ``round`` once phase 1 has
    read everything it needs (Matchmaker GarbageCollect)."""

    round: int


@dataclasses.dataclass(frozen=True)
class Phase1a:
    round: int
    chosen_watermark: int


@dataclasses.dataclass(frozen=True)
class Phase1bSlotInfo:
    slot: int
    vote_round: int
    vote_value: Value


@dataclasses.dataclass(frozen=True)
class Phase1b:
    round: int
    acceptor_index: int
    info: tuple[Phase1bSlotInfo, ...]


@dataclasses.dataclass(frozen=True)
class Phase2a:
    slot: int
    round: int
    value: Value


@dataclasses.dataclass(frozen=True)
class Phase2b:
    slot: int
    round: int
    acceptor_index: int


@dataclasses.dataclass(frozen=True)
class Chosen:
    slot: int
    value: Value


@dataclasses.dataclass(frozen=True)
class AcceptorNack:
    round: int


@dataclasses.dataclass(frozen=True)
class Reconfigure:
    quorum_system: dict


@dataclasses.dataclass(frozen=True)
class Die:
    """Chaos: kill a matchmaker (Matchmaker.scala:664)."""


@dataclasses.dataclass
class _Matchmaking:
    quorum_system: QuorumSystem
    match_replies: dict[int, MatchReply]
    pending_batches: list[ClientRequest]


@dataclasses.dataclass
class _Phase1:
    quorum_system: QuorumSystem
    previous: dict[int, QuorumSystem]
    pending_rounds: set[int]
    phase1bs: dict[int, Phase1b]
    pending_batches: list[ClientRequest]


@dataclasses.dataclass
class _Phase2:
    quorum_system: QuorumSystem
    pending_values: dict[int, Value]
    phase2bs: dict[int, set[int]]


class MMPLeader(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerMultiPaxosConfig,
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.index = list(config.leader_addresses).index(address)
        self.round_system = ClassicRoundRobin(len(config.leader_addresses))
        self.round = -1
        self.next_slot = 0
        self.chosen_watermark = 0
        self.log: BufferMap = BufferMap()
        self.state: object = None  # Inactive
        # The configuration to adopt at the next matchmaking, set by the
        # reconfigurer.
        self.next_quorum_system: QuorumSystem = SimpleMajority(
            range(2 * config.f + 1))
        if self.index == 0:
            self._start_matchmaking()

    # --- matchmaking ------------------------------------------------------
    def _start_matchmaking(self) -> None:
        pending = []
        if isinstance(self.state, (_Matchmaking, _Phase1)):
            pending = self.state.pending_batches
        self.round = self.round_system.next_classic_round(self.index,
                                                          self.round)
        request = MatchRequest(
            round=self.round,
            quorum_system=quorum_system_to_dict(self.next_quorum_system))
        for matchmaker in self.config.matchmaker_addresses:
            self.send(matchmaker, request)
        self.state = _Matchmaking(self.next_quorum_system, {}, pending)

    def _acceptor(self, index: int) -> Address:
        return self.config.acceptor_addresses[index]

    # --- handlers ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        if isinstance(message, ClientRequest):
            self._handle_client_request(src, message)
        elif isinstance(message, MatchReply):
            self._handle_match_reply(src, message)
        elif isinstance(message, (MatchmakerNack, AcceptorNack)):
            self._handle_nack(message.round)
        elif isinstance(message, Phase1b):
            self._handle_phase1b(src, message)
        elif isinstance(message, Phase2b):
            self._handle_phase2b(src, message)
        elif isinstance(message, Reconfigure):
            self._handle_reconfigure(src, message)
        elif isinstance(message, Chosen):
            self._learn(message.slot, message.value)
        else:
            self.logger.fatal(f"unexpected leader message {message!r}")

    def _handle_client_request(self, src: Address,
                               request: ClientRequest) -> None:
        if self.state is None:
            return
        if isinstance(self.state, (_Matchmaking, _Phase1)):
            self.state.pending_batches.append(request)
            return
        self._propose(request.command)

    def _propose(self, value: Value) -> None:
        state: _Phase2 = self.state
        slot = self.next_slot
        self.next_slot += 1
        state.pending_values[slot] = value
        state.phase2bs[slot] = set()
        phase2a = Phase2a(slot=slot, round=self.round, value=value)
        for i in state.quorum_system.random_write_quorum(self.rng):
            self.send(self._acceptor(i), phase2a)

    def _handle_match_reply(self, src: Address, reply: MatchReply) -> None:
        if not isinstance(self.state, _Matchmaking) \
                or reply.round != self.round:
            return
        state = self.state
        state.match_replies[reply.matchmaker_index] = reply
        if len(state.match_replies) < self.config.f + 1:
            return
        previous: dict[int, QuorumSystem] = {}
        for r in state.match_replies.values():
            for round, qs_dict in r.configurations:
                previous[round] = quorum_system_from_dict(qs_dict)
        pending_rounds = set(previous)
        if not pending_rounds:
            self.state = _Phase2(state.quorum_system, {}, {})
            for request in state.pending_batches:
                self._propose(request.command)
            return
        # Phase 1 over a read quorum of every prior configuration.
        targets: set[int] = set()
        for qs in previous.values():
            targets |= qs.random_read_quorum(self.rng)
        phase1a = Phase1a(round=self.round,
                          chosen_watermark=self.chosen_watermark)
        for i in targets:
            self.send(self._acceptor(i), phase1a)
        self.state = _Phase1(state.quorum_system, previous, pending_rounds,
                             {}, state.pending_batches)

    def _handle_phase1b(self, src: Address, phase1b: Phase1b) -> None:
        if not isinstance(self.state, _Phase1) \
                or phase1b.round != self.round:
            return
        state = self.state
        state.phase1bs[phase1b.acceptor_index] = phase1b
        responders = set(state.phase1bs)
        for round in list(state.pending_rounds):
            if state.previous[round].is_superset_of_read_quorum(responders):
                state.pending_rounds.discard(round)
        if state.pending_rounds:
            return
        # Phase 1 done: matchmaker state below this round is prunable.
        for matchmaker in self.config.matchmaker_addresses:
            self.send(matchmaker, GarbageCollect(round=self.round))
        max_slot = max((i.slot for p in state.phase1bs.values()
                        for i in p.info), default=-1)
        phase2 = _Phase2(state.quorum_system, {}, {})
        pending = state.pending_batches
        self.state = phase2
        for slot in range(self.chosen_watermark, max_slot + 1):
            if self.log.get(slot) is not None:
                continue
            infos = [i for p in state.phase1bs.values() for i in p.info
                     if i.slot == slot]
            value = (max(infos, key=lambda i: i.vote_round).vote_value
                     if infos else NOOP)
            phase2.pending_values[slot] = value
            phase2.phase2bs[slot] = set()
            phase2a = Phase2a(slot=slot, round=self.round, value=value)
            for i in phase2.quorum_system.random_write_quorum(self.rng):
                self.send(self._acceptor(i), phase2a)
        self.next_slot = max(self.next_slot, max_slot + 1,
                             self.chosen_watermark)
        for request in pending:
            self._propose(request.command)

    def _handle_phase2b(self, src: Address, phase2b: Phase2b) -> None:
        if not isinstance(self.state, _Phase2) \
                or phase2b.round != self.round:
            return
        state = self.state
        voters = state.phase2bs.get(phase2b.slot)
        if voters is None:
            return
        voters.add(phase2b.acceptor_index)
        if not state.quorum_system.is_superset_of_write_quorum(voters):
            return
        value = state.pending_values.pop(phase2b.slot)
        del state.phase2bs[phase2b.slot]
        self._learn(phase2b.slot, value)
        for replica in self.config.replica_addresses:
            self.send(replica, Chosen(slot=phase2b.slot, value=value))
        for leader in self.config.leader_addresses:
            if leader != self.address:
                self.send(leader, Chosen(slot=phase2b.slot, value=value))

    def _learn(self, slot: int, value: Value) -> None:
        if self.log.get(slot) is None:
            self.log.put(slot, value)
        while self.log.get(self.chosen_watermark) is not None:
            self.chosen_watermark += 1
        self.next_slot = max(self.next_slot, self.chosen_watermark)

    def _handle_nack(self, nack_round: int) -> None:
        if nack_round <= self.round or self.state is None:
            return
        self._start_matchmaking()

    def _handle_reconfigure(self, src: Address,
                            reconfigure: Reconfigure) -> None:
        """Adopt a new acceptor configuration in our next round
        (the Reconfigurer's handoff)."""
        if self.state is None:
            return
        self.next_quorum_system = quorum_system_from_dict(
            reconfigure.quorum_system)
        self._start_matchmaking()


class MMPMatchmaker(Actor):
    """Stores per-round configurations; monotone; supports GC and Die
    (Matchmaker.scala:79-700)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerMultiPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.matchmaker_addresses).index(address)
        self.configurations: dict[int, dict] = {}
        self.gc_watermark = -1
        self.dead = False

    def receive(self, src: Address, message) -> None:
        if self.dead:
            return
        if isinstance(message, MatchRequest):
            if self.configurations \
                    and message.round <= max(self.configurations):
                self.send(src, MatchmakerNack(
                    round=max(self.configurations)))
                return
            self.send(src, MatchReply(
                round=message.round, matchmaker_index=self.index,
                configurations=tuple(
                    (r, self.configurations[r])
                    for r in sorted(self.configurations)
                    if r > self.gc_watermark)))
            self.configurations[message.round] = message.quorum_system
        elif isinstance(message, GarbageCollect):
            self.gc_watermark = max(self.gc_watermark, message.round - 1)
            for round in [r for r in self.configurations
                          if r <= self.gc_watermark]:
                del self.configurations[round]
        elif isinstance(message, Die):
            self.dead = True
        else:
            self.logger.fatal(f"unexpected matchmaker message {message!r}")


class MMPReconfigurer(Actor):
    """Drives acceptor-set changes (Reconfigurer.scala:98-500, condensed:
    the new configuration is handed to the leaders, which matchmake it
    into their next round)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerMultiPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config

    def reconfigure(self, quorum_system: QuorumSystem) -> None:
        message = Reconfigure(quorum_system_to_dict(quorum_system))
        for leader in self.config.leader_addresses:
            self.send(leader, message)

    def receive(self, src: Address, message) -> None:
        if isinstance(message, Reconfigure):
            for leader in self.config.leader_addresses:
                self.send(leader, message)
        else:
            self.logger.fatal(f"unexpected reconfigurer message {message!r}")


@dataclasses.dataclass
class _VoteState:
    vote_round: int
    vote_value: Value


class MMPAcceptor(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerMultiPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.acceptor_addresses).index(address)
        self.round = -1
        self.votes: dict[int, _VoteState] = {}

    def receive(self, src: Address, message) -> None:
        if isinstance(message, Phase1a):
            if message.round < self.round:
                self.send(src, AcceptorNack(round=self.round))
                return
            self.round = message.round
            info = tuple(
                Phase1bSlotInfo(slot=slot, vote_round=state.vote_round,
                                vote_value=state.vote_value)
                for slot, state in sorted(self.votes.items())
                if slot >= message.chosen_watermark)
            self.send(src, Phase1b(round=message.round,
                                   acceptor_index=self.index, info=info))
        elif isinstance(message, Phase2a):
            if message.round < self.round:
                self.send(src, AcceptorNack(round=self.round))
                return
            self.round = message.round
            self.votes[message.slot] = _VoteState(message.round,
                                                  message.value)
            self.send(src, Phase2b(slot=message.slot, round=message.round,
                                   acceptor_index=self.index))
        else:
            self.logger.fatal(f"unexpected acceptor message {message!r}")


class MMPReplica(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerMultiPaxosConfig,
                 state_machine: StateMachine):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.state_machine = state_machine
        self.index = list(config.replica_addresses).index(address)
        self.log: BufferMap = BufferMap()
        self.executed_watermark = 0
        self.client_table: dict[tuple, tuple[int, bytes]] = {}

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, Chosen):
            self.logger.fatal(f"unexpected replica message {message!r}")
        if self.log.get(message.slot) is None:
            self.log.put(message.slot, message.value)
        while True:
            value = self.log.get(self.executed_watermark)
            if value is None:
                return
            slot = self.executed_watermark
            self.executed_watermark += 1
            if isinstance(value, Noop):
                continue
            cid = value.command_id
            key = (cid.client_address, cid.client_pseudonym)
            cached = self.client_table.get(key)
            if cached is not None and cid.client_id < cached[0]:
                continue
            if cached is not None and cid.client_id == cached[0]:
                result = cached[1]
            else:
                result = self.state_machine.run(value.command)
                self.client_table[key] = (cid.client_id, result)
            if slot % len(self.config.replica_addresses) == self.index:
                self.send(cid.client_address,
                          ClientReply(command_id=cid, result=result))


@dataclasses.dataclass
class _Pending:
    id: int
    command: bytes
    callback: Callable[[bytes], None]
    resend: object


class MMPClient(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerMultiPaxosConfig,
                 resend_period_s: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.ids: dict[int, int] = {}
        self.pending: dict[int, _Pending] = {}

    def write(self, pseudonym: int, command: bytes,
              callback: Optional[Callable[[bytes], None]] = None) -> None:
        if pseudonym in self.pending:
            raise RuntimeError(f"pseudonym {pseudonym} has a pending op")
        id = self.ids.get(pseudonym, 0)
        request = ClientRequest(Command(
            CommandId(self.address, pseudonym, id), command))

        def send_it():
            for leader in self.config.leader_addresses:
                self.send(leader, request)

        def resend():
            send_it()
            timer.start()

        send_it()
        timer = self.timer(f"resend-{pseudonym}", self.resend_period_s,
                           resend)
        timer.start()
        self.pending[pseudonym] = _Pending(id, command,
                                           callback or (lambda _: None),
                                           timer)
        self.ids[pseudonym] = id + 1

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ClientReply):
            self.logger.fatal(f"unexpected client message {message!r}")
        pending = self.pending.get(message.command_id.client_pseudonym)
        if pending is None or pending.id != message.command_id.client_id:
            return
        pending.resend.stop()
        del self.pending[message.command_id.client_pseudonym]
        pending.callback(message.result)
